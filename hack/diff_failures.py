#!/usr/bin/env python3
"""Diff the FAILED sets of two pytest logs (the tier-1 workflow gate).

Every PR since the accelerator drift has hand-rolled this comparison:
run tier-1 on a stashed HEAD, run it on the working tree, and prove
the failure set did not GROW (pre-existing failures are tolerated;
new ones are regressions).  This tool is that ritual, scripted:

    # baseline (stash or clean checkout)
    pytest tests/ -q ... | tee /tmp/base.log
    # candidate (working tree)
    pytest tests/ -q ... | tee /tmp/head.log
    python hack/diff_failures.py /tmp/base.log /tmp/head.log

Parses ``FAILED <nodeid>`` / ``ERROR <nodeid>`` lines (the -q summary
format; trailing ``- <message>`` stripped), prints the added and
removed ids, and exits:

    0  no newly-failing tests (fixes alone are fine)
    1  at least one test fails in the candidate log but not the base
    2  usage / unreadable or unparsable input

A log with zero FAILED lines is legal (a fully green run); a log that
does not look like pytest output at all (no summary markers) is
refused rather than silently treated as green.

Documented in docs/operations.md ("Tier-1 workflow").
"""
from __future__ import annotations

import re
import sys
from pathlib import Path
from typing import Set, Tuple

# the id is everything up to the " - <message>" separator, NOT \S+:
# parametrized ids routinely contain spaces ("test[foo 1]") and a
# \S+ cut would collapse distinct params into one id, letting a new
# regression hide behind a pre-existing sibling
_ID_LINE = re.compile(r"^(FAILED|ERROR)\s+(.+?)(?:\s+-\s+.*)?$")
# evidence the file is a pytest log at all: the final summary line or
# the short-test-summary header (either survives tee/truncation)
_PYTEST_MARKERS = re.compile(
    r"(=+ short test summary info =+"
    r"|\d+ (?:passed|failed|error|deselected|skipped)"
    r"|no tests ran)")


def parse_failures(path: Path) -> Tuple[Set[str], Set[str]]:
    """(failed ids, errored ids) from a pytest log."""
    try:
        text = path.read_text(errors="replace")
    except OSError as exc:
        print(f"diff_failures: cannot read {path}: {exc}",
              file=sys.stderr)
        raise SystemExit(2)
    if not _PYTEST_MARKERS.search(text):
        print(f"diff_failures: {path} does not look like a pytest "
              f"log (no summary markers) — refusing to treat it as a "
              f"green run", file=sys.stderr)
        raise SystemExit(2)
    # scope to the short-test-summary section when present: captured
    # live-log output at ERROR level ("ERROR <logger>:<file>:<line>
    # <msg>") matches the FAILED|ERROR shape, and the embedded source
    # line number shifts whenever the module above it gains a line —
    # every such noise line then diffs as a "new error"
    lines = text.splitlines()
    for i in range(len(lines) - 1, -1, -1):
        if "short test summary info" in lines[i]:
            lines = lines[i + 1:]
            break
    failed: Set[str] = set()
    errored: Set[str] = set()
    for line in lines:
        m = _ID_LINE.match(line.strip())
        if not m:
            continue
        kind, nodeid = m.groups()
        if re.search(r"\s", nodeid):
            # node ids (tests/x.py::t, or a bare file for collection
            # errors) never contain whitespace; a multi-word "id" is a
            # log-noise line that slipped past the section scoping
            continue
        # "FAILED tests/x.py::t - AssertionError: ..." -> the id alone
        (failed if kind == "FAILED" else errored).add(nodeid)
    return failed, errored


def main(argv) -> int:
    args = [a for a in argv[1:] if not a.startswith("-")]
    if "--help" in argv or "-h" in argv or len(args) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    base_path, head_path = Path(args[0]), Path(args[1])
    base_failed, base_err = parse_failures(base_path)
    head_failed, head_err = parse_failures(head_path)
    base_all = base_failed | base_err
    head_all = head_failed | head_err

    added = sorted(head_all - base_all)
    removed = sorted(base_all - head_all)

    print(f"base: {len(base_failed)} failed + {len(base_err)} errors "
          f"({base_path})")
    print(f"head: {len(head_failed)} failed + {len(head_err)} errors "
          f"({head_path})")
    if removed:
        print(f"\nfixed ({len(removed)}):")
        for nodeid in removed:
            print(f"  - {nodeid}")
    if added:
        print(f"\nNEWLY FAILING ({len(added)}) — regressions:")
        for nodeid in added:
            print(f"  + {nodeid}")
        return 1
    print("\nno newly-failing tests")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
