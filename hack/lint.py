#!/usr/bin/env python3
"""Stdlib linter: pyflakes-class checks without pyflakes (VERDICT r2
item 7 — no linter package is installable in this environment, so the
gate is built on ``ast`` alone).

Checks (high-precision by design — the gate tolerates zero findings,
so every rule over-approximates "used" rather than ever flagging
legitimate code):

  L001 unused import        (module scope; loads counted anywhere in
                             the module, incl. string annotations and
                             ``__all__``)
  L002 unused local         (single-name assignment in a function,
                             never loaded anywhere in that function's
                             subtree; tuple unpacking exempt, matching
                             pyflakes' default)
  L003 bare except          (``except:`` swallows KeyboardInterrupt)
  L004 mutable default arg  (list/dict/set displays or bare
                             constructors)
  L005 f-string without placeholders (format-spec f-strings exempt)
  L006 redefined name       (decorator-less def/class defined twice in
                             one scope — property pairs stay legal)
  L007 useless noqa         (a ``# noqa: <code>`` naming a code this
                             suite knows — L00x or a pyflakes-era alias
                             — on a line where that rule does not fire;
                             codes the suite does not implement, e.g.
                             E402/E501, are left alone)

Suppress a line with ``# noqa`` or ``# noqa: L00X``.

The concurrency contract rules (L101-L120, see
aws_global_accelerator_controller_tpu/analysis/concurrency_lint.py) run
with ``--concurrency`` (only them) or ``--all`` (both passes — what
``make lint`` runs).  ``tests/lint_fixtures/`` holds deliberately
violating rule fixtures and is excluded from tree runs.

Usage: python hack/lint.py [--concurrency|--all] [paths...]
Exit 0 clean, 1 findings, 2 crashed-on-file.
"""
from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DEFAULT_PATHS = [
    "aws_global_accelerator_controller_tpu", "tests", "hack",
    "bench.py", "__graft_entry__.py",
]
_IDENT = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
_BUILTIN_MUTABLES = {"list", "dict", "set", "bytearray", "defaultdict",
                     "deque", "Counter", "OrderedDict"}
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)
_SCOPES = _FUNCS + (ast.Lambda,)


def _noqa_lines(source: str) -> dict:
    """line number -> set of codes suppressed ('' means all)."""
    out = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = re.search(r"#\s*noqa(?::\s*([A-Z0-9, ]+))?", line)
        if m:
            codes = m.group(1)
            out[i] = ({c.strip() for c in codes.split(",")}
                      if codes else {""})
    return out


# the tree predates this linter and carries pyflakes-style noqa codes;
# honor both spellings
_CODE_ALIASES = {"L001": {"L001", "F401"}, "L002": {"L002", "F841"},
                 "L003": {"L003", "E722", "BLE001"},
                 "L005": {"L005", "F541"}}


def _suppressed(noqa, line, code) -> bool:
    codes = noqa.get(line)
    if codes is None:
        return False
    accepted = _CODE_ALIASES.get(code, {code})
    return "" in codes or bool(codes & accepted)


class _Finding:
    def __init__(self, path, line, code, msg):
        self.path, self.line, self.code, self.msg = path, line, code, msg

    def __str__(self):
        return f"{self.path}:{self.line}: {self.code} {self.msg}"


def _collect_names(node, used: set) -> None:
    """Name-use harvesting for ONE node (no recursion), over-
    approximated: Load/Del contexts, `x += y` reads, global/nonlocal
    declarations, and identifiers inside ALL string constants (quoted
    forward-ref annotations, __all__ entries, getattr strings) — a
    string mention is treated as a use so the gate never flags a
    legitimate indirect reference."""
    if isinstance(node, ast.Name) \
            and isinstance(node.ctx, (ast.Load, ast.Del)):
        used.add(node.id)
    elif isinstance(node, ast.AugAssign) \
            and isinstance(node.target, ast.Name):
        # `x += y` reads x at runtime even though the target Name
        # carries Store ctx
        used.add(node.target.id)
    elif isinstance(node, (ast.Global, ast.Nonlocal)):
        used.update(node.names)
    elif isinstance(node, ast.Constant) \
            and isinstance(node.value, str) and len(node.value) < 4096:
        used.update(_IDENT.findall(node.value))
    elif isinstance(node, ast.ExceptHandler) and node.name:
        used.add(node.name)   # binding, but keeps rule L002 scoped


def _scan_scopes(scope, path, findings, is_function) -> set:
    """One bottom-up traversal shared by L001 and L002: returns the
    used-name set of `scope`'s whole subtree, merging child function
    and class scopes' sets upward instead of re-walking each nested
    subtree per enclosing function (the old per-function
    `ast.walk` + exclusion-set shape was quadratic in nesting depth).
    At each function scope the candidate single-name assignments are
    checked against the subtree set — assignments inside a nested
    ClassDef are class ATTRIBUTES (read via attribute access, not name
    loads) and assignments inside a nested function belong to THAT
    function's check, so both recurse as their own scope.  The
    module-level return value is exactly the old whole-tree
    `_loads_and_strings`, which L001 reuses for free."""
    used: set = set()
    candidates: list = []

    def descend(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNCS + (ast.ClassDef,)):
                used.update(_scan_scopes(child, path, findings,
                                         isinstance(child, _FUNCS)))
                continue
            _collect_names(child, used)
            if is_function and isinstance(child, ast.Assign) \
                    and len(child.targets) == 1:
                candidates.append(child)
            descend(child)

    descend(scope)
    for node in candidates:
        tgt = node.targets[0]
        # single plain names only: tuple unpacking, attribute and
        # subscript targets are exempt (pyflakes' F841 default)
        if not isinstance(tgt, ast.Name) or tgt.id.startswith("_"):
            continue
        if tgt.id in used:
            continue
        findings.append(_Finding(
            path, node.lineno, "L002",
            f"local variable '{tgt.id}' assigned but never used"))
    return used


def _unused_imports(nodes, path, findings, is_init, used):
    if is_init:
        # __init__.py imports are the package's public re-export
        # surface; "unused" is their job
        return
    for node in nodes:
        names = []
        if isinstance(node, ast.Import):
            names = [(a.asname or a.name.split(".")[0], a.name)
                     for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            names = [(a.asname or a.name, a.name)
                     for a in node.names if a.name != "*"]
        if isinstance(node, ast.ImportFrom) \
                and node.module == "__future__":
            continue
        for binding, target in names:
            if binding in used or binding.startswith("_"):
                continue
            if node.col_offset > 0:
                # function-local imports get a pass: they exist for
                # import-cycle/lazy-init reasons and the subtree scan
                # above already counted module-wide loads
                continue
            findings.append(_Finding(
                path, node.lineno, "L001",
                f"'{target}' imported but unused"))


def _format_spec_ids(nodes) -> set:
    """id()s of JoinedStr nodes that are f-string format specs — the
    '{x:>8}' spec parses as its own JoinedStr and must not be linted
    as a placeholder-less f-string."""
    specs: set = set()
    for node in nodes:
        if isinstance(node, ast.FormattedValue) \
                and node.format_spec is not None:
            specs.add(id(node.format_spec))
    return specs


def _ast_findings(nodes, path, findings):
    specs = _format_spec_ids(nodes)
    for node in nodes:
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(_Finding(
                path, node.lineno, "L003",
                "bare 'except:' (catches SystemExit/"
                "KeyboardInterrupt; use 'except Exception:')"))
        elif isinstance(node, _SCOPES):
            for default in (node.args.defaults
                            + [d for d in node.args.kw_defaults if d]):
                bad = (isinstance(default, (ast.List, ast.Dict, ast.Set))
                       or (isinstance(default, ast.Call)
                           and isinstance(default.func, ast.Name)
                           and default.func.id in _BUILTIN_MUTABLES
                           and not default.args
                           and not default.keywords))
                if bad:
                    name = getattr(node, "name", "<lambda>")
                    findings.append(_Finding(
                        path, default.lineno, "L004",
                        f"mutable default argument in '{name}()'"))
        elif isinstance(node, ast.JoinedStr) and id(node) not in specs:
            if not any(isinstance(v, ast.FormattedValue)
                       for v in node.values):
                findings.append(_Finding(
                    path, node.lineno, "L005",
                    "f-string without placeholders"))
        if isinstance(node, (ast.Module, ast.ClassDef) + _FUNCS):
            seen: dict = {}
            for stmt in getattr(node, "body", []):
                if isinstance(stmt, _FUNCS + (ast.ClassDef,)) \
                        and not stmt.decorator_list:
                    if stmt.name in seen:
                        findings.append(_Finding(
                            path, stmt.lineno, "L006",
                            f"'{stmt.name}' redefined (first defined "
                            f"line {seen[stmt.name]})"))
                    seen.setdefault(stmt.name, stmt.lineno)


# code -> the rule it suppresses (the L007 probe direction)
_REVERSE_ALIASES: dict = {}
for _rule, _codes in _CODE_ALIASES.items():
    for _c in _codes:
        _REVERSE_ALIASES[_c] = _rule
for _rule in ("L001", "L002", "L003", "L004", "L005", "L006"):
    _REVERSE_ALIASES.setdefault(_rule, _rule)


def _string_noqa_lines(tree) -> set:
    """Lines where a '# noqa' match is (or may be) inside a string
    constant — docstrings quoting noqa syntax, lint-test fixture
    snippets.  L007 must not demand deletion of text that is data."""
    lines: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            end = getattr(node, "end_lineno", node.lineno)
            if end > node.lineno:
                lines.update(range(node.lineno, end + 1))
            elif "noqa" in node.value:
                lines.add(node.lineno)
    return lines


def _useless_noqa(path, noqa, raw, string_lines) -> list:
    """L007: every EXPLICIT noqa code this suite implements must still
    be earning its keep — a ``# noqa: F401`` on a line rule L001 no
    longer fires on is stale pyflakes-era residue that would silently
    mask a future real finding.  Blanket ``# noqa`` and codes of
    linters this suite does not implement (E402, E501, ...) are left
    alone."""
    fired = {(f.line, f.code) for f in raw}
    out = []
    for line, codes in sorted(noqa.items()):
        if "" in codes or line in string_lines:
            continue
        for code in sorted(codes):
            rule = _REVERSE_ALIASES.get(code)
            if rule is None or (line, rule) in fired:
                continue
            out.append(_Finding(
                path, line, "L007",
                f"useless noqa: rule {rule} ('{code}') does not fire "
                f"on this line — delete the suppression"))
    return out


def lint_file(path: Path) -> list:
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return [_Finding(path, e.lineno or 0, "L000",
                         f"syntax error: {e.msg}")]
    return lint_tree(path, source, tree)


def lint_tree(path: Path, source: str, tree) -> list:
    """Base rules over an already-parsed module — `--all` parses each
    file once and shares the tree with the concurrency engine."""
    noqa = _noqa_lines(source)
    raw: list = []
    # one scope pass emits L002 AND yields the module-wide used-name
    # set L001 needs — the tree is traversed twice total (here and in
    # _ast_findings), not once per rule per function
    used = _scan_scopes(tree, path, raw, is_function=False)
    nodes = list(ast.walk(tree))   # one walk, shared by L001/L003-L006
    _unused_imports(nodes, path, raw,
                    is_init=path.name == "__init__.py", used=used)
    _ast_findings(nodes, path, raw)
    findings = [f for f in raw
                if not _suppressed(noqa, f.line, f.code)]
    findings.extend(
        f for f in _useless_noqa(path, noqa, raw,
                                 _string_noqa_lines(tree))
        if not _suppressed(noqa, f.line, "L007"))
    return findings


def _concurrency_engine():
    # the engine lives inside the package so the runtime detectors and
    # tests share it; keep hack/ import-light by pathing to the repo
    sys.path.insert(0, str(REPO))
    from aws_global_accelerator_controller_tpu.analysis import (
        concurrency_lint,
    )
    return concurrency_lint.Engine()


def main(argv) -> int:
    args = list(argv[1:])
    concurrency_only = "--concurrency" in args
    run_all = "--all" in args
    unknown = [a for a in args if a.startswith("--")
               and a not in ("--concurrency", "--all")]
    if unknown:
        # a typo'd flag silently running only the base pass would
        # green-light unchecked code (same failure class as the
        # mistyped-path guard below)
        print(f"lint: unknown option(s): {' '.join(unknown)}",
              file=sys.stderr)
        return 2
    paths = [a for a in args if not a.startswith("--")] \
        or [str(REPO / p) for p in DEFAULT_PATHS]
    files: list = []
    for p in paths:
        pth = Path(p)
        if pth.is_dir():
            files.extend(sorted(pth.rglob("*.py")))
        elif pth.is_file() and pth.suffix == ".py":
            files.append(pth)
        else:
            # a mistyped CI path silently linting nothing would
            # green-light unlinted code
            print(f"lint: no such file or directory: {p}",
                  file=sys.stderr)
            return 2
    # __pycache__ is noise; lint_fixtures are DELIBERATE violations
    # (the rule test corpus, tests/test_lint.py)
    files = [f for f in files
             if "__pycache__" not in f.parts
             and "lint_fixtures" not in f.parts]
    findings: list = []
    engine = None
    if concurrency_only or run_all:
        try:
            engine = _concurrency_engine()
        except Exception as exc:
            print(f"concurrency lint crashed: {exc!r}", file=sys.stderr)
            return 2
    # one parse per file: the base pass and the concurrency engine
    # share the tree (Engine.add_file accepts a pre-parsed module)
    for f in files:
        source = f.read_text()
        try:
            tree = ast.parse(source, filename=str(f))
        except SyntaxError as e:
            if not concurrency_only:
                findings.append(_Finding(f, e.lineno or 0, "L000",
                                         f"syntax error: {e.msg}"))
            if engine is not None:
                # engine re-parses only this broken file, for its L100
                engine.add_file(f, source)
            continue
        if not concurrency_only:
            try:
                findings.extend(lint_tree(f, source, tree))
            except Exception as exc:
                print(f"{f}: linter crashed: {exc!r}", file=sys.stderr)
                return 2
        if engine is not None:
            engine.add_file(f, source, tree)
    if engine is not None:
        try:
            findings.extend(sorted(
                engine.run(),
                key=lambda x: (str(x.path), x.line, x.code)))
        except Exception as exc:
            print(f"concurrency lint crashed: {exc!r}", file=sys.stderr)
            return 2
    for finding in sorted(findings, key=lambda x: (str(x.path), x.line)):
        print(finding)
    print(f"lint: {len(files)} files, {len(findings)} findings",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
