#!/usr/bin/env bash
# Analogue of the reference's hack/kind-with-registry.sh: instead of a kind
# cluster + local registry, spin up the in-process fake API server + fake AWS
# cloud, seed a demo fleet (annotated NLB Service + hosted zone), and run the
# controller until the accelerator chain and DNS records converge.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m aws_global_accelerator_controller_tpu -v 4 controller \
  --fake --demo --cluster-name demo "$@"
