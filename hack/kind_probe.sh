#!/usr/bin/env bash
# Probe whether the kind e2e (hack/kind-e2e.sh, mirroring the
# reference's CI workflow) can execute in this environment, and record
# the evidence in a committed artifact (VERDICT r4 next #6: the
# partial webhook-e2e row must carry proof of impossibility, not
# silence).  Usage: hack/kind_probe.sh [out-file]
set -u
OUT="${1:-bench_artifacts/kind_probe_r5.txt}"
cd "$(dirname "$0")/.."

{
    echo "# kind e2e environment probe"
    echo "date: $(date -u +%FT%TZ)"
    echo "tree: $(git rev-parse --short HEAD 2>/dev/null)$(git status --porcelain -uno 2>/dev/null | grep -q . && echo '+dirty')"
    echo
    for tool in kind kubectl docker podman; do
        if command -v "$tool" >/dev/null 2>&1; then
            echo "$tool: $(command -v "$tool") ($("$tool" --version 2>&1 | head -1))"
        else
            echo "$tool: ABSENT"
        fi
    done
    echo
    echo "# network egress (kind needs to pull node images)"
    if command -v getent >/dev/null 2>&1; then
        if timeout 5 getent hosts registry.k8s.io >/dev/null 2>&1; then
            echo "dns registry.k8s.io: resolves"
        else
            echo "dns registry.k8s.io: FAILS (no egress)"
        fi
    else
        echo "getent: ABSENT"
    fi
    # a raw TCP attempt, independent of DNS
    if timeout 5 bash -c 'exec 3<>/dev/tcp/1.1.1.1/443' 2>/dev/null; then
        echo "tcp 1.1.1.1:443: connects"
    else
        echo "tcp 1.1.1.1:443: FAILS (no egress)"
    fi
    echo
    echo "# verdict"
    if command -v kind >/dev/null 2>&1 && command -v kubectl >/dev/null 2>&1; then
        echo "kind+kubectl present: hack/kind-e2e.sh is runnable; run it."
    else
        echo "kind e2e NOT runnable here: container tooling absent (and"
        echo "no egress to install it).  The suite's 19+ golden real-"
        echo "apiserver wire fixtures + kube/rest_server.py stub remain"
        echo "the strongest available evidence; .github/workflows/"
        echo "kind-e2e.yml runs the real thing where CI exists."
    fi
} | tee "$OUT"
