#!/usr/bin/env python3
"""Catalog-driven contract-mutation probes (``make probes``).

Every concurrency contract L101-L120 ships with at least one PROBE: a
strip-the-contract mutation applied to the REAL tree in memory — drop
a lock, remove a fence consult, sever a trace context, delete a guard
declaration — after which the matching rule MUST fire.  "The lint
fired once when we wrote it" becomes a CI-enforced property of every
contract (FoundationdB-style: mutate the invariant to prove the
checker is alive).  A probe that stops firing means the rule or the
shipped code shape silently changed; a needle that stops matching
means the anchor moved — both fail loudly here instead of rotting.

Each catalog entry names the rule, the shipped file it mutates, and a
transform over the file's source.  The engine writes the mutated file
to a temp dir that MIRRORS the package-relative path (scope-sensitive
rules key off ``aws_global_accelerator_controller_tpu`` in the path),
lints it with the full concurrency engine, and asserts (a) the
expected rule fires on the mutant and (b) the UNMUTATED file is clean
under that rule (so the probe proves the mutation fired it, not a
pre-existing finding).

tests/test_lint.py runs the same catalog via ``probe.run_all`` and a
meta-test asserts every documented rule L101-L120 is covered here.

Usage: python hack/probe.py [--list] [name ...]
Exit 0 all probes fired, 1 any failed/skipped-on-shape-drift.
"""
from __future__ import annotations

import argparse
import sys
import tempfile
import time
from pathlib import Path
from typing import Callable, List, NamedTuple, Optional

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from aws_global_accelerator_controller_tpu.analysis import (  # noqa: E402
    concurrency_lint,
)

PKG = "aws_global_accelerator_controller_tpu"


class ShapeDrift(AssertionError):
    """The shipped code no longer contains the probe's anchor."""


class Probe(NamedTuple):
    name: str            # unique, kebab-case
    rule: str            # the code that must fire on the mutant
    path: str            # repo-relative shipped file to mutate
    mutate: Callable[[str], str]
    # substring the firing finding's message must contain (None = any
    # finding of the rule counts)
    msg_needle: Optional[str] = None


def _replace(src: str, needle: str, repl: str, probe: str) -> str:
    if needle not in src:
        raise ShapeDrift(
            f"{probe}: anchor not found — shipped shape changed, "
            f"update the probe (needle: {needle[:60]!r})")
    return src.replace(needle, repl, 1)


def _insert_after(src: str, needle: str, insertion: str,
                  probe: str) -> str:
    return _replace(src, needle, needle + insertion, probe)


def _append(src: str, block: str) -> str:
    return src.rstrip("\n") + "\n\n\n" + block.lstrip("\n")


# -- mutations --------------------------------------------------------


def _m_l101(src):
    return _append(src, '''
import threading as _probe_threading


class _ProbeInversion:
    def __init__(self):
        self.a_lock = _probe_threading.Lock()
        self.b_lock = _probe_threading.Lock()

    def one(self):
        with self.a_lock:
            with self.b_lock:
                pass

    def two(self):
        with self.b_lock:
            with self.a_lock:
                pass
''')


def _m_l102(src):
    return _replace(
        src,
        "        with self._lock:\n            self._managed = True\n",
        "        with self._lock:\n"
        "            time.sleep(0.25)\n"
        "            self._managed = True\n",
        "blocking-call-under-lock")


def _m_l103(src):
    return _append(src, '''
def _probe_touch(informer, ns, name):
    svc = informer.lister.get(ns, name)
    svc.metadata.annotations["touched"] = "true"
    return svc
''')


def _m_l104(src):
    start = src.find("def _update_accelerator")
    end = src.find("def get_listener")
    if start < 0 or end < 0 or start > end:
        raise ShapeDrift("lock-strip-update-accelerator: "
                         "_update_accelerator shape changed")
    body = src[start:end]
    if body.count("with self._s.lock:") != 1:
        raise ShapeDrift("lock-strip-update-accelerator: "
                         "lock block count changed")
    return src[:start] \
        + body.replace("with self._s.lock:", "if True:") + src[end:]


def _m_l105(src):
    return _append(src, '''
def _probe_peek(cloud, arn):
    return cloud.ga.describe_accelerator(arn)
''')


def _m_l106(src):
    return _append(src, '''
def _probe_flush(apis, zone_id, record_set):
    apis.route53.change_resource_record_sets(
        zone_id, "UPSERT", record_set)
''')


def _m_l107(src):
    return _insert_after(
        src,
        "    ports, protocol = listener_for_service(svc)\n",
        "    svc.apis.ga.describe_accelerator(svc.key())\n",
        "apis-in-fingerprint")


def _m_l108(src):
    return _replace(
        src,
        "                if op in MUTATION_METHODS:\n"
        "                    if self.fence is not None:\n"
        "                        self.fence.check(\"wrapper\")\n"
        "                    for extra_fence in active_write_fences():\n"
        "                        extra_fence.check(\"wrapper\")\n",
        "                pass\n",
        "fence-strip-wrapper")


def _m_l109(src):
    return _replace(
        src,
        "    queue.add_rate_limited(key, klass=CLASS_INTERACTIVE,"
        " ctx=ctx)",
        "    queue.add_rate_limited(key, ctx=ctx)",
        "classless-enqueue")


def _m_l110(src):
    return _replace(
        src,
        '        sid = self._shards.check(container_key, '
        'surface="coalescer")\n',
        "        sid = 0\n",
        "shard-check-strip")


def _m_l111(src):
    return _replace(
        src,
        "        compiler_params=CompilerParams(\n",
        "        compiler_params=pltpu.CompilerParams(\n",
        "bare-pltpu-graft")


def _m_l112_egb(src):
    out = _replace(src,
                   "        outcome = self.rollout.decide(\n",
                   "        outcome = _Passthrough(\n",
                   "rollout-strip-egb")
    return _replace(out, "not self._rollout_declared(obj)", "True",
                    "rollout-strip-egb")


def _m_l112_r53(src):
    return _replace(
        src,
        "        policy, ramp_weights, ramp_requeue = "
        "self._record_rollout(\n"
        "            svc, \"service\", hostnames, "
        "self.kube_client.services)\n",
        "        policy, ramp_weights, ramp_requeue = "
        "None, None, 0.0\n",
        "rollout-strip-route53")


def _m_l113_loop(src):
    return _replace(
        src,
        "    s = score_rows(params, rows)",
        "    for _row in rows:\n        pass\n"
        "    s = score_rows(params, rows)",
        "device-loop-graft")


def _m_l113_apis(src):
    return _insert_after(
        src,
        "    table = InternTable()\n",
        "    apis.ga.describe_endpoint_group(groups[0])\n",
        "apis-in-packing")


def _m_l114_ctx(src):
    return _replace(
        src,
        "    queue.add_rate_limited(key, klass=CLASS_INTERACTIVE,"
        " ctx=ctx)",
        "    queue.add_rate_limited(key, klass=CLASS_INTERACTIVE)",
        "ctx-strip-enqueue")


def _m_l114_ambient(src):
    return _replace(src,
                    "        ctx = ambient_context()\n",
                    "        ctx = None\n",
                    "ambient-capture-strip")


def _m_l115(src):
    return _replace(
        src,
        "                self._resync_due(spread)\n",
        "                import time\n"
        "                time.sleep(0.001)\n"
        "                self._resync_due(spread)\n",
        "bare-sleep-informer")


def _m_l116(src):
    return _replace(
        src,
        "        if self._aggregator is not None:\n"
        "            self._aggregator.submit_record_sets(\n"
        "                zone_id, changes, fence=self._fence, "
        "ctxs=ctxs,\n"
        "                shard_id=self._shard_id)\n"
        "            return\n",
        "",
        "aggregator-handoff-strip")


def _m_l117(src):
    return _replace(src,
                    "    linger: float = knobcat.COALESCER_LINGER\n",
                    "    linger: float = 0.005\n",
                    "literal-linger")


def _m_l118(src):
    return _replace(
        src,
        "                wave = planner.plan_wave()\n",
        "                packed = pack_fleet(\n"
        "                    fleet.snapshot_groups())\n"
        "                wave = planner.plan_wave()\n",
        "wave-repack-graft")


def _m_l119(src):
    return _replace(
        src,
        "        with self._lock:\n            self._managed = True\n",
        "        if True:\n            self._managed = True\n",
        "guard-strip-shardset")


def _m_l120(src):
    return _replace(
        src,
        "  # guarded-by: self._cache_lock\n"
        "        self._ns_snapshots",
        "\n        self._ns_snapshots",
        "declaration-strip-informer")


PROBES: List[Probe] = [
    Probe("inverted-lock-pair", "L101",
          f"{PKG}/sharding/shardset.py", _m_l101),
    Probe("blocking-call-under-lock", "L102",
          f"{PKG}/sharding/shardset.py", _m_l102),
    Probe("lister-view-mutation", "L103",
          f"{PKG}/controller/globalaccelerator.py", _m_l103),
    Probe("lock-strip-update-accelerator", "L104",
          f"{PKG}/cloudprovider/aws/provider.py", _m_l104),
    Probe("bare-service-call", "L105",
          f"{PKG}/controller/globalaccelerator.py", _m_l105),
    Probe("uncoalesced-mutation", "L106",
          f"{PKG}/controller/globalaccelerator.py", _m_l106),
    Probe("apis-in-fingerprint", "L107",
          f"{PKG}/controller/globalaccelerator.py", _m_l107),
    Probe("fence-strip-wrapper", "L108",
          f"{PKG}/resilience/wrapper.py", _m_l108),
    Probe("classless-enqueue", "L109",
          f"{PKG}/controller/base.py", _m_l109),
    Probe("shard-check-strip", "L110",
          f"{PKG}/cloudprovider/aws/batcher.py", _m_l110),
    Probe("bare-pltpu-graft", "L111",
          f"{PKG}/ops/pallas_attention.py", _m_l111),
    Probe("rollout-strip-egb", "L112",
          f"{PKG}/controller/endpointgroupbinding.py", _m_l112_egb),
    Probe("rollout-strip-route53", "L112",
          f"{PKG}/controller/route53.py", _m_l112_r53,
          msg_needle="process_service_create_or_update"),
    Probe("device-loop-graft", "L113",
          f"{PKG}/parallel/fleet_plan.py", _m_l113_loop,
          msg_needle="loop"),
    Probe("apis-in-packing", "L113",
          f"{PKG}/reconcile/columnar.py", _m_l113_apis,
          msg_needle="provider call"),
    Probe("ctx-strip-enqueue", "L114",
          f"{PKG}/controller/base.py", _m_l114_ctx),
    Probe("ambient-capture-strip", "L114",
          f"{PKG}/cloudprovider/aws/batcher.py", _m_l114_ambient),
    Probe("bare-sleep-informer", "L115",
          f"{PKG}/kube/informers.py", _m_l115,
          msg_needle="time.sleep"),
    Probe("aggregator-handoff-strip", "L116",
          f"{PKG}/cloudprovider/aws/batcher.py", _m_l116),
    Probe("literal-linger", "L117",
          f"{PKG}/cloudprovider/aws/batcher.py", _m_l117),
    Probe("wave-repack-graft", "L118",
          f"{PKG}/controller/fleetsweep.py", _m_l118),
    Probe("guard-strip-shardset", "L119",
          f"{PKG}/sharding/shardset.py", _m_l119),
    Probe("declaration-strip-informer", "L120",
          f"{PKG}/kube/informers.py", _m_l120),
]


class ProbeResult(NamedTuple):
    probe: Probe
    ok: bool
    detail: str


# baseline-clean results cached per (path, rule) across the catalog run
_BASELINE_CACHE: dict = {}


def run_probe(probe: Probe, tmp_root: Path) -> ProbeResult:
    real = REPO / probe.path
    src = real.read_text()

    # baseline: the unmutated file must be clean under the probe's
    # rule, else "it fired" proves nothing (cached per path+rule)
    bkey = (probe.path, probe.rule)
    if bkey not in _BASELINE_CACHE:
        _BASELINE_CACHE[bkey] = [
            f for f in concurrency_lint.lint_files([real])
            if f.code == probe.rule]
    if _BASELINE_CACHE[bkey]:
        return ProbeResult(probe, False,
                           f"baseline not clean: {_BASELINE_CACHE[bkey][0]}")

    try:
        mutated = probe.mutate(src)
    except ShapeDrift as e:
        return ProbeResult(probe, False, str(e))
    if mutated == src:
        return ProbeResult(probe, False, "mutation was a no-op")

    dst = tmp_root / probe.name / probe.path
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_text(mutated)
    fired = [f for f in concurrency_lint.lint_files([dst])
             if f.code == probe.rule
             and (probe.msg_needle is None
                  or probe.msg_needle in f.msg)]
    if not fired:
        return ProbeResult(probe, False,
                           f"{probe.rule} did not fire on the mutant")
    return ProbeResult(probe, True,
                       f"{probe.rule} fired at line {fired[0].line}")


def run_all(names=None) -> List[ProbeResult]:
    selected = [p for p in PROBES
                if not names or p.name in names or p.rule in names]
    results = []
    with tempfile.TemporaryDirectory(prefix="agac-probes-") as tmp:
        for probe in selected:
            results.append(run_probe(probe, Path(tmp)))
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("names", nargs="*",
                    help="probe names or rule codes (default: all)")
    ap.add_argument("--list", action="store_true")
    args = ap.parse_args(argv)

    if args.list:
        for p in PROBES:
            print(f"{p.rule}  {p.name:32s} {p.path}")
        return 0

    t0 = time.monotonic()
    results = run_all(args.names)
    failed = [r for r in results if not r.ok]
    for r in results:
        mark = "ok  " if r.ok else "FAIL"
        print(f"{mark} {r.probe.rule} {r.probe.name:32s} {r.detail}")
    rules = sorted({p.rule for p in PROBES})
    print(f"probes: {len(results)} run, {len(failed)} failed, "
          f"{len(rules)} rules ({rules[0]}-{rules[-1]}), "
          f"{time.monotonic() - t0:.1f}s")
    return 1 if failed or not results else 0


if __name__ == "__main__":
    raise SystemExit(main())
