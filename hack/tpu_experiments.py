#!/usr/bin/env python3
"""On-chip experiments for the fused one-sweep flash backward's gates.

The round-4 fused backward (`ops/pallas_attention._dqkv_kernel`) is
gated to Tp*D*4 <= 2 MB and H <= 32 because the temporal shape
(S=128 streams-as-heads under a scan loop) hit Mosaic kernel-vmem-stack
OOM and T=8192 was untested.  Each experiment here answers one
promotion question, in its OWN subprocess (a Mosaic failure or wedge
must not kill the batch), appending JSON lines to
``bench_artifacts/experiments_r5.jsonl``:

- ``s128_vmem``: does an explicit ``vmem_limit_bytes`` let the fused
  kernel compile at S=128 under a scan — and is it faster than the
  two-sweep it would replace?  (Promotion: raise/remove
  ``_FUSED_BWD_MAX_HEADS`` and set the working limit.)
- ``t8192``: does the fused kernel compile + win at T=8192/H=8 (dq
  accumulator 4 MB)?  (Promotion: raise ``_FUSED_BWD_DQ_BYTES``.)
- ``temporal_tuned``: the staged single-chip levers end-to-end —
  ``attention_chunk=32`` + ``optimizer="flat_adam"`` vs the shipped
  defaults on the real sequence-supervised train step.

Run by hand on a live window (after ``hack/capture_live.py``):
``python hack/tpu_experiments.py [name ...]``.  Exit 0 iff every
requested experiment produced a result line (wins not required —
a clean negative is a result).
"""
from __future__ import annotations

import datetime
import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "bench_artifacts" / "experiments_r5.jsonl"

_PROLOG = """
import json, sys
sys.path.insert(0, {repo!r})
import bench
import numpy as np
from aws_global_accelerator_controller_tpu.jaxenv import import_jax
jax = import_jax()
if jax.default_backend() != "tpu":
    print(json.dumps({{"skipped": "non-tpu"}})); raise SystemExit
import jax.numpy as jnp
from jax import lax
from aws_global_accelerator_controller_tpu.ops import pallas_attention as pa


def chain_grad(q, k, v, n):
    # FULL backward: grad w.r.t. (q, k, v) with every cotangent feeding
    # the chain — grad w.r.t. q alone lets JAX DCE the two-sweep dK/dV
    # pallas_call, making fused-vs-two-sweep A/Bs apples-to-oranges
    # (r4 VERDICT weak #1/#2)
    g = jax.grad(lambda qq, kk, vv: jnp.sum(
        pa.flash_attention(qq, kk, vv, causal=True)
        .astype(jnp.float32)), argnums=(0, 1, 2))
    def body(_, qq):
        dq, dk, dv = g(qq, k, v)
        return (dq + dk + dv).astype(qq.dtype)
    return jax.jit(lambda q0: lax.fori_loop(0, n, body, q0)[0, 0]
                   .astype(jnp.float32))


def ab(progs, q, n, rounds=3, reps=2):
    # interleaved A/B (single-shot timings through this tunnel drift
    # 4x); n large enough that the chain dwarfs latency noise.
    # progs[name] = (f1, fn, gates): the gate globals each program was
    # BUILT under.  jax.clear_caches() between builds evicts earlier
    # executables, and a re-invocation would silently retrace under
    # whatever globals are current — rebinding each program's own
    # gates before every call (plus an untimed re-warm in round 0)
    # keeps every measurement on the kernel it claims to measure.
    best = {{name: float("inf") for name in progs}}
    for rnd in range(rounds):
        for name, (f1, fn, gates) in progs.items():
            for attr, val in gates.items():
                setattr(pa, attr, val)
            if rnd == 0:
                np.asarray(f1(q)); np.asarray(fn(q))   # re-warm
            t1 = min(bench._timed_call(np, f1, q) for _ in range(reps))
            tn = min(bench._timed_call(np, fn, q) for _ in range(reps))
            best[name] = min(best[name], max(tn - t1, 1e-9) / (n - 1))
    return {{name: round(v * 1e6, 1) for name, v in best.items()}}


def gates_snapshot():
    return {{"_FUSED_BWD_DQ_BYTES": pa._FUSED_BWD_DQ_BYTES,
             "_FUSED_BWD_MAX_HEADS": pa._FUSED_BWD_MAX_HEADS,
             "_FUSED_BWD_VMEM_LIMIT": pa._FUSED_BWD_VMEM_LIMIT}}
"""

_BODIES = {
    # S=128: try raised vmem limits; compare against two-sweep
    "s128_vmem": """
t, s, d, n = 2048, 128, 128, 64
ks = jax.random.split(jax.random.PRNGKey(0), 3)
q, k, v = (jax.random.normal(kk, (t, s, d), jnp.bfloat16) for kk in ks)
result = {"exp": "s128_vmem", "t": t, "s": s}
progs = {}
pa._FUSED_BWD_DQ_BYTES = 0            # two-sweep baseline
jax.clear_caches()
f1, fn = chain_grad(q, k, v, 1), chain_grad(q, k, v, n)
np.asarray(f1(q)); np.asarray(fn(q))
progs["two_sweep"] = (f1, fn, gates_snapshot())
for limit_mb in (64, 96, 128):
    pa._FUSED_BWD_DQ_BYTES = 2 * 2 ** 20
    pa._FUSED_BWD_MAX_HEADS = 1024
    pa._FUSED_BWD_VMEM_LIMIT = limit_mb * 2 ** 20
    jax.clear_caches()
    try:
        f1, fn = chain_grad(q, k, v, 1), chain_grad(q, k, v, n)
        np.asarray(f1(q)); np.asarray(fn(q))
        progs[f"fused_{limit_mb}mb"] = (f1, fn, gates_snapshot())
    except Exception as exc:
        result[f"fused_{limit_mb}mb_error"] = (
            f"{type(exc).__name__}: {str(exc)[-160:]}")
result["us_per_iter"] = ab(progs, q, n)
print(json.dumps(result))
""",
    # T=8192 H=8: fused with the budget raised to cover the 4 MB dq acc
    "t8192": """
t, h, d, n = 8192, 8, 128, 16
ks = jax.random.split(jax.random.PRNGKey(0), 3)
q, k, v = (jax.random.normal(kk, (t, h, d), jnp.bfloat16) for kk in ks)
result = {"exp": "t8192", "t": t, "h": h}
progs = {}
pa._FUSED_BWD_DQ_BYTES = 0
jax.clear_caches()
f1, fn = chain_grad(q, k, v, 1), chain_grad(q, k, v, n)
np.asarray(f1(q)); np.asarray(fn(q))
progs["two_sweep"] = (f1, fn, gates_snapshot())
for limit_mb in (None, 128):
    pa._FUSED_BWD_DQ_BYTES = 4 * 2 ** 20
    pa._FUSED_BWD_VMEM_LIMIT = limit_mb and limit_mb * 2 ** 20
    jax.clear_caches()
    tag = f"fused_{limit_mb or 'default'}"
    try:
        f1, fn = chain_grad(q, k, v, 1), chain_grad(q, k, v, n)
        np.asarray(f1(q)); np.asarray(fn(q))
        progs[tag] = (f1, fn, gates_snapshot())
    except Exception as exc:
        result[tag + "_error"] = (
            f"{type(exc).__name__}: {str(exc)[-160:]}")
result["us_per_iter"] = ab(progs, q, n)
print(json.dumps(result))
""",
    # h=32 at the chunked-attention shape: the CLI's --attention-chunk
    # 32 path lands exactly on _FUSED_BWD_MAX_HEADS=32, whose comment
    # admits only h <= 8 was confirmed to compile on-chip (r4 ADVICE).
    # Verifies the fused compile at the gate edge and A/Bs it against
    # the two-sweep it would otherwise take.
    "h32_gate": """
t, h, d, n = 2048, 32, 128, 64
ks = jax.random.split(jax.random.PRNGKey(0), 3)
q, k, v = (jax.random.normal(kk, (t, h, d), jnp.bfloat16) for kk in ks)
result = {"exp": "h32_gate", "t": t, "h": h,
          "gates": gates_snapshot()}
progs = {}
shipped_dq_bytes = pa._FUSED_BWD_DQ_BYTES   # the default under test
pa._FUSED_BWD_DQ_BYTES = 0            # two-sweep baseline
jax.clear_caches()
f1, fn = chain_grad(q, k, v, 1), chain_grad(q, k, v, n)
np.asarray(f1(q)); np.asarray(fn(q))
progs["two_sweep"] = (f1, fn, gates_snapshot())
pa._FUSED_BWD_DQ_BYTES = shipped_dq_bytes   # fused at h=32 (shipped)
jax.clear_caches()
try:
    f1, fn = chain_grad(q, k, v, 1), chain_grad(q, k, v, n)
    np.asarray(f1(q)); np.asarray(fn(q))
    progs["fused_h32"] = (f1, fn, gates_snapshot())
except Exception as exc:
    result["fused_h32_error"] = (
        f"{type(exc).__name__}: {str(exc)[-160:]}")
result["us_per_iter"] = ab(progs, q, n)
print(json.dumps(result))
""",
    # staged levers end-to-end on the real train step
    "temporal_tuned": """
from aws_global_accelerator_controller_tpu.models.temporal import (
    TemporalTrafficModel, synthetic_window)

t, g, e, d, hdim, n = 2048, 8, 16, 128, 256, 16
window, batch = synthetic_window(jax.random.PRNGKey(1), steps=t,
                                 groups=g, endpoints=e, per_step=True)
result = {"exp": "temporal_tuned", "t": t}
progs = {}
for tag, kwargs in (
        ("default", {}),
        ("chunk32", {"attention_chunk": 32}),
        ("flat_adam", {"optimizer": "flat_adam"}),
        ("chunk32_flat", {"attention_chunk": 32,
                          "optimizer": "flat_adam"})):
    m = TemporalTrafficModel(feature_dim=8, embed_dim=d,
                             hidden_dim=hdim, attention="flash",
                             supervision="sequence", **kwargs)
    params = m.init_params(jax.random.PRNGKey(0))
    opt = m.init_opt_state(params)
    def chained(steps, m=m, opt=opt):
        def body(carry, _):
            p, o = carry
            p, o, loss = m.train_step(p, o, window, batch)
            return (p, o), loss
        return jax.jit(lambda p: lax.scan(
            body, (p, opt), None, length=steps)[1][-1])
    try:
        f1, fn = chained(1), chained(n)
        np.asarray(f1(params)); np.asarray(fn(params))
        progs[tag] = (f1, fn, gates_snapshot())
    except Exception as exc:
        result[tag + "_error"] = (
            f"{type(exc).__name__}: {str(exc)[-160:]}")
result["us_per_iter"] = ab(progs, params, n)
print(json.dumps(result))
""",
}


def main(argv=None) -> int:
    names = (argv or sys.argv[1:]) or list(_BODIES)
    unknown = [n for n in names if n not in _BODIES]
    if unknown:
        # a typo must not burn the live window on a traceback
        print(f"unknown experiment(s) {unknown}; "
              f"valid: {', '.join(_BODIES)}", file=sys.stderr)
        return 2
    # tree provenance on every result line (r4 VERDICT weak #5) —
    # same stamp as the bench transcripts
    sys.path.insert(0, str(REPO / "hack"))
    from capture_live import _tree
    tree = _tree()
    ok = True
    for name in names:
        code = _PROLOG.format(repo=str(REPO)) + _BODIES[name]
        started = datetime.datetime.now(
            datetime.timezone.utc).strftime("%FT%TZ")
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=2400, cwd=REPO)
            line = (proc.stdout.strip().splitlines() or ["{}"])[-1]
            parsed = json.loads(line)
        except subprocess.TimeoutExpired:
            parsed = {"exp": name, "skipped": "wrapper timeout"}
        except (ValueError, OSError) as exc:
            parsed = {"exp": name,
                      "skipped": f"{type(exc).__name__}: {exc}"}
        parsed["started_at"] = started
        parsed["tree"] = tree
        with open(OUT, "a") as f:
            f.write(json.dumps(parsed) + "\n")
        print(f"[experiment] {name}: {json.dumps(parsed)[:300]}",
              flush=True)
        ok = ok and "skipped" not in parsed
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
