#!/usr/bin/env python3
"""Replay a recorded adaptive-soak scenario from its seed and diff
the convergence ledger against the recorded run (ISSUE 15).

The adaptive-soak bench (``bench.py adaptive-soak``) records each
adaptive arm to ``bench_artifacts/fuzz/<family>-<seed>.json``: the
(family, seed) replay handle, the script's sha1 (generator-drift
guard), the run config, and the convergence-ledger slice the run
produced.  This tool regenerates the script from NOTHING but the
seed, re-runs it under a fresh virtual clock with the same autotune
config, and diffs the ledgers record-by-record — the cross-process
half of the determinism contract tests/chaos/test_chaos_determinism
proves in-process.

Exit codes:
  0  ledgers byte-identical (the scenario replays)
  1  DIVERGENCE — a wall-clock leak, an unseeded draw, or a behavior
     change landed since the artifact was recorded (bounded diff on
     stderr)
  2  not comparable: unreadable artifact, or the script generator
     itself changed (script sha mismatch — re-record, don't diff)

Usage:
  python hack/fuzz_replay.py bench_artifacts/fuzz/<family>-<seed>.json
  python hack/fuzz_replay.py --selftest   # record a small scenario,
                                          # then replay it in a FRESH
                                          # subprocess (make fuzz-smoke)
"""
from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

SELFTEST_FAMILY = "bursty-creates"
SELFTEST_SEED = 20260805
SELFTEST_N = 12
SELFTEST_DURATION = 40.0


def _run_scenario(family: str, seed: int, n_services: int,
                  duration: float, workers: int,
                  interval: float) -> dict:
    from aws_global_accelerator_controller_tpu.autotune import (
        AutotuneConfig,
    )
    from aws_global_accelerator_controller_tpu.simulation import (
        clock as simclock,
    )
    from aws_global_accelerator_controller_tpu.simulation.fuzzer import (
        ScenarioRunner,
        generate,
    )

    script = generate(family, seed, n_services=n_services,
                      duration=duration)
    clk = simclock.VirtualClock(max_virtual=24 * 3600.0).activate()
    try:
        out = ScenarioRunner(
            script, workers=workers,
            autotune=AutotuneConfig(enabled=True,
                                    interval=interval)).run()
    finally:
        clk.deactivate()
    out["script_sha"] = hashlib.sha1(
        script.canonical_json().encode()).hexdigest()
    return out


def _diff_ledgers(recorded, replayed) -> int:
    """Bounded record-level diff; returns the divergence count."""
    div = 0
    for i, (a, b) in enumerate(zip(recorded, replayed)):
        if a != b:
            div += 1
            if div <= 5:
                print(f"  record {i}:\n    recorded: {a}\n"
                      f"    replayed: {b}", file=sys.stderr)
    if len(recorded) != len(replayed):
        div += abs(len(recorded) - len(replayed))
        print(f"  length: recorded {len(recorded)} vs replayed "
              f"{len(replayed)}", file=sys.stderr)
    return div


def replay(path: str) -> int:
    try:
        with open(path) as f:
            art = json.load(f)
        family, seed = art["family"], int(art["seed"])
        n, duration = int(art["n_services"]), float(art["duration"])
        workers = int(art.get("workers", 2))
        interval = float(art.get("interval", 0.5))
        recorded_sha = art["script_sha"]
        recorded_ledger = art["ledger"]
    except (OSError, KeyError, ValueError, json.JSONDecodeError) as e:
        print(f"fuzz_replay: unreadable artifact {path}: {e}",
              file=sys.stderr)
        return 2
    print(f"fuzz_replay: re-running {family}:{seed} "
          f"({n} services, {duration}s sim) from the seed alone...",
          file=sys.stderr)
    out = _run_scenario(family, seed, n, duration, workers, interval)
    if out["script_sha"] != recorded_sha:
        print("fuzz_replay: the script GENERATOR changed since this "
              "artifact was recorded (sha mismatch) — ledgers are "
              "not comparable; re-record with bench.py adaptive-soak",
              file=sys.stderr)
        return 2
    # normalize through one JSON round-trip: the recorded side lived
    # through json.dump (tuples become lists)
    replayed = json.loads(json.dumps(out["ledger"]))
    div = _diff_ledgers(recorded_ledger, replayed)
    if div:
        print(f"fuzz_replay: DIVERGED — {div} ledger record(s) "
              f"differ: a wall-clock leak or unseeded draw broke "
              f"replay-identity (lint L115 and the determinism suite "
              f"are the usual suspects)", file=sys.stderr)
        return 1
    print(f"fuzz_replay: OK — {len(replayed)} ledger records "
          f"byte-identical", file=sys.stderr)
    return 0


def selftest() -> int:
    """Record a small scenario, then replay it in a FRESH subprocess:
    the true cross-process determinism check (make fuzz-smoke)."""
    print("fuzz_replay --selftest: recording "
          f"{SELFTEST_FAMILY}:{SELFTEST_SEED}...", file=sys.stderr)
    out = _run_scenario(SELFTEST_FAMILY, SELFTEST_SEED, SELFTEST_N,
                        SELFTEST_DURATION, workers=2, interval=0.5)
    fd, path = tempfile.mkstemp(suffix=".json", prefix="fuzz-smoke-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump({
                "family": SELFTEST_FAMILY, "seed": SELFTEST_SEED,
                "n_services": SELFTEST_N,
                "duration": SELFTEST_DURATION,
                "workers": 2, "interval": 0.5, "adaptive": True,
                "script_sha": out["script_sha"],
                "ledger": out["ledger"],
            }, f, sort_keys=True)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), path],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            timeout=600)
        return proc.returncode
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


def main(argv) -> int:
    args = [a for a in argv[1:]]
    if "--selftest" in args:
        return selftest()
    paths = [a for a in args if not a.startswith("--")]
    if len(paths) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    return replay(paths[0])


if __name__ == "__main__":
    sys.exit(main(sys.argv))
