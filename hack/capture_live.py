#!/usr/bin/env python3
"""Capture a driver-checkable live-TPU bench run (VERDICT r2 item 1).

Runs every accelerator bench through ``python bench.py <name>`` (each is
already a bounded, retried subprocess), tees the raw child stdout/stderr
into a timestamped transcript under ``bench_artifacts/``, assembles a
dated ``bench_artifacts/BENCH_LIVE.json``, and commits both — so the
evidence survives even if the session dies right after the tunnel does.

Meant to be invoked by ``hack/tpu_watch.sh`` the moment a probe sees the
tunnel alive, but safe to run by hand.  Exit 0 iff at least one TPU
bench produced a non-skipped result.

Optional argv: leg names (see ``BENCHES``) to run only those — for a
second window after a partial capture (the tunnel tends to give one
healthy early window, then wedge mid-list).  A partial run MERGES into
the existing ``BENCH_LIVE.json`` instead of overwriting it, so the legs
already captured live keep their evidence; success then means "every
requested leg produced a non-skipped result".
"""
from __future__ import annotations

import datetime
import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
ART = REPO / "bench_artifacts"

# autotune last: it is the long pole (20 min budget) and the headline
# numbers should land even if the tunnel dies mid-sweep.  Wrapper
# budgets sit above each bench's own worst case (inner subprocess
# timeout x2 for the built-in retry, plus interpreter startup) so the
# wrapper never kills a bench that was about to finish or skip
# gracefully.
# smoke first: it is the Mosaic compile gate — if a kernel-layout
# change broke TPU lowering, every later leg would fail anyway and
# smoke's per-variant compile report is the diagnostic we want
BENCHES = [
    ("smoke", 660.0),
    ("flash", 660.0),
    ("flash-long", 660.0),
    ("flash-xl", 1100.0),
    ("temporal", 1100.0),
    ("temporal-breakdown", 2900.0),
    ("planner", 660.0),
    ("autotune", 2500.0),
]
# the benches whose success means "we captured a live perf number";
# smoke passing is necessary but not sufficient (it only compiles)
_PERF = ("flash", "flash-long", "flash-xl", "temporal")


def _run_group(cmd, budget: float):
    """subprocess.run-alike that runs cmd in its OWN process group and
    SIGKILLs the whole group on timeout: bench.py's legs spawn
    grandchildren (bench._run_subprocess), and an orphaned grandchild
    still holding the single-tenant TPU would wedge every later leg."""
    import os
    import signal

    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            cwd=REPO, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=budget)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        out, err = proc.communicate()
        raise subprocess.TimeoutExpired(cmd, budget, output=out,
                                        stderr=err)


def _utc() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def _tree() -> str:
    """Short SHA of the tree being measured, '+dirty' when the working
    tree differs from it — stamped into the transcript header and every
    leg so each number traces to the code that produced it (r4 VERDICT
    weak #5: every committed kernel number described a tree 20 commits
    behind HEAD with nothing recording that)."""
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
            capture_output=True, text=True).stdout.strip() or "unknown"
        dirty = subprocess.run(
            ["git", "status", "--porcelain", "-uno"], cwd=REPO,
            capture_output=True, text=True).stdout.strip()
        return sha + ("+dirty" if dirty else "")
    except OSError:
        return "unknown"


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    known = {name for name, _ in BENCHES}
    unknown = [a for a in argv if a not in known]
    if unknown:
        print(f"unknown legs {unknown}; known: {sorted(known)}",
              file=sys.stderr)
        return 2
    selected = [(n, b) for n, b in BENCHES if not argv or n in argv]
    partial = bool(argv)

    ART.mkdir(exist_ok=True)
    stamp = _utc().replace(":", "")
    tree = _tree()
    transcript = ART / f"transcript_{stamp}.log"
    results: dict = {}
    any_live = False
    ok_legs: list = []
    with transcript.open("w") as log:
        log.write(f"# live TPU bench capture started {_utc()}\n")
        log.write(f"# tree: {tree}\n")
        log.write("# host cmd: python bench.py <name> (see bench.py)\n")
        if partial:
            log.write(f"# partial capture: {[n for n, _ in selected]}\n")
        for name, budget in selected:
            start = _utc()
            log.write(f"\n===== bench.py {name} (start {start}, "
                      f"budget {budget:.0f}s) =====\n")
            log.flush()
            try:
                rc, out, err = _run_group(
                    [sys.executable, "bench.py", name], budget)
                log.write(out)
                if err:
                    log.write(f"\n--- stderr ---\n{err}\n")
                line = out.strip().splitlines()
                if rc != 0 or not line:
                    parsed = {"skipped": f"rc={rc}, "
                              f"stderr={err.strip()[-200:]}"}
                else:
                    parsed = json.loads(line[-1])
            except subprocess.TimeoutExpired as exc:
                log.write(f"\n--- wrapper timeout after {budget:.0f}s "
                          f"---\n{(exc.stdout or '')}\n{(exc.stderr or '')}\n")
                parsed = {"skipped": f"wrapper timeout > {budget:.0f}s"}
            except (json.JSONDecodeError, OSError) as exc:
                parsed = {"skipped": f"capture error: {exc}"}
            end = _utc()
            log.write(f"===== bench.py {name} done {end} =====\n")
            log.flush()
            # per-leg transcript provenance: a partial second-window
            # capture merges into BENCH_LIVE.json, so carried-over
            # legs cite a DIFFERENT transcript than this run's —
            # bench.py report reads this field per row
            results[name] = {"started_at": start, "finished_at": end,
                             "transcript": transcript.name,
                             "tree": tree,
                             **(parsed if isinstance(parsed, dict)
                                else {"value": parsed})}
            leg_ok = isinstance(parsed, dict) and "skipped" not in parsed
            if leg_ok and name in _PERF:
                any_live = True
            ok_legs.append(leg_ok)
            print(f"[capture] {name}: "
                  f"{json.dumps(parsed)[:200]}", flush=True)

    autotune = results.get("autotune") or {}
    if autotune.get("ranked"):
        # proposal only — a human reviews the sweep (noise, failed
        # configs) before promoting it to ops/flash_blocks.json, where
        # pallas_attention._resolve_blocks starts honoring it
        best = autotune["ranked"][0]
        (ART / "flash_blocks_proposed.json").write_text(json.dumps({
            "generated_at": _utc(),
            "device_kind": autotune.get("device_kind"),
            "swept_shape": autotune.get("shape"),
            "bands": [{
                "t_max": (autotune.get("shape") or {}).get("t", 0),
                "block_q": best["block_q"] or 1024,
                "block_k": best["block_k"] or 1024,
            }],
            "ranked": autotune["ranked"],
        }, indent=2) + "\n")

    live_path = ART / "BENCH_LIVE.json"
    merged_results, live_flag = results, any_live
    transcripts = [transcript.name]
    if partial and live_path.exists():
        try:
            prior = json.loads(live_path.read_text())
        except ValueError:
            prior = {}
        merged_results = {**(prior.get("results") or {}), **results}
        live_flag = any_live or bool(prior.get("live"))
        # keep the evidence chain: carried-over legs live in the PRIOR
        # capture's transcript(s), not this partial run's
        transcripts = [t for t in (prior.get("transcripts")
                                   or ([prior["transcript"]]
                                       if prior.get("transcript")
                                       else []))
                       if t != transcript.name] + transcripts
    payload = {
        "measured_at": _utc(),
        "transcript": transcript.name,
        "transcripts": transcripts,
        "tree": tree,
        "live": live_flag,
        "results": merged_results,
    }
    live_path.write_text(json.dumps(payload, indent=2) + "\n")
    # commit ONLY the artifact paths: the watcher may fire while the
    # working tree holds unrelated in-progress edits.  git's stdout is
    # swallowed — when this script runs under nohup redirected into
    # bench_artifacts/, commit chatter would append itself to an
    # already-staged capture log
    subprocess.run(["git", "add", "bench_artifacts"], cwd=REPO,
                   stdout=subprocess.DEVNULL)
    subprocess.run(
        ["git", "commit",
         "-m", f"bench: live TPU capture {payload['measured_at']} "
               f"(live={live_flag}"
               + (f", legs={'+'.join(n for n, _ in selected)}"
                  if partial else "") + ")",
         "--", "bench_artifacts"], cwd=REPO,
        stdout=subprocess.DEVNULL)
    if partial:
        return 0 if ok_legs and all(ok_legs) else 1
    return 0 if any_live else 1


if __name__ == "__main__":
    sys.exit(main())
