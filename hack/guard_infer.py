#!/usr/bin/env python3
"""Render runtime guard-access profiles as reviewable ``# guarded-by:``
declarations (the inference half of the L119/L120 ownership pass).

Input: one or more JSON dumps produced by
``analysis/locks.dump_guard_profile`` — run any suite with
``AGAC_GUARD_PROFILE=/tmp/guard.json`` and the conftest session hook
writes the dump at exit.  Each dump maps ``Class.attr`` to the
multiset of locksets held across every post-``__init__`` write the
patched ``__setattr__`` observed.

Output, per observed field:

  propose   not yet declared, and ONE lock was held at every observed
            write -> a paste-ready ``# guarded-by: self.<lock>`` line
  review    not yet declared, and the held locksets disagree (or were
            empty): a human must decide between a lock, ``external:``
            ownership, or a real race
  declared  already declared; flags a MISMATCH when the dominant
            observed lock is not the declared one (the static map and
            the dynamic evidence disagree — one of them is wrong)

The proposals are evidence, not truth: a field written under one lock
in the exercised paths may still be read lock-free elsewhere.  Review
before pasting; the static pass (``make lint``) then holds whatever
you declare.

Usage: python hack/guard_infer.py profile.json [more.json ...]
       [--root aws_global_accelerator_controller_tpu]
Exit 0 (informational; declared-map MISMATCH rows exit 1 so CI can
object when dynamic evidence contradicts a declaration).
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def load_profiles(paths):
    """Merge dumps: 'Class.attr' -> {lockset-desc -> count}."""
    merged = {}
    for p in paths:
        doc = json.loads(Path(p).read_text())
        for key, entry in doc.items():
            held = merged.setdefault(key, {})
            for desc, n in entry.get("held", {}).items():
                held[desc] = held.get(desc, 0) + int(n)
    return merged


def declared_map(root: Path):
    """'Class.attr' -> GuardDecl from the tree's static declarations."""
    from aws_global_accelerator_controller_tpu.analysis.ownership import (
        declared_runtime_guards,
    )
    return {
        f"{cls}.{attr}": decl
        for cls, attrs in declared_runtime_guards(root).items()
        for attr, decl in attrs.items()
    }


def dominant(held):
    """(set of locks held at EVERY observed write, total writes)."""
    total = sum(held.values())
    always = None
    for desc, _ in held.items():
        locks = set() if desc == "<none>" else set(desc.split("|"))
        always = locks if always is None else (always & locks)
    return always or set(), total


def pick(always):
    """Paste-ready spelling: prefer a ``self.<attr>`` name."""
    named = sorted(always, key=lambda s: (not s.startswith("self."), s))
    return named[0] if named else None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("profiles", nargs="+", help="dump_guard_profile JSONs")
    ap.add_argument("--root", default=str(
        REPO / "aws_global_accelerator_controller_tpu"))
    args = ap.parse_args(argv)

    merged = load_profiles(args.profiles)
    declared = declared_map(Path(args.root))

    mismatches = 0
    for key in sorted(merged):
        held = merged[key]
        always, total = dominant(held)
        decl = declared.get(key)
        if decl is not None:
            if decl.kind == "lock":
                want = ".".join(decl.chain or ())
                if "<untracked>" in held:
                    print(f"declared {key}: '{want}' is a plain "
                          f"primitive — invisible to the tracker "
                          f"({total} writes unverifiable)")
                elif want in always:
                    print(f"declared {key}: '{want}' "
                          f"({total} writes consistent)")
                else:
                    mismatches += 1
                    seen = ", ".join(sorted(held)) or "<none>"
                    print(f"MISMATCH {key}: declared '{want}' not "
                          f"held at every observed write "
                          f"({total} writes; locksets: {seen})")
            else:
                print(f"declared {key}: {decl.kind} ({total} writes)")
        elif always:
            print(f"propose  {key}: # guarded-by: {pick(always)} "
                  f"(held at all {total} observed writes)")
        else:
            seen = ", ".join(sorted(held)) or "<none>"
            print(f"review   {key}: no single lock held "
                  f"({total} writes; locksets: {seen})")
    if not merged:
        print("no profiled writes (was AGAC_GUARD_PROFILE set and the "
              "suite exercised?)")
    return 1 if mismatches else 0


if __name__ == "__main__":
    raise SystemExit(main())
