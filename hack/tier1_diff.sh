#!/usr/bin/env bash
# The per-PR "failure set no worse" gate as ONE command (`make
# tier1-diff`): run tier-1 on a clean BASELINE checkout (a detached
# git worktree of TIER1_BASE, default HEAD — the stashed-HEAD ritual
# every PR since the accelerator drift has hand-rolled) and on the
# working tree, then diff the FAILED/ERROR sets with
# hack/diff_failures.py.  Exit status is diff_failures' own: 0 = no
# newly-failing tests (fixes alone are fine), 1 = regressions, 2 =
# unusable logs.
#
# The package resolves from the pytest cwd (it is not installed), so
# the baseline worktree runs the baseline CODE — the two runs share
# nothing but the interpreter.  Both logs are kept (TIER1_BASE_LOG /
# TIER1_HEAD_LOG, defaults under /tmp) for post-mortems.
#
# Documented in docs/operations.md "Tier-1 workflow".
set -uo pipefail

BASE_REF="${TIER1_BASE:-HEAD}"
BASE_LOG="${TIER1_BASE_LOG:-/tmp/tier1_base.log}"
HEAD_LOG="${TIER1_HEAD_LOG:-/tmp/tier1_head.log}"
REPO="$(git rev-parse --show-toplevel)" || exit 2
WT="$(mktemp -d /tmp/tier1-base.XXXXXX)" || exit 2

# ROADMAP.md's tier-1 verify line, minus the pass-count accounting
run_tier1() {
    timeout -k 10 870 env JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly
}

cleanup() {
    git -C "$REPO" worktree remove --force "$WT" >/dev/null 2>&1 || true
    rm -rf "$WT"
}
trap cleanup EXIT

if ! git -C "$REPO" worktree add --detach "$WT" "$BASE_REF" >/dev/null; then
    echo "tier1-diff: cannot create baseline worktree at $BASE_REF" >&2
    exit 2
fi

echo "tier1-diff: baseline $BASE_REF -> $BASE_LOG"
(cd "$WT" && run_tier1) >"$BASE_LOG" 2>&1
echo "tier1-diff: working tree -> $HEAD_LOG"
(cd "$REPO" && run_tier1) >"$HEAD_LOG" 2>&1

python "$REPO/hack/diff_failures.py" "$BASE_LOG" "$HEAD_LOG"
