#!/usr/bin/env bash
# Watch for the TPU tunnel coming alive; capture live benches when it
# does (VERDICT r2 item 1).  The tunnel wedges for hours at a time
# (memory: healthy early-session windows only), so the only reliable
# way to get a driver-checkable number is to poll and pounce.
#
# Exits 0 after a successful capture, 1 when the deadline passes.
set -u
cd "$(dirname "$0")/.."

DEADLINE_H="${1:-11}"
shift 2>/dev/null || true
# remaining args: leg names forwarded to capture_live.py (partial
# second-window capture; empty = the full list)
LEGS=("$@")
SLEEP_S=240
export PROBE_TIMEOUT=75
end=$(( $(date +%s) + DEADLINE_H * 3600 ))

while [ "$(date +%s)" -lt "$end" ]; do
    status=$(python - <<'EOF'
import bench
s, d = bench.tpu_probe(timeout=float(__import__("os").environ.get("PROBE_TIMEOUT", "75")))
print(s)
EOF
)
    echo "$(date -u +%FT%TZ) probe: ${status}"
    if [ "$status" = "tpu" ]; then
        echo "$(date -u +%FT%TZ) tunnel ALIVE - capturing"
        if python hack/capture_live.py ${LEGS[@]+"${LEGS[@]}"}; then
            echo "$(date -u +%FT%TZ) capture complete - running gate experiments"
            # burn the rest of the window on the staged-promotion
            # experiments (fused-backward gates, temporal levers);
            # capture_live already committed its own artifacts
            if python hack/tpu_experiments.py; then
                echo "$(date -u +%FT%TZ) experiments complete"
            else
                echo "$(date -u +%FT%TZ) experiments incomplete (see bench_artifacts/experiments_r5.jsonl)"
            fi
            # stage ONLY the file this run produced (tpu_experiments.py
            # appends to experiments_r5.jsonl; capture_live committed its
            # own artifacts above) — a bare `git add bench_artifacts`
            # would sweep up unrelated scratch files (half-written
            # captures, jax_cache debris) into the experiment commit
            EXPERIMENTS_OUT=bench_artifacts/experiments_r5.jsonl
            git add -- "$EXPERIMENTS_OUT" 2>/dev/null
            if ! git commit -m "bench: on-chip gate experiments $(date -u +%FT%TZ)" -- "$EXPERIMENTS_OUT" >/dev/null 2>&1; then
                echo "$(date -u +%FT%TZ) WARNING: experiment-artifact commit failed - $EXPERIMENTS_OUT left uncommitted (commit by hand)"
            fi
            exit 0
        fi
        echo "$(date -u +%FT%TZ) capture produced no live result; continuing watch"
    fi
    sleep "$SLEEP_S"
done
echo "$(date -u +%FT%TZ) deadline reached without a live capture"
exit 1
