#!/usr/bin/env bash
# Analogue of the reference's hack/update-codegen.sh: regenerate all derived
# artifacts (CRD manifest, RBAC role, webhook configuration) from the Python
# type definitions. CI gates on a clean diff (`make check-manifests`).
set -euo pipefail
cd "$(dirname "$0")/.."
python -m aws_global_accelerator_controller_tpu.codegen
echo "generated manifests are up to date under config/"
