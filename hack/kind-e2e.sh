#!/usr/bin/env bash
# Genuine-apiserver e2e (VERDICT r2 missing #1): run the shipped
# manifests and the controller's --real HTTP backend against a REAL
# kube-apiserver (kind), mirroring the reference's e2e
# (/root/reference/.github/workflows/e2e.yml + e2e/e2e_test.go).
#
# Preconditions (the kind-e2e.yml workflow provides them):
#   - kubectl context pointing at a kind cluster
#   - cert-manager installed and ready
#   - the controller image built and `kind load`-ed as $WEBHOOK_IMAGE
#   - this package pip-installed on the host (the controller process
#     runs on the host, speaking real HTTP to the apiserver)
set -euo pipefail

WEBHOOK_IMAGE="${WEBHOOK_IMAGE:-aws-global-accelerator-controller-tpu:latest}"
NS=system
RESOURCE_NS=default
EGB=demo-binding
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
CTL_LOG="$(mktemp)"
CTL_PID=""

cleanup() {
    [ -n "$CTL_PID" ] && kill "$CTL_PID" 2>/dev/null || true
    # --wait=false: the binding carries a controller-owned finalizer and
    # the controller is already down — don't hang on finalization
    kubectl delete endpointgroupbindings -n "$RESOURCE_NS" --all \
        --ignore-not-found --wait=false >/dev/null 2>&1 || true
    echo "--- controller log tail ---"
    tail -50 "$CTL_LOG" || true
}
trap cleanup EXIT

step() { echo; echo "=== $* ==="; }

step "Apply CRD + RBAC"
kubectl apply -f "$ROOT/config/crd"
kubectl create namespace "$NS" --dry-run=client -o yaml | kubectl apply -f -

step "Deploy webhook (Deployment + Service + cert-manager Certificate)"
# pin the image the workflow loaded into the kind nodes
sed "s|image: aws-global-accelerator-controller-tpu:latest|image: ${WEBHOOK_IMAGE}|" \
    "$ROOT/config/webhook/deployment.yaml" | kubectl apply -f -
kubectl apply -f "$ROOT/config/webhook/manifests.yaml"
kubectl -n "$NS" rollout status deployment/webhook --timeout=300s
kubectl -n "$NS" wait certificate/webhook-serving-cert \
    --for=condition=Ready --timeout=120s

step "Webhook: ARN immutability enforced by the REAL admission chain"
kubectl apply -f "$ROOT/config/samples/endpointgroupbinding.yaml"
if kubectl -n "$RESOURCE_NS" patch endpointgroupbinding "$EGB" \
    --type=merge \
    -p '{"spec":{"endpointGroupArn":"arn:aws:globalaccelerator::123456789012:accelerator/5678efgh-efgh-5678-efgh-5678efgh5678"}}' \
    2>"$CTL_LOG.patch"; then
    echo "FAIL: ARN mutation was admitted"; exit 1
fi
grep -qi "immutable" "$CTL_LOG.patch" \
    || { echo "FAIL: denial did not cite immutability:"; cat "$CTL_LOG.patch"; exit 1; }
echo "OK: ARN mutation denied with immutability message"

step "Webhook: weight mutation admitted"
kubectl -n "$RESOURCE_NS" patch endpointgroupbinding "$EGB" \
    --type=merge -p '{"spec":{"weight":200}}'
echo "OK: weight change admitted"

step "Controller --real over HTTP: Service -> accelerator convergence"
python -m aws_global_accelerator_controller_tpu controller \
    --real --kubeconfig "${KUBECONFIG:-$HOME/.kube/config}" \
    --fake-cloud --health-port 0 >"$CTL_LOG" 2>&1 &
CTL_PID=$!

kubectl apply -f "$ROOT/config/samples/nlb-public-service.yaml"
SVC_NS=default
SVC=demo-app
# kind has no AWS cloud controller: inject the NLB hostname the way the
# in-cluster AWS LB controller would, via the status subresource
kubectl -n "$SVC_NS" patch service "$SVC" --subresource=status \
    --type=merge \
    -p '{"status":{"loadBalancer":{"ingress":[{"hostname":"e2e0123456789abc-0123456789abcdef.elb.ap-northeast-1.amazonaws.com"}]}}}'

deadline=$(( $(date +%s) + 180 ))
until kubectl -n "$SVC_NS" get events \
        --field-selector "involvedObject.name=${SVC},reason=GlobalAcceleratorCreated" \
        -o name 2>/dev/null | grep -q event; do
    if [ "$(date +%s)" -gt "$deadline" ]; then
        echo "FAIL: no GlobalAcceleratorCreated event within 180s"
        kubectl -n "$SVC_NS" get events | tail -20
        exit 1
    fi
    sleep 3
done
echo "OK: controller reconciled the Service through the real apiserver"

step "PASS"
