#!/usr/bin/env python3
"""Render a flight-recorder dump (flight.py) as a per-key timeline
and/or Chrome trace-event JSON.

Usage:
    python hack/flight_replay.py DUMP.json            # timeline to stdout
    python hack/flight_replay.py DUMP.json --chrome OUT.json
    python hack/flight_replay.py DUMP.json --key default/svc-1

The timeline groups the frozen span ring by trace id, joins each trace
to its convergence-ledger record (stage breakdown: queued / planned /
coalesced / inflight / baked), and prints one indented tree per traced
key — chaos injections and span errors annotated inline.  The
``--chrome`` export uses the same trace-event serializer as the
``/traces?format=chrome`` endpoint (tracing.to_chrome_events); load it
in chrome://tracing or https://ui.perfetto.dev.

Exit codes: 0 rendered, 2 unreadable/non-dump input.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from aws_global_accelerator_controller_tpu.tracing import (  # noqa: E402
    to_chrome_events,
)


def load_dump(path: str) -> dict:
    try:
        with open(path) as f:
            dump = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: cannot read dump {path!r}: {e}", file=sys.stderr)
        raise SystemExit(2)
    if not isinstance(dump, dict) or "spans" not in dump:
        print(f"error: {path!r} is not a flight-recorder dump "
              "(no 'spans')", file=sys.stderr)
        raise SystemExit(2)
    return dump


def _span_line(span: dict, t0: float) -> str:
    off = span.get("start_wall", t0) - t0
    dur = span.get("duration_s", 0.0)
    bits = [f"+{off:8.4f}s", f"{dur * 1000:8.3f}ms",
            f"tid={span.get('tid', 0)}", span.get("name", "?")]
    attrs = span.get("attributes", {})
    for k in ("key", "queue", "kind", "group", "outcome", "rung",
              "cohort"):
        if k in attrs:
            bits.append(f"{k}={attrs[k]}")
    if attrs.get("chaos"):
        bits.append(f"chaos={attrs['chaos']}")
    if span.get("links"):
        bits.append(f"links={span['links']}")
    if span.get("error"):
        bits.append(f"ERROR({span['error']})")
    return "  ".join(str(b) for b in bits)


def render_timeline(dump: dict, only_key: str | None = None) -> str:
    spans = dump.get("spans", [])
    ledger = dump.get("ledger", [])
    by_trace: "defaultdict[int, list]" = defaultdict(list)
    for s in spans:
        by_trace[s.get("trace_id", 0)].append(s)
        # a span linking other traces (flush cohorts, folds) appears
        # in every linked trace's lane too: the walk follows links
        for t in s.get("links", []):
            if t != s.get("trace_id"):
                by_trace[t].append(s)
    records = [r for r in ledger
               if only_key is None or r.get("key") == only_key]
    out = [f"flight dump: reason={dump.get('reason')} "
           f"detail={dump.get('detail')!r} pid={dump.get('pid')}"]
    seen_traces = set()
    for rec in records:
        tid = rec.get("trace_id")
        seen_traces.add(tid)
        stages = rec.get("stages", {})
        stage_bits = "  ".join(
            f"{name}={stages[name] * 1000:.3f}ms"
            for name in ("queued", "planned", "coalesced", "inflight",
                         "baked") if name in stages)
        extra = {k: v for k, v in stages.items()
                 if k not in ("queued", "planned", "coalesced",
                              "inflight", "baked")}
        if extra:
            stage_bits += "  " + "  ".join(
                f"{k}={v * 1000:.3f}ms" for k, v in sorted(extra.items()))
        out.append("")
        out.append(f"key {rec.get('key')}  trace={tid} "
                   f"origin={rec.get('origin')} "
                   f"total={rec.get('total_s', 0) * 1000:.3f}ms")
        out.append(f"  stages: {stage_bits or '(none)'}")
        if rec.get("links"):
            out.append(f"  folded traces: {rec['links']}")
        trace_spans = sorted(by_trace.get(tid, []),
                             key=lambda s: s.get("start_wall", 0.0))
        if trace_spans:
            t0 = trace_spans[0].get("start_wall", 0.0)
            for s in trace_spans:
                out.append("    " + _span_line(s, t0))
    if only_key is None:
        # traces with spans but no ledger record (still in flight when
        # the box froze) — the stall you're probably looking for
        leftovers = sorted(t for t in by_trace
                           if t not in seen_traces and t)
        if leftovers:
            out.append("")
            out.append(f"unconverged traces at freeze: "
                       f"{len(leftovers)}")
            for tid in leftovers[:10]:
                trace_spans = sorted(by_trace[tid],
                                     key=lambda s: s.get("start_wall",
                                                         0.0))
                names = [s.get("name") for s in trace_spans]
                out.append(f"  trace={tid}: {len(trace_spans)} spans "
                           f"({', '.join(names[:6])}"
                           f"{'...' if len(names) > 6 else ''})")
    chaos = dump.get("chaos", {})
    for source, decisions in sorted(chaos.items()):
        out.append("")
        out.append(f"chaos[{source}]: {len(decisions)} injected "
                   f"decisions")
        for d in decisions[-8:]:
            out.append(f"  {d}")
    delta = dump.get("metrics_delta", {})
    if delta:
        out.append("")
        out.append(f"metrics delta since arm ({len(delta)} series, "
                   "top 15 by magnitude):")
        top = sorted(delta.items(), key=lambda kv: -abs(kv[1]))[:15]
        for name, v in top:
            out.append(f"  {name} {v:+g}")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dump", help="flight-recorder JSON dump")
    ap.add_argument("--chrome", metavar="OUT",
                    help="also write Chrome trace-event JSON here")
    ap.add_argument("--key", help="restrict the timeline to one "
                    "object key")
    args = ap.parse_args(argv)
    dump = load_dump(args.dump)
    print(render_timeline(dump, only_key=args.key))
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump({"traceEvents": to_chrome_events(dump["spans"])},
                      f)
        print(f"\nchrome trace written to {args.chrome} "
              "(open in chrome://tracing or ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
