"""User-facing annotation API surface.

Mirrors the reference's annotation constants (pkg/apis/type.go:3-13) --
these annotations on Service/Ingress objects *are* the controller's
configuration system (SURVEY.md §5 "Config / flag system").
"""

# Annotations owned by this controller (reference pkg/apis/type.go:4-9).
AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/global-accelerator-managed"
)
ROUTE53_HOSTNAME_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/route53-hostname"
)
CLIENT_IP_PRESERVATION_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/client-ip-preservation"
)
AWS_GLOBAL_ACCELERATOR_NAME_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/global-accelerator-name"
)
AWS_GLOBAL_ACCELERATOR_TAGS_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/global-accelerator-tags"
)
AWS_GLOBAL_ACCELERATOR_IP_ADDRESS_TYPE_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/ip-address-type"
)

# Weighted Route53 routing (ROADMAP item 5 traffic engineering): an
# annotated object's alias/TXT records become a WEIGHTED record set —
# SetIdentifier names this object's side of the pair, weight is the
# served share.  Two objects claiming the same hostname with DISTINCT
# set identifiers are a legitimate blue-green pair, not a contested
# claim.
ROUTE53_SET_IDENTIFIER_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/route53-set-identifier"
)
ROUTE53_WEIGHT_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/route53-weight"
)

# Safe-rollout annotations (rollout/): a declared weight ramp instead
# of an atomic snap.  Spelling per the rollout engine's contract —
# rollout.agac/steps: "5,25,50,100" (percent of target per step),
# rollout.agac/interval: seconds a step must hold healthy before
# advancing, rollout.agac/health: "gated" (default: breaker + observed
# convergence + error window) or "none", rollout.agac/rollback:
# "immediate" (default), rollout.agac/abort: any value = a terminal
# health verdict (external probers / operators flip this to force the
# auto-rollback).  State lives in object STATUS (EndpointGroupBinding)
# or the controller-owned rollout.agac/state annotation (core kinds).
ROLLOUT_PREFIX = "rollout.agac/"
ROLLOUT_STEPS_ANNOTATION = ROLLOUT_PREFIX + "steps"
ROLLOUT_INTERVAL_ANNOTATION = ROLLOUT_PREFIX + "interval"
ROLLOUT_HEALTH_ANNOTATION = ROLLOUT_PREFIX + "health"
ROLLOUT_ROLLBACK_ANNOTATION = ROLLOUT_PREFIX + "rollback"
ROLLOUT_ABORT_ANNOTATION = ROLLOUT_PREFIX + "abort"
ROLLOUT_STATE_ANNOTATION = ROLLOUT_PREFIX + "state"

# Foreign annotations this controller reads (reference pkg/apis/type.go:11-12).
AWS_LOAD_BALANCER_TYPE_ANNOTATION = "service.beta.kubernetes.io/aws-load-balancer-type"
INGRESS_CLASS_ANNOTATION = "kubernetes.io/ingress.class"

# ALB listen-ports annotation honored by the listener diff
# (reference pkg/cloudprovider/aws/global_accelerator.go:526).
ALB_LISTEN_PORTS_ANNOTATION = "alb.ingress.kubernetes.io/listen-ports"
