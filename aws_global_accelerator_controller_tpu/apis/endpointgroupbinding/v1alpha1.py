"""EndpointGroupBinding v1alpha1 types.

Mirrors reference pkg/apis/endpointgroupbinding/v1alpha1/types.go:16-70:
spec{endpointGroupArn required, clientIPPreservation default false,
weight nullable, serviceRef/ingressRef} and
status{endpointIds[], observedGeneration}.  Dict round-tripping uses the
same camelCase JSON shape as the Go types so admission payloads and
manifests interoperate.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ...kube.objects import KubeObject, ObjectMeta

GROUP = "operator.h3poteto.dev"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "EndpointGroupBinding"
PLURAL = "endpointgroupbindings"


@dataclass(slots=True)
class ServiceReference:
    name: str = ""


@dataclass(slots=True)
class IngressReference:
    name: str = ""


@dataclass(slots=True)
class EndpointGroupBindingSpec:
    endpoint_group_arn: str = ""
    client_ip_preservation: bool = False
    weight: Optional[int] = None
    service_ref: Optional[ServiceReference] = None
    ingress_ref: Optional[IngressReference] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "endpointGroupArn": self.endpoint_group_arn,
            "clientIPPreservation": self.client_ip_preservation,
        }
        if self.weight is not None:
            d["weight"] = self.weight
        if self.service_ref is not None:
            d["serviceRef"] = {"name": self.service_ref.name}
        if self.ingress_ref is not None:
            d["ingressRef"] = {"name": self.ingress_ref.name}
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EndpointGroupBindingSpec":
        svc = d.get("serviceRef")
        ing = d.get("ingressRef")
        weight = d.get("weight")
        return cls(
            endpoint_group_arn=d.get("endpointGroupArn", ""),
            client_ip_preservation=bool(d.get("clientIPPreservation", False)),
            weight=int(weight) if weight is not None else None,
            service_ref=ServiceReference(name=svc.get("name", "")) if svc else None,
            ingress_ref=IngressReference(name=ing.get("name", "")) if ing else None,
        )


@dataclass(slots=True)
class EndpointGroupBindingStatus:
    endpoint_ids: List[str] = field(default_factory=list)
    observed_generation: int = 0
    # durable safe-rollout state (rollout/machine.py RolloutState
    # serialized dict: phase, step, stepStartedAt, fencing token, from/
    # to weight vectors, rollback reason).  Lives in STATUS — never
    # process memory — so a crash, leader handoff or shard rebalance
    # mid-ramp resumes from the persisted step instead of re-snapping.
    # Kept as the raw camelCase dict so round-tripping matches the
    # wire shape byte-for-byte; rollout/ owns the typed view.
    rollout: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "endpointIds": list(self.endpoint_ids),
            "observedGeneration": self.observed_generation,
        }
        if self.rollout is not None:
            d["rollout"] = dict(self.rollout)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EndpointGroupBindingStatus":
        rollout = d.get("rollout")
        return cls(
            endpoint_ids=list(d.get("endpointIds") or []),
            observed_generation=int(d.get("observedGeneration", 0)),
            rollout=dict(rollout) if rollout else None,
        )


@dataclass(slots=True)
class EndpointGroupBinding(KubeObject):
    kind = KIND
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: EndpointGroupBindingSpec = field(default_factory=EndpointGroupBindingSpec)
    status: EndpointGroupBindingStatus = field(
        default_factory=EndpointGroupBindingStatus)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": KIND,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EndpointGroupBinding":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=EndpointGroupBindingSpec.from_dict(d.get("spec") or {}),
            status=EndpointGroupBindingStatus.from_dict(d.get("status") or {}),
        )


@dataclass(slots=True)
class EndpointGroupBindingList:
    """List kind (reference types.go:62-70)."""
    items: List[EndpointGroupBinding] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": f"{KIND}List",
            "items": [i.to_dict() for i in self.items],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EndpointGroupBindingList":
        return cls(items=[EndpointGroupBinding.from_dict(i)
                          for i in d.get("items") or []])
