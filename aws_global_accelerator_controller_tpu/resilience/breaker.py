"""Per-region circuit breaker + adaptive (AIMD) throttle token bucket.

Circuit breaker state machine (docs/resilience.md has the diagram):

    CLOSED --(failure rate >= threshold over window,
              with >= min_calls volume)--> OPEN
    OPEN   --(open_seconds elapsed)-----> HALF_OPEN
    HALF_OPEN --(probe succeeds)--------> CLOSED
    HALF_OPEN --(probe fails)-----------> OPEN (timer restarts)

While OPEN, ``allow()`` raises :class:`CircuitOpenError` carrying the
remaining open time as ``retry_after`` — callers fail fast instead of
queueing onto a region that is actively browning out, and the
reconcile loop parks the key for exactly that long.  Only throttle and
transient outcomes count as failures: a NotFound or a validation error
is the service answering correctly, so the wrapper records it as a
success (breaker health is about the REGION, not the request).

``AdaptiveTokenBucket`` is the client-side send-rate governor: calls
take a token (going into bounded debt = queueing delay when empty),
the refill rate scales with an adaptive capacity that HALVES on every
throttle response and recovers by a fixed step per success — AIMD, the
same control law TCP uses for the same reason (many independent
clients must converge on a fair share of an unknown limit without
coordinating).

Both classes compute under a tracked lock and NEVER sleep or call out
while holding it (lint rule L102); waiting happens in the wrapper,
outside every lock.
"""
from __future__ import annotations

from collections import deque
from typing import Optional

from .. import metrics
from ..autotune import knobs as knobcat
from ..autotune import targets as tune_targets
from ..simulation import clock as simclock
from ..analysis import locks
from ..errors import AWSAPIError

STATE_CLOSED = "closed"
STATE_HALF_OPEN = "half_open"
STATE_OPEN = "open"

# Gauge encoding for circuit_state{region}: closed < half-open < open,
# so an operator's max() over time shows the worst state reached.
STATE_VALUES = {STATE_CLOSED: 0.0, STATE_HALF_OPEN: 1.0, STATE_OPEN: 2.0}


class CircuitOpenError(AWSAPIError):
    """The region's circuit is open: fail fast, retry after the probe
    window."""

    def __init__(self, region: str, retry_after: float):
        super().__init__(
            "CircuitOpen",
            f"circuit for region {region!r} is open; "
            f"retry after {retry_after:.2f}s")
        self.region = region
        self.retry_after = retry_after


class CircuitBreaker:
    def __init__(self, region: str = "global",
                 window: float = knobcat.BREAKER_WINDOW,
                 min_calls: int = 10, failure_threshold: float = 0.5,
                 open_seconds: float = 5.0, half_open_probes: int = 1,
                 registry: "Optional[metrics.Registry]" = None,
                 clock=simclock.monotonic):
        self.region = region
        self._clock = clock
        self.window = window  # guarded-by: self._lock
        self.min_calls = min_calls
        self.failure_threshold = failure_threshold
        self.open_seconds = open_seconds
        self.half_open_probes = half_open_probes
        self._registry = registry
        self._lock = locks.make_lock(f"circuit-breaker-{region}")
        self._events: "deque[tuple[float, bool]]" = deque()  # guarded-by: self._lock
        self._state = STATE_CLOSED  # guarded-by: self._lock
        self._opened_until = 0.0  # guarded-by: self._lock
        self._probes_inflight = 0  # guarded-by: self._lock
        # feedback-tunable target (autotune/): the engine lengthens a
        # flapping breaker's window live via set_window
        tune_targets.note_breaker(self)

    def set_window(self, window: float) -> None:
        """Retune the failure-rate observation window live (the
        autotune registry's apply surface).  Takes effect at the next
        record/allow consult; recorded events keep their stamps, so a
        longer window immediately sees more history."""
        with self._lock:
            self.window = window

    # -- state ----------------------------------------------------------

    def state(self, now: Optional[float] = None) -> str:
        now = self._clock() if now is None else now
        with self._lock:
            self._refresh_locked(now)
            return self._state

    def state_value(self) -> float:
        """Numeric encoding for the circuit_state gauge."""
        return STATE_VALUES[self.state()]

    def _refresh_locked(self, now: float) -> None:
        if self._state == STATE_OPEN and now >= self._opened_until:
            self._transition_locked(STATE_HALF_OPEN)
            self._probes_inflight = 0

    def _transition_locked(self, to: str) -> None:
        if self._state == to:
            return
        self._state = to
        metrics.record_circuit_transition(self.region, to,
                                          registry=self._registry)
        if to == STATE_OPEN:
            # the region was failing hard enough to trip the breaker:
            # fingerprints recorded through that window proved nothing
            # — drop them all so the next resync re-verifies (lazy
            # import: the reconcile package is a consumer of this
            # layer, not a dependency)
            from ..reconcile.fingerprint import invalidate_all_caches
            invalidate_all_caches(f"circuit_open:{self.region}")
            # ...and freeze the flight recorder's black box while the
            # spans/chaos decisions that tripped it are still in the
            # rings.  On a DETACHED thread: this method runs under the
            # breaker lock that every call in the region serializes
            # through, and the dump does disk I/O — blocking here
            # would stall all workers at exactly the failing moment
            # (the recorder is debounced + no-op unarmed, so thread
            # churn is bounded by the cooldown)
            import threading as _threading

            from .. import flight
            _threading.Thread(
                target=flight.trigger,
                args=(flight.TRIGGER_CIRCUIT_OPEN, self.region),
                daemon=True, name="flight-dump").start()

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.window
        events = self._events
        while events and events[0][0] < horizon:
            events.popleft()

    # -- call gating ----------------------------------------------------

    def check_open(self, now: Optional[float] = None) -> None:
        """Fail fast without claiming a half-open probe slot — the
        cheap pre-gate callers run BEFORE paying any per-call cost
        (token reserve, pacing sleep).  Fully OPEN raises; HALF_OPEN
        with every probe slot already taken raises too (those callers
        would only lose at ``allow()`` after paying the pacing debt);
        CLOSED — and HALF_OPEN with a free slot — pass, and ``allow()``
        still decides actual probe admission."""
        now = self._clock() if now is None else now
        with self._lock:
            self._refresh_locked(now)
            if self._state == STATE_OPEN:
                raise CircuitOpenError(self.region,
                                       max(0.05, self._opened_until - now))
            if (self._state == STATE_HALF_OPEN
                    and self._probes_inflight >= self.half_open_probes):
                raise CircuitOpenError(self.region,
                                       max(0.05, self.open_seconds / 4))

    def allow(self, now: Optional[float] = None) -> None:
        """Admit one call or raise CircuitOpenError."""
        now = self._clock() if now is None else now
        with self._lock:
            self._refresh_locked(now)
            if self._state == STATE_CLOSED:
                return
            if self._state == STATE_HALF_OPEN:
                if self._probes_inflight < self.half_open_probes:
                    self._probes_inflight += 1
                    return
                # probe slots taken: everyone else keeps failing fast
                # for a fraction of the window while the probe decides
                raise CircuitOpenError(self.region,
                                       max(0.05, self.open_seconds / 4))
            raise CircuitOpenError(self.region,
                                   max(0.05, self._opened_until - now))

    def record_success(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            self._refresh_locked(now)
            if self._state == STATE_HALF_OPEN:
                # the probe came back: the region recovered
                self._transition_locked(STATE_CLOSED)
                self._events.clear()
                return
            if self._state == STATE_CLOSED:
                self._events.append((now, True))
                self._prune_locked(now)

    def record_failure(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            self._refresh_locked(now)
            if self._state == STATE_HALF_OPEN:
                self._open_locked(now)   # the probe failed: back to open
                return
            if self._state != STATE_CLOSED:
                return
            self._events.append((now, False))
            self._prune_locked(now)
            total = len(self._events)
            if total < self.min_calls:
                return
            failures = sum(1 for _, ok in self._events if not ok)
            if failures / total >= self.failure_threshold:
                self._open_locked(now)

    def _open_locked(self, now: float) -> None:
        self._transition_locked(STATE_OPEN)
        self._opened_until = now + self.open_seconds
        self._events.clear()


class AdaptiveTokenBucket:
    """Token bucket whose capacity adapts to throttle feedback (AIMD:
    multiplicative decrease on throttle, additive increase on
    success).  ``reserve()`` always claims a token — when the bucket is
    in debt the caller is told how long to sleep first, which paces
    admission at the effective refill rate instead of erroring."""

    def __init__(self, capacity: float = 500.0,
                 refill_rate: float = 1000.0, min_capacity: float = 5.0,
                 shrink_factor: float = 0.5, recover_step: float = 1.0,
                 region: str = "global", clock=simclock.monotonic):
        self._clock = clock
        self.max_capacity = float(capacity)
        self.refill_rate = float(refill_rate)
        self.min_capacity = float(min_capacity)
        self.shrink_factor = float(shrink_factor)
        self.recover_step = float(recover_step)
        self.region = region
        self._lock = locks.make_lock(f"throttle-bucket-{region}")
        self._capacity = self.max_capacity
        self._tokens = self.max_capacity
        self._at = self._clock()

    def _effective_rate_locked(self) -> float:
        # a shrunken bucket refills proportionally slower: capacity is
        # the adaptive estimate of what the service will bear
        return max(1e-9,
                   self.refill_rate * (self._capacity / self.max_capacity))

    def _refill_locked(self, now: float) -> None:
        dt = max(0.0, now - self._at)
        self._at = now
        self._tokens = min(self._capacity,
                           self._tokens + dt * self._effective_rate_locked())

    def reserve(self, now: Optional[float] = None) -> float:
        """Claim one token; returns seconds the caller must sleep
        before issuing the call (0.0 when a token was available)."""
        now = self._clock() if now is None else now
        with self._lock:
            self._refill_locked(now)
            self._tokens -= 1.0
            if self._tokens >= 0.0:
                return 0.0
            return -self._tokens / self._effective_rate_locked()

    def on_throttle(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            self._refill_locked(now)
            self._capacity = max(self.min_capacity,
                                 self._capacity * self.shrink_factor)
            self._tokens = min(self._tokens, self._capacity)

    def on_success(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            self._refill_locked(now)
            self._capacity = min(self.max_capacity,
                                 self._capacity + self.recover_step)

    def level(self) -> float:
        """Current token count (the throttle_tokens gauge); may be
        negative while callers are queued on debt."""
        with self._lock:
            self._refill_locked(self._clock())
            return self._tokens

    def capacity(self) -> float:
        with self._lock:
            return self._capacity
