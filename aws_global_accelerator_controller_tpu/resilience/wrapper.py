"""ResilientAPIs: the transparent policy wrapper around an AWSAPIs
bundle.

One per region (the factory builds it in ``provider_for``), composing
the whole subsystem around every service call:

    breaker.allow -> bucket.reserve (pace) -> inner call
        -> classify -> {success | throttle | transient | terminal}
        -> breaker/bucket feedback -> backoff-retry or raise

Only the method names of the three API interfaces are wrapped; any
other attribute (the fakes' ``register_load_balancer``/
``create_hosted_zone`` seeding helpers) passes straight through, so a
wrapped fake is drop-in for tests.  All waiting happens here, outside
every lock (L102): the breaker and bucket only compute.

Failure surface to callers:

- terminal / not-found errors raise unchanged on the first attempt;
- throttle / transient errors retry in-call under the policy, then
  raise :class:`RetryBudgetExceededError` (attempt budget) or
  :class:`DeadlineExceededError` (wall clock) with the original error
  as ``__cause__`` and a ``retry_after`` park hint;
- an open circuit raises :class:`CircuitOpenError` immediately.

All three hint errors are AWSAPIError subclasses so typed provider
call sites still catch them, but they are NOT answers about the
resource: ``except AWSAPIError`` handlers that infer state from a
failure (the provider's deleted-out-of-band rescue paths) must
re-raise when ``errors.retry_after_hint(e) > 0`` — a brownout says
nothing about whether the accelerator exists.  The same hint is how
the reconcile loop parks the key instead of hot-requeuing.
"""
from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional

from .. import metrics
from ..autotune import knobs as knobcat
from ..simulation import clock as simclock
from ..tracing import default_tracer, stamp_ambient
from .breaker import AdaptiveTokenBucket, CircuitBreaker
from .classify import ErrorClass, classify
from .fence import active_write_fences
from .retry import DeadlineExceededError, RetryBudgetExceededError, RetryPolicy

# The wrapped call surface per service attribute (the abstract methods
# of api.GlobalAcceleratorAPI / ELBv2API / Route53API — kept as literal
# name sets so this package never imports the cloudprovider layer,
# which imports it back through the factory).
GA_METHODS = frozenset({
    "list_accelerators", "describe_accelerator", "list_tags_for_resource",
    "create_accelerator", "update_accelerator", "tag_resource",
    "delete_accelerator", "list_listeners", "create_listener",
    "update_listener", "delete_listener", "list_endpoint_groups",
    "describe_endpoint_group", "create_endpoint_group",
    "update_endpoint_group", "add_endpoints", "remove_endpoints",
    "delete_endpoint_group",
})
ELB_METHODS = frozenset({"describe_load_balancers"})

# GA mutations NOT on the coalesced write surface (accelerator /
# listener / endpoint-group lifecycle — issued directly through
# ``apis``, one call each).  On success the wrapper attributes them to
# drift repair when a sweep-origin sync is on the calling thread
# (reconcile/fingerprint.py); the coalesced surface is deliberately
# EXCLUDED here — its payloads are counted per change at the
# coalescer's submit-await, on the submitter's own thread, so a flush
# led by the sweep thread is never double-counted.
UNCOALESCED_MUTATIONS = frozenset({
    "create_accelerator", "update_accelerator", "tag_resource",
    "delete_accelerator", "create_listener", "update_listener",
    "delete_listener", "create_endpoint_group", "delete_endpoint_group",
})
ROUTE53_METHODS = frozenset({
    "list_hosted_zones", "list_hosted_zones_by_name",
    "list_resource_record_sets", "change_resource_record_sets",
    # the write coalescer's flush (batcher.py): ONE wrapped call per
    # drained batch, so a whole cohort shares one retry budget /
    # breaker verdict — per-waiter attribution happens above this
    # layer (flush-level classify, waiter-level demux)
    "change_resource_record_sets_batch",
})

# The regional aggregation point (topology/aggregator.py): one wrapped
# call per region batch, so a whole region's cohort shares one
# retry/breaker/bucket verdict — and each REGION'S wrapper carries its
# own breaker, the per-region independence the partition chaos e2e
# asserts.  The digest read is the sweep tier's one-exchange-per-wave.
GATEWAY_METHODS = frozenset({"apply_region_batch", "get_region_digest"})

# Every method that mutates cloud state — the lifecycle fence
# (resilience/fence.py) is consulted for these before each attempt, so
# a stopping or deposed-leader process cannot land a queued mutation
# concurrently with its successor's writes (lint rule L108 keeps this
# gate in place).  Reads stay unfenced: a draining process may still
# observe the world.  ``apply_region_batch`` is fenced too — and the
# aggregator pushes every contribution's shard fence into the
# per-attempt write TLS, so a seal landing mid-retry rejects exactly
# the sealed shard's share on the next attempt.
MUTATION_METHODS = UNCOALESCED_MUTATIONS | frozenset({
    "update_endpoint_group", "add_endpoints", "remove_endpoints",
    "change_resource_record_sets", "change_resource_record_sets_batch",
    "apply_region_batch",
})


@dataclass(frozen=True)
class ResilienceConfig:
    """Deployment-level knobs for one region's resilient call layer.
    Defaults are production-scale; FakeCloudFactory substitutes a fast
    permissive profile so tests and benches stay sub-second."""

    enabled: bool = True
    # retry
    max_attempts: int = 4
    base_delay: float = 0.2
    max_delay: float = 5.0
    deadline: float = 30.0
    # circuit breaker (window default owned by the knob catalog —
    # autotune/knobs.py, lint rule L117)
    breaker_window: float = knobcat.BREAKER_WINDOW
    breaker_min_calls: int = 10
    breaker_failure_threshold: float = 0.5
    breaker_open_seconds: float = 5.0
    half_open_probes: int = 1
    # adaptive token bucket
    bucket_capacity: float = 500.0
    bucket_refill: float = 1000.0
    bucket_min_capacity: float = 5.0
    bucket_shrink: float = 0.5
    bucket_recover: float = 1.0
    # deterministic jitter for tests; None seeds from the OS
    seed: Optional[int] = None

    def retry_policy(self) -> RetryPolicy:
        return RetryPolicy(max_attempts=self.max_attempts,
                           base_delay=self.base_delay,
                           max_delay=self.max_delay,
                           deadline=self.deadline)


# the fast profile the fake factory uses: real backoff shapes at
# 100x speed, breaker thresholds high enough that the one-shot fault
# injections of the ordinary e2e suites never trip it
FAKE_CLOUD_CONFIG = ResilienceConfig(
    max_attempts=4, base_delay=0.002, max_delay=0.05, deadline=5.0,
    breaker_window=knobcat.FAKE_BREAKER_WINDOW, breaker_min_calls=50,
    breaker_failure_threshold=0.9, breaker_open_seconds=0.25,
    bucket_capacity=1e6, bucket_refill=1e6, bucket_min_capacity=100.0,
    bucket_recover=100.0)


class _ResilientService:
    """Per-service proxy: wrapped methods go through the shared policy
    engine, everything else passes through to the inner service."""

    def __init__(self, inner, method_names, engine: "ResilientAPIs"):
        self._inner = inner
        self._methods = method_names
        self._engine = engine

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name not in self._methods or not callable(attr):
            return attr

        def call(*args, **kwargs):
            return self._engine.invoke(name, attr, args, kwargs)

        call.__name__ = name
        # cache the bound wrapper: __getattr__ only fires on misses
        object.__setattr__(self, name, call)
        return call


class ResilientAPIs:
    """Drop-in AWSAPIs bundle enforcing the resilience policy.

    Shares ONE breaker + token bucket across the region's three
    services: a regional brownout rarely respects service boundaries,
    and the throttle budget the bucket estimates is per-principal, not
    per-API.
    """

    def __init__(self, inner, region: str = "global",
                 config: Optional[ResilienceConfig] = None,
                 registry: "Optional[metrics.Registry]" = None,
                 clock=simclock.monotonic, sleep=simclock.sleep):
        cfg = config or ResilienceConfig()
        self.inner = inner
        self.region = region
        self.config = cfg
        self.policy = cfg.retry_policy()
        self._registry = registry
        self._clock = clock
        self._sleep = sleep
        self._rng = random.Random(cfg.seed)
        # lifecycle fence (resilience/fence.py), installed by
        # CloudFactory.set_fence; None = unfenced (bare test bundles)
        self.fence = None
        # the breaker/bucket share this wrapper's clock: their gauge
        # callbacks (state_value/level) run on the metrics scrape
        # thread with no explicit `now`, and a real-clock default
        # there would corrupt fake-clock state in tests
        self.breaker = CircuitBreaker(
            region=region, window=cfg.breaker_window,
            min_calls=cfg.breaker_min_calls,
            failure_threshold=cfg.breaker_failure_threshold,
            open_seconds=cfg.breaker_open_seconds,
            half_open_probes=cfg.half_open_probes, registry=registry,
            clock=clock)
        self.bucket = AdaptiveTokenBucket(
            capacity=cfg.bucket_capacity, refill_rate=cfg.bucket_refill,
            min_capacity=cfg.bucket_min_capacity,
            shrink_factor=cfg.bucket_shrink,
            recover_step=cfg.bucket_recover, region=region, clock=clock)
        self.elb = _ResilientService(inner.elb, ELB_METHODS, self)
        self.ga = _ResilientService(inner.ga, GA_METHODS, self)
        self.route53 = _ResilientService(inner.route53, ROUTE53_METHODS,
                                         self)
        # the optional regional aggregation point (api.RegionGatewayAPI)
        # rides the same policy engine; bundles without one stay flat
        gateway = getattr(inner, "gateway", None)
        self.gateway = (_ResilientService(gateway, GATEWAY_METHODS, self)
                        if gateway is not None else None)
        metrics.watch_circuit_state(region, self.breaker.state_value,
                                    registry=registry)
        metrics.watch_throttle_tokens(region, self.bucket.level,
                                      registry=registry)

    # ------------------------------------------------------------------

    def invoke(self, op: str, fn, args, kwargs):
        """One policy-governed call: breaker gate, bucket pacing,
        classify-and-retry under the attempt budget and deadline —
        under an ``aws.<op>`` span covering every attempt, whose id is
        stamped into the ambient trace context (tracing.py): the trace
        an artifact carries names the exact provider calls that served
        it, and chaos injections inside the call annotate this span."""
        with default_tracer.span(f"aws.{op}", region=self.region) as sp:
            stamp_ambient(sp.span_id, "provider")
            policy = self.policy
            deadline = self._clock() + policy.deadline
            prev_delay = policy.base_delay
            attempt = 1
            while True:
                # lifecycle fence first (L108): a mutation from a stopping
                # or deposed process must not reach the wire — checked per
                # attempt, so a retry sleeping across a lease loss is
                # rejected when it wakes, not issued with dead authority.
                # The thread's pushed write fences (a routed dispatch's
                # shard fence, a per-shard flush — resilience/fence.py
                # push_write_fence) gate at the same per-attempt point, so
                # a SHARD lease lost mid-retry rejects identically.
                if op in MUTATION_METHODS:
                    if self.fence is not None:
                        self.fence.check("wrapper")
                    for extra_fence in active_write_fences():
                        extra_fence.check("wrapper")
                # cheap open-circuit pre-gate first (claims nothing), so a
                # fully open circuit costs no token and no pacing sleep —
                # otherwise failing-fast workers would drain the bucket
                # into debt with zero traffic reaching the service.  Then
                # pace BEFORE the probe-claiming allow(): a half-open
                # probe slot claimed by allow() must always reach the
                # inner call, so nothing that can raise may sit between
                # allow() and the try block.
                self.breaker.check_open(self._clock())
                self._pace(op, deadline)
                self.breaker.allow(self._clock())
                try:
                    result = fn(*args, **kwargs)
                except Exception as e:
                    cls = classify(e)
                    if cls is ErrorClass.THROTTLE:
                        now = self._clock()
                        self.bucket.on_throttle(now)
                        self.breaker.record_failure(now)
                    elif cls is ErrorClass.TRANSIENT:
                        self.breaker.record_failure(self._clock())
                    else:
                        # the service answered (not-found / validation):
                        # the region is healthy, the request is just wrong
                        self.breaker.record_success(self._clock())
                        raise
                    if attempt >= policy.max_attempts:
                        raise RetryBudgetExceededError(
                            op, attempt,
                            policy.requeue_hint(prev_delay)) from e
                    delay = policy.next_delay(self._rng, prev_delay)
                    prev_delay = delay
                    if self._clock() + delay > deadline:
                        metrics.record_aws_call_deadline_exceeded(
                            op, registry=self._registry)
                        raise DeadlineExceededError(
                            op, policy.deadline,
                            policy.requeue_hint(prev_delay)) from e
                    metrics.record_aws_call_retry(op,
                                                  registry=self._registry)
                    attempt += 1
                    self._sleep(delay)
                else:
                    now = self._clock()
                    self.breaker.record_success(now)
                    self.bucket.on_success(now)
                    if op in UNCOALESCED_MUTATIONS:
                        # lazy import: the reconcile package is a consumer
                        # of this layer, not a dependency
                        from ..reconcile.fingerprint import (
                            note_provider_mutation,
                        )
                        note_provider_mutation()
                    sp.attributes["attempts"] = attempt
                    return result

    def _pace(self, op: str, deadline: float) -> None:
        """Client-side throttle pacing: sleep off the token debt, but
        never past the call deadline."""
        wait = self.bucket.reserve(self._clock())
        if wait <= 0.0:
            return
        if self._clock() + wait > deadline:
            metrics.record_aws_call_deadline_exceeded(
                op, registry=self._registry)
            raise DeadlineExceededError(
                op, self.policy.deadline,
                self.policy.requeue_hint(wait))
        self._sleep(wait)
