"""Retry policy: capped decorrelated-jitter backoff under a deadline.

The backoff schedule is the "decorrelated jitter" variant (each sleep
drawn uniformly from [base, 3 * previous sleep], capped) — under a
throttling storm N clients on plain exponential backoff re-collide on
every retry tier; decorrelation spreads the herd across the whole
window.  Two independent budgets bound every wrapped call:

- ``max_attempts``: total tries (first call included).  Exhaustion
  raises :class:`RetryBudgetExceededError`.
- ``deadline``: wall-clock seconds for the whole call including
  backoff sleeps.  A sleep that would cross it raises
  :class:`DeadlineExceededError` instead of parking the worker past
  its useful life (NCCL-style bounded-timeout semantics, PAPERS.md).

Both errors carry ``retry_after`` — the reconcile loop parks the key
with ``Forget`` + ``AddAfter(retry_after)`` instead of hot-requeuing
(reconcile.py error dispatch via ``errors.retry_after_hint``).
"""
from __future__ import annotations

import random
from dataclasses import dataclass

from ..errors import AWSAPIError


class RetryBudgetExceededError(AWSAPIError):
    """All in-call attempts failed on retryable errors; the caller
    should requeue after ``retry_after`` rather than retry inline."""

    def __init__(self, op: str, attempts: int, retry_after: float):
        super().__init__(
            "RetryBudgetExceeded",
            f"{op}: {attempts} attempts exhausted; "
            f"retry after {retry_after:.2f}s")
        self.op = op
        self.attempts = attempts
        self.retry_after = retry_after


class DeadlineExceededError(AWSAPIError):
    """The call (including backoff) would outlive its deadline."""

    def __init__(self, op: str, deadline: float, retry_after: float):
        super().__init__(
            "DeadlineExceeded",
            f"{op}: deadline of {deadline:.2f}s exceeded; "
            f"retry after {retry_after:.2f}s")
        self.op = op
        self.deadline = deadline
        self.retry_after = retry_after


@dataclass(frozen=True)
class RetryPolicy:
    """Per-call retry parameters (wrapper.ResilienceConfig carries the
    deployment-level knobs; the fake factory substitutes fast ones)."""

    max_attempts: int = 4
    base_delay: float = 0.2
    max_delay: float = 5.0
    deadline: float = 30.0

    def next_delay(self, rng: random.Random, prev: float) -> float:
        """Decorrelated jitter: uniform in [base, 3*prev], capped."""
        lo = self.base_delay
        hi = max(lo, min(self.max_delay, 3.0 * max(prev, lo)))
        return rng.uniform(lo, hi)

    def requeue_hint(self, prev: float) -> float:
        """Suggested park time after a budget/deadline failure: one
        more (capped) backoff step — long enough to let a brownout
        clear, short enough that convergence resumes promptly."""
        return min(self.max_delay, max(self.base_delay, 2.0 * prev))
