"""Lifecycle mutation fence: the write-side gate for ordered shutdown
and lease-fenced leadership.

A controller process may stop issuing mutations for two reasons — it
is shutting down, or it lost the leadership lease — and in both cases
the danger is the same: a mutation QUEUED while the process had
authority landing AFTER it no longer does, concurrently with a
successor's writes (the split-brain double-create ROADMAP item 1's
shard handoff forbids).  The fence is the single object both paths
trip, consulted at the two write chokepoints:

- the :class:`~..cloudprovider.aws.batcher.MutationCoalescer`'s
  submit surface — a tripped fence rejects NEW mutation intents;
- the :class:`~.wrapper.ResilientAPIs` call gate — a SEALED fence
  rejects every mutation call, including a coalesced flush.

Two stages, matching the ordered-stop contract (ARCHITECTURE.md
"Lifecycle & fencing"):

``trip(reason)``
    No new intents.  In-flight cohorts may still FLUSH — the
    coalescer's drain wraps its flushes in :meth:`flush_pass`, the
    thread-scoped permit that lets already-accepted work complete so
    every waiter is answered exactly once.
``seal(reason)``
    Nothing mutates, flushes included.  Shutdown seals after the drain
    deadline; lease loss seals IMMEDIATELY (a deposed leader has no
    authority left to flush under — its cohorts fail fast with
    :class:`FencedError` and the new leader reconverges them).

The fencing token (``token``) is the leadership epoch: the elector
arms the fence with the lease's ``lease_transitions`` at acquire time,
so re-acquiring after a loss re-arms with a strictly larger token —
the monotone ordering a cross-process observer (or the leader-handoff
e2e) uses to prove writes from two terms never interleave.

:class:`FencedError` is a :class:`~..errors.NoRetryError`: a fenced
sync must be dropped, not requeued — the successor (or the next
leadership term) owns the key now.
"""
from __future__ import annotations

import logging
import threading
from contextlib import contextmanager

from .. import metrics
from ..errors import NoRetryError

logger = logging.getLogger(__name__)


class FencedError(NoRetryError):
    """A mutation was rejected by the lifecycle fence.  No-retry by
    type: requeueing would just re-reject (this process's authority is
    gone) while the successor converges the key."""

    def __init__(self, reason: str, token: int, sealed: bool):
        stage = "sealed" if sealed else "fenced"
        super().__init__(
            f"mutation rejected: fence {stage} ({reason}; token {token})")
        self.reason = reason
        self.token = token
        self.sealed = sealed


# thread-scoped flush permit (see MutationFence.flush_pass)
_pass_tls = threading.local()


@contextmanager
def flush_permit():
    """The drain-window permit as a bare context manager: inside the
    block, THIS thread's fence checks pass a TRIPPED (but not sealed)
    fence.  The permit depth is module-global — one permit covers
    every fence instance on the thread — which is what lets a layer
    holding many callers' fences (the region aggregator,
    topology/aggregator.py) check each under the same drain-window
    semantics the coalescer's own :meth:`MutationFence.flush_pass`
    grants."""
    _pass_tls.depth = getattr(_pass_tls, "depth", 0) + 1
    try:
        yield
    finally:
        _pass_tls.depth -= 1

# thread-scoped EXTRA write gates: fences pushed around a routed
# dispatch (sharding/shardset.py ShardSet.guard) or a per-shard
# coalescer flush, consulted by ResilientAPIs.invoke per attempt in
# addition to its own process fence — so a shard lease lost while a
# retry sleeps rejects the write on wake, exactly like the process
# fence does, without the wrapper knowing anything about shards.
_write_tls = threading.local()


@contextmanager
def push_write_fence(fence):
    """Arm ``fence`` as an additional per-attempt write gate for code
    running on this thread inside the block (re-entrant; None is a
    no-op so callers need no conditional)."""
    if fence is None:
        yield
        return
    stack = getattr(_write_tls, "stack", None)
    if stack is None:
        stack = _write_tls.stack = []
    stack.append(fence)
    try:
        yield
    finally:
        stack.pop()


def active_write_fences():
    """The fences pushed on this thread's stack (innermost last)."""
    return tuple(getattr(_write_tls, "stack", ()) or ())


class MutationFence:
    """One process-lifecycle fence per CloudFactory, wired into the
    factory's coalescer and every region's resilient wrapper at build
    time (factory.provider_for) and re-armed IN PLACE by the elector
    at each leadership term (arm)."""

    def __init__(self, token: int = 0, name: str = "process"):
        self.name = name
        self._lock = threading.Lock()
        self._token = token
        self._tripped = False
        self._sealed = False
        self._reason = ""

    # -- state ----------------------------------------------------------

    @property
    def token(self) -> int:
        with self._lock:
            return self._token

    @property
    def reason(self) -> str:
        with self._lock:
            return self._reason

    def is_tripped(self) -> bool:
        with self._lock:
            return self._tripped

    def is_sealed(self) -> bool:
        with self._lock:
            return self._sealed

    # -- transitions ----------------------------------------------------

    def arm(self, token: int) -> None:
        """(Re-)arm for a new leadership term with a strictly larger
        fencing token.  Only a fresh or tripped fence re-arms — the
        token must be monotone, or a stale term could masquerade as a
        new one."""
        with self._lock:
            if token <= self._token and (self._tripped or self._sealed):
                raise ValueError(
                    f"fence token must be monotone: have {self._token}, "
                    f"got {token}")
            self._token = max(self._token, token)
            self._tripped = False
            self._sealed = False
            self._reason = ""
        logger.info("fence %s armed (token %d)", self.name, token)

    def trip(self, reason: str) -> bool:
        """Reject new mutation intents from now on; returns True when
        THIS call tripped it (idempotent)."""
        with self._lock:
            if self._tripped:
                return False
            self._tripped = True
            self._reason = reason
        logger.info("fence %s tripped: %s", self.name, reason)
        return True

    def seal(self, reason: str) -> bool:
        """Reject every mutation, flushes included (implies trip)."""
        with self._lock:
            if self._sealed:
                return False
            self._tripped = True
            self._sealed = True
            if not self._reason:
                self._reason = reason
        logger.info("fence %s sealed: %s", self.name, reason)
        return True

    # -- the gates ------------------------------------------------------

    def check(self, surface: str) -> None:
        """Raise :class:`FencedError` when mutations from ``surface``
        are no longer allowed.  Called on the write hot path: one
        uncontended lock acquisition when the fence is open."""
        with self._lock:
            sealed = self._sealed
            tripped = self._tripped
            token = self._token
            reason = self._reason
        if not tripped:
            return
        if not sealed and getattr(_pass_tls, "depth", 0) > 0:
            return      # drain window: an in-flight cohort flushing
        metrics.record_fenced_mutation(surface)
        raise FencedError(reason or "fence tripped", token, sealed)

    def flush_pass(self):
        """Thread-scoped permit for the drain window: a flush carrying
        already-accepted intents may pass a TRIPPED (but not sealed)
        fence, so every waiter that got in before the trip is answered
        exactly once.  (The permit itself is the module-level
        :func:`flush_permit` — depth is shared across fence instances
        on the thread.)"""
        return flush_permit()


class CompositeFence:
    """Several fences consulted as one — the per-shard coalescer's
    gate is CompositeFence(process fence, shard fence): the ordered
    shutdown trips the process fence, a shard-lease loss trips/seals
    that shard's, and either alone stops the cohort.  ``token`` is the
    shard fence's (the LAST member's): the per-term fencing token the
    handoff e2e orders writes by.  The flush-pass permit is
    thread-scoped and shared across every fence instance, so wrapping
    one member covers all."""

    def __init__(self, *fences):
        self._fences = tuple(f for f in fences if f is not None)
        if not self._fences:
            raise ValueError("CompositeFence needs at least one fence")

    @property
    def token(self) -> int:
        return self._fences[-1].token

    @property
    def reason(self) -> str:
        for fence in self._fences:
            if fence.reason:
                return fence.reason
        return ""

    def is_tripped(self) -> bool:
        return any(f.is_tripped() for f in self._fences)

    def is_sealed(self) -> bool:
        return any(f.is_sealed() for f in self._fences)

    def check(self, surface: str) -> None:
        for fence in self._fences:
            fence.check(surface)

    def flush_pass(self):
        return self._fences[0].flush_pass()
