"""Resilient AWS call layer (classify / retry / breaker / deadlines).

The reference controller leans entirely on workqueue requeue for fault
handling: every SDK call is a bare invocation and the only error
distinction is NoRetryError.  This package is the production-scale
answer (ROADMAP north star; the same transient-vs-terminal,
deadline-bounded taxonomy the fault-tolerant collective libraries in
PAPERS.md build for training jobs):

- ``classify``: AWSAPIError codes -> throttle / transient / terminal /
  not-found (errors.py holds the code tables; real.py maps boto codes
  into them).
- ``retry``: capped exponential backoff with decorrelated jitter, an
  overall attempt budget and a per-call wall-clock deadline.
- ``breaker``: per-region circuit breaker (closed -> open on failure
  rate -> half-open probe) plus an AIMD token bucket that shrinks on
  throttle responses and recovers on success.
- ``wrapper``: ``ResilientAPIs``, a transparent decorator around the
  ``AWSAPIs`` bundle — the factory wraps every provider's apis in one,
  so provider.py, singleflight and fleet sweeps all go through the
  policy without a call-site change (lint rule L105 keeps it that way).
- ``fence``: ``MutationFence``, the process-lifecycle write gate —
  ordered shutdown and lease loss trip it so a stopping or deposed
  process cannot issue mutations concurrently with its successor
  (lint rule L108 keeps the wrapper's fence consult in place).

Every retry, deadline miss, breaker transition and token level flows
into metrics.py (``aws_call_retries_total``,
``aws_call_deadline_exceeded_total``, ``circuit_state{region}``,
``throttle_tokens{region}``).  docs/resilience.md has the taxonomy
table and the breaker state machine.
"""
from .classify import ErrorClass, classify
from .retry import (
    DeadlineExceededError,
    RetryBudgetExceededError,
    RetryPolicy,
)
from .breaker import (
    AdaptiveTokenBucket,
    CircuitBreaker,
    CircuitOpenError,
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
)
from .fence import (
    CompositeFence,
    FencedError,
    MutationFence,
    active_write_fences,
    push_write_fence,
)
from .wrapper import ResilienceConfig, ResilientAPIs

__all__ = [
    "AdaptiveTokenBucket",
    "CircuitBreaker",
    "CircuitOpenError",
    "DeadlineExceededError",
    "ErrorClass",
    "CompositeFence",
    "FencedError",
    "MutationFence",
    "active_write_fences",
    "push_write_fence",
    "ResilienceConfig",
    "ResilientAPIs",
    "RetryBudgetExceededError",
    "RetryPolicy",
    "STATE_CLOSED",
    "STATE_HALF_OPEN",
    "STATE_OPEN",
    "classify",
]
