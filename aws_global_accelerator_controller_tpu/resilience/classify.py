"""Error classification: which failures deserve which response.

One function, one table: ``classify(err)`` maps any exception an AWS
call can raise into the four-way taxonomy the retry loop and circuit
breaker dispatch on.  The code tables live in errors.py (so real.py's
boto mapping and the fake's chaos engine share them); this module owns
the precedence rules:

1. ``NoRetryError`` anywhere in the explicit cause chain is TERMINAL —
   the reconcile engine's drop contract outranks everything.
2. An ``AWSAPIError`` classifies by its code: throttle codes ->
   THROTTLE, transient codes -> TRANSIENT, ``*NotFoundException`` (or
   the known suffix-less codes) -> NOT_FOUND, anything else TERMINAL.
   An explicit ``retryable`` verdict from the transport (boto marks
   5xx and connection resets retryable) overrides an unknown code.
3. OS-level transport errors (``ConnectionError``, ``TimeoutError``,
   ``socket``-class ``OSError``) are TRANSIENT: the request may never
   have reached the service.
4. Everything else — TypeError, KeyError, assertion failures — is
   TERMINAL: retrying a programming error just multiplies it.
"""
from __future__ import annotations

import enum

from ..errors import (
    AWSAPIError,
    NOT_FOUND_CODES,
    THROTTLE_CODES,
    TRANSIENT_CODES,
    is_no_retry,
)


class ErrorClass(enum.Enum):
    THROTTLE = "throttle"      # back off AND shrink the send rate
    TRANSIENT = "transient"    # back off and retry in-call
    TERMINAL = "terminal"      # raise now; requeue policy decides
    NOT_FOUND = "not_found"    # absence is an answer, not a fault


def _classify_code(err: AWSAPIError) -> ErrorClass:
    code = err.code or ""
    if code in THROTTLE_CODES:
        return ErrorClass.THROTTLE
    if code.endswith("NotFoundException") or code in NOT_FOUND_CODES:
        return ErrorClass.NOT_FOUND
    if code in TRANSIENT_CODES:
        return ErrorClass.TRANSIENT
    # unknown code: trust an explicit transport verdict, else terminal
    # (AWS 4xx client errors are not retryable; the reconcile loop's
    # rate-limited requeue still gets its level-triggered second look)
    if err.retryable:
        return ErrorClass.TRANSIENT
    return ErrorClass.TERMINAL


def classify(err: BaseException) -> ErrorClass:
    if is_no_retry(err):
        return ErrorClass.TERMINAL
    if isinstance(err, AWSAPIError):
        return _classify_code(err)
    if isinstance(err, (ConnectionError, TimeoutError)):
        return ErrorClass.TRANSIENT
    if isinstance(err, OSError):
        # socket/DNS-layer trouble reaching the endpoint
        return ErrorClass.TRANSIENT
    return ErrorClass.TERMINAL
