"""Signal handling (reference pkg/signals/signals.go:16-30).

SIGINT/SIGTERM set the returned stop event; a second signal exits with
code 1.  Registering twice raises, mirroring the reference's
close-of-closed-channel panic guard.
"""
from __future__ import annotations

import os
import signal
import threading

_registered = False


def setup_signal_handler() -> threading.Event:
    global _registered
    if _registered:
        raise RuntimeError("setup_signal_handler called twice")
    _registered = True

    stop = threading.Event()

    def handler(signum, frame):
        if stop.is_set():
            os._exit(1)  # second signal: exit directly
        stop.set()

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)
    return stop


class ScopedStopSignal:
    """Context-managed SIGINT/SIGTERM -> stop-event translation that
    RESTORES the previous handlers on exit — for bounded entry points
    (the train CLI) that may run several times in one process and must
    not permanently hijack the host's handlers (pytest's
    KeyboardInterrupt, an embedding application's own shutdown).  A
    second signal while stopping still hard-exits, like
    ``setup_signal_handler``.  Off the main thread (where signal
    registration is illegal) it degrades to a never-set event."""

    def __init__(self):
        self.stop = threading.Event()
        self._prev: "dict | None" = {}

    def __enter__(self) -> threading.Event:
        def handler(signum, frame):
            if self.stop.is_set():
                os._exit(1)
            self.stop.set()

        try:
            for sig in (signal.SIGINT, signal.SIGTERM):
                self._prev[sig] = signal.signal(sig, handler)
        except ValueError:  # not the main thread
            self._prev = None
        return self.stop

    def __exit__(self, *exc) -> None:
        if self._prev:
            for sig, prev in self._prev.items():
                signal.signal(sig, prev)
