"""The autotune engine: one background loop closing the
observability→scheduling feedback circle (ISSUE 15).

Each tick (clock-aware — virtual seconds under simulation) the engine
samples the signal reader, and either:

- **freezes**: any anomaly in the snapshot (non-finite value,
  regressed counter, implausible delta, stalled stream) snaps EVERY
  knob to its default and holds through the cooldown
  (``autotune_frozen_total{knob,reason}``).  A lying signal's worst
  case is the static plane — the chaos e2e's contract; or
- **steers**: runs every knob policy against the snapshot.  The
  policies map the signals the system already exports to the knob
  catalog:

  =====================  ==============================================
  knob                   policy (controllers.py law)
  =====================  ==============================================
  coalescer.linger       hill-climb on fold efficiency
                         (enqueued/flushes) while mutation traffic
                         flows, vetoed (retreat to default) when
                         interactive p99 breaches the budget — the
                         NCCL shape: pick the bandwidth protocol only
                         while the message flow justifies it
  coalescer.warm_gap     follows linger (one wave-detection constant)
  sweep.every            AIMD: observed drift repairs halve the period
                         (detect faster while drift is live); quiet
                         windows decay it back to the default
  queue.depth_watermark  AIMD: sheds while interactive p99 is healthy
                         raise the watermark (shedding was premature);
                         p99 breach with a deep backlog lowers it
  queue.age_watermark    same pressure pair, age-flavored
  queue.aging_horizon    p99 breach raises it (protect interactive);
                         starved background (p99 >> horizon) lowers it
  breaker.window         AIMD: breaker flapping (many transitions per
                         window) lengthens the window
  digest.exchange_every  AIMD: drift snaps it to 1 (exchange every
                         wave); sustained quiet stretches the cadence
  =====================  ==============================================

Every applied move is logged to a bounded decision log (virtual
timestamps) — the determinism suite replays it byte-identically, and
the adaptive-soak bench records the per-knob trajectory from the
registry into reconcile_history.jsonl.
"""
from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..simulation import clock as simclock
from .controllers import (
    AIMDController,
    HOLD,
    HillClimbController,
    LOWER,
    RAISE,
)
from .registry import TunableRegistry
from .signals import SignalReader, SignalSnapshot

logger = logging.getLogger(__name__)


@dataclass
class AutotuneConfig:
    """Engine opt-in + envelope.  Disabled by default: a plane without
    an engine is exactly the static plane (tests and benches that do
    not opt in see byte-identical behavior)."""

    enabled: bool = False
    # seconds between signal samples (virtual under simulation)
    interval: float = 1.0
    # interactive p99 budget: the latency the tuner must not trade
    # away for batching/fairness wins (the PR-7 SLO's order)
    p99_budget: float = 0.5
    # mutation intents per tick below which the write path reads idle
    min_activity: float = 8.0
    # seconds a freeze holds the knobs at default
    freeze_cooldown: float = 30.0
    # operator pins: knob name -> fixed value (never moved)
    pins: Dict[str, float] = field(default_factory=dict)
    # registry default overrides (the plane's actual static config —
    # the assembling manager seeds these from the factory/controller
    # configs so snap-to-default restores exactly the static plane)
    defaults: Dict[str, float] = field(default_factory=dict)


class AutotuneEngine:
    """Builds the registry + policies and runs the tick loop."""

    def __init__(self, config: AutotuneConfig,
                 reader: Optional[SignalReader] = None,
                 registry: Optional[TunableRegistry] = None):
        self.config = config
        self.reader = reader or SignalReader()
        self.registry = registry or TunableRegistry(
            defaults=config.defaults, pins=config.pins,
            freeze_cooldown=config.freeze_cooldown)
        self._decisions: deque = deque(maxlen=4096)  # guarded-by: internal
        self._thread: Optional[threading.Thread] = None
        self._policies = self._build_policies()

    # -- policies --------------------------------------------------------

    def _build_policies(self) -> List:
        cfg = self.config
        reg = self.registry

        def fold_efficiency(s: SignalSnapshot):
            # (intents, wire calls) this tick — the controller windows
            # the volume-weighted ratio (intents per call = the
            # batching win the linger buys); None while the write
            # path is idle
            if s.delta("enqueued") < cfg.min_activity:
                return None
            return (s.delta("enqueued"),
                    max(1.0, s.delta("flushes")))

        def p99_healthy(s: SignalSnapshot) -> bool:
            return (s.interactive_p99 is None
                    or s.interactive_p99 <= cfg.p99_budget)

        def linger_earning(s: SignalSnapshot) -> bool:
            # the climb's veto: breached interactive p99 while the
            # write path is near-idle means the linger is taxing lone
            # urgent changes without buying any batching — retreat.
            # During a saturating storm (bulk intents flowing) the
            # latency is the storm's, and SHRINKING the linger would
            # only multiply wire calls and make it worse.
            if p99_healthy(s):
                return True
            return s.delta("enqueued") >= cfg.min_activity


        def sweep_pressure(s: SignalSnapshot) -> str:
            return RAISE if s.delta("drift_repairs") > 0 else HOLD

        def depth_pressure(s: SignalSnapshot) -> str:
            if s.delta("sheds") > 0 and p99_healthy(s):
                return RAISE      # shedding while latency is fine
            if (not p99_healthy(s)
                    and s.queue_depth
                    > 0.5 * reg.current("queue.depth_watermark")):
                return LOWER      # shed earlier: latency is drowning
            return HOLD

        def age_pressure(s: SignalSnapshot) -> str:
            if s.delta("sheds") > 0 and p99_healthy(s):
                return RAISE
            if (not p99_healthy(s) and s.queue_oldest_age
                    > 0.5 * reg.current("queue.age_watermark")):
                return LOWER
            return HOLD

        def aging_pressure(s: SignalSnapshot) -> str:
            if not p99_healthy(s):
                return RAISE      # protect interactive: age slower
            horizon = reg.current("queue.aging_horizon")
            if (s.background_p99 is not None
                    and s.background_p99 > 5.0 * horizon):
                return LOWER      # background starved far past bound
            return HOLD

        def breaker_pressure(s: SignalSnapshot) -> str:
            # >= 4 transitions per tick = open/close flapping: a
            # longer window steadies the verdict
            return (RAISE if s.delta("breaker_transitions") >= 4
                    else HOLD)

        def digest_pressure(s: SignalSnapshot) -> str:
            if s.delta("drift_repairs") > 0:
                return LOWER      # drift is live: exchange every wave
            if s.delta("digest_exchanges") > 0:
                return RAISE      # exchanges flowing, all quiet:
            return HOLD           # stretch the cadence

        return [
            HillClimbController(
                reg, "coalescer.linger", fold_efficiency,
                step_factor=1.6, cooldown=2 * cfg.interval,
                guard=linger_earning, explore_up_at=3.0),
            # sweep.every's responsive direction is DOWN (sweep more
            # often while drift flows); the decay drifts it back up.
            # The decay horizon must EXCEED the sensing loop's own
            # latency — repairs arrive at most once per sweep period,
            # so a decay faster than the period un-tunes the knob
            # between the very confirmations that keep it tuned
            AIMDController(
                reg, "sweep.every", sweep_pressure, up_factor=0.5,
                cooldown=4 * cfg.interval, decay_after=60),
            AIMDController(
                reg, "queue.depth_watermark", depth_pressure,
                up_factor=1.5, down_factor=0.66,
                cooldown=2 * cfg.interval),
            AIMDController(
                reg, "queue.age_watermark", age_pressure,
                up_factor=1.5, down_factor=0.66,
                cooldown=2 * cfg.interval),
            AIMDController(
                reg, "queue.aging_horizon", aging_pressure,
                up_factor=1.5, down_factor=0.66,
                cooldown=2 * cfg.interval),
            AIMDController(
                reg, "breaker.window", breaker_pressure,
                up_factor=1.5, cooldown=4 * cfg.interval,
                decay_after=10),
            AIMDController(
                reg, "digest.exchange_every", digest_pressure,
                up_factor=2.0, down_factor=0.0,   # LOWER = snap to lo
                cooldown=4 * cfg.interval, decay_after=20),
        ]

    # -- the loop --------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> SignalSnapshot:
        """One control step (public for tests and the replay tool)."""
        now = simclock.monotonic() if now is None else now
        snap = self.reader.sample(now)
        if snap.anomalies:
            reason = snap.anomalies[0].split(":", 1)[0]
            self.registry.freeze_all(
                reason, cooldown=self.config.freeze_cooldown)
            self._decisions.append({
                "t": round(now, 6), "action": "freeze",
                "reason": sorted(set(snap.anomalies))})
            return snap
        for policy in self._policies:
            applied = policy.update(snap)
            if applied is not None:
                self._decisions.append({
                    "t": round(now, 6), "action": "adjust",
                    "knob": policy.knob, "direction": applied,
                    "value": self.registry.current(policy.knob)})
        # warm_gap is COUPLED to linger, not independently steered:
        # both encode "gaps this small mean a bulk wave", and a linger
        # the warm-gap test keeps cutting short is a dead knob (the
        # interactive urgency path flushes immediately unless the
        # group reads warm — batcher.py deadline-aware linger)
        linger = self.registry.current("coalescer.linger")
        gap = self.registry.current("coalescer.warm_gap")
        if gap != linger:
            applied_gap = self.registry.set(
                "coalescer.warm_gap", linger,
                direction="up" if linger > gap else "down")
            if applied_gap != gap:
                self._decisions.append({
                    "t": round(now, 6), "action": "adjust",
                    "knob": "coalescer.warm_gap",
                    "direction": "up" if applied_gap > gap
                    else "down",
                    "value": applied_gap})
        return snap

    def decision_log(self) -> List[dict]:
        """Bounded, ordered move/freeze log (virtual timestamps) — the
        determinism suite's evidence and a flight-recorder source."""
        return list(self._decisions)

    def start_background(self, stop: threading.Event) -> threading.Thread:
        """Run the tick loop until ``stop``; knobs snap back to their
        defaults on exit (a stopped engine leaves the static plane)."""

        def loop():
            while not stop.is_set():
                simclock.sleep(self.config.interval)
                if stop.is_set():
                    break
                try:
                    self.tick()
                except Exception:
                    logger.exception("autotune tick failed; freezing")
                    self.registry.freeze_all("tick-error")
            self.registry.reset()

        self._thread = simclock.start_thread(
            loop, daemon=True, name="autotune-engine")
        return self._thread
