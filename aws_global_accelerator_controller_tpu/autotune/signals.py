"""Signal sampling for the feedback controllers (ISSUE 15).

The tuner reads ONLY signals the system already exports through the
metrics registry — the same numbers an operator sees on /metrics:
reconcile-latency histograms per class, the coalescer's
enqueued/flush/fold counters, shed counters, drift-repair and
sweep-verify counters, breaker transitions, queue depth/age gauges,
and the convergence ledger's stage attribution (which names the
dominant pipeline stage, i.e. which knob family is the bottleneck).
Sampling is delta-based: each :meth:`SignalReader.sample` reports the
movement since the previous tick.

Trust boundary: a production signal pipeline can LIE — a scrape
glitch, a wedged exporter, a clock step — and a feedback loop that
believes garbage will drive the knobs somewhere pathological and stay
there.  Every snapshot therefore carries an ``anomalies`` list, filled
when a counter runs backwards, a value is NaN/inf/negative, a delta is
physically implausible for one tick, or the stream has STALLED (no
counter movement across several ticks while the queues demonstrably
hold work).  The engine's response to any anomaly is the freeze
(registry.freeze_all): snap to defaults, hold, re-sample — the chaos
e2e proves a FaultInjector-corrupted stream leaves throughput within
noise of the static plane.

The ``corrupt`` hook is that chaos surface: the fake cloud's
FaultInjector (cloudprovider/aws/fake.py ``set_signal_corruption``)
deterministically garbles sampled values on their way into the
snapshot, exactly like its API-call fault schedule — seeded, logged,
replayable.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .. import metrics

# one tick's counter delta above this is a lie, not a workload (the
# busiest measured storms move thousands per second, not billions)
IMPLAUSIBLE_DELTA = 1e9
# latencies above this are a lie on any plane this code runs (an hour)
IMPLAUSIBLE_SECONDS = 3600.0
# ticks with zero movement anywhere while queues hold work = stalled
STALL_TICKS = 5

_COUNTERS = {
    "enqueued": "provider_mutations_enqueued_total",
    "flushes": "provider_mutation_flushes_total",
    "folds": "provider_mutation_folds_total",
    "sheds": "sheds_total",
    "drift_repairs": "drift_repairs_total",
    "sweep_verifies": "drift_sweep_verifies_total",
    "fastpath_skips": "reconcile_fastpath_skips_total",
    "breaker_transitions": "circuit_transitions_total",
    "digest_exchanges": "region_digest_exchanges_total",
    "syncs": "controller_sync_total",
}


@dataclass
class SignalSnapshot:
    """One tick's view of the plane.  Deltas are since the previous
    sample; latencies are windowed p99 estimates from the histogram
    bucket deltas (None = nothing converged this window)."""

    now: float = 0.0
    deltas: Dict[str, float] = field(default_factory=dict)
    interactive_p99: Optional[float] = None
    background_p99: Optional[float] = None
    queue_depth: float = 0.0
    queue_oldest_age: float = 0.0
    dominant_stage: Optional[str] = None
    anomalies: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.anomalies

    def delta(self, name: str) -> float:
        return self.deltas.get(name, 0.0)


def _finite(value: float) -> bool:
    return isinstance(value, (int, float)) and math.isfinite(value)


def _p99_from_hist(series) -> Optional[float]:
    """p99 estimate over summed bucket-count deltas: the upper bound of
    the first bucket whose cumulative share crosses 0.99 (histogram
    percentile the Prometheus way — coarse, monotone, good enough for
    a controller that only needs direction)."""
    total = sum(n for _, n in series)
    if total <= 0:
        return None
    finite = [le for le, _ in series if math.isfinite(le)]
    top = finite[-1] if finite else 0.0
    rank = 0.99 * total
    cum = 0
    for le, n in series:
        cum += n
        if cum >= rank:
            # a crossing in the overflow bucket reports the top finite
            # bound: "at least this" is direction enough for control
            return le if math.isfinite(le) else top
    return top


class SignalReader:
    """Delta-sampling reader over a metrics registry.

    ``corrupt(name, value) -> value`` is the chaos hook — identity when
    unset; the engine treats whatever comes back as the observed
    truth, which is exactly the point: the VALIDATION downstream, not
    the sampling, is what keeps a lying stream from wedging the plane.
    """

    def __init__(self,
                 registry: Optional[metrics.Registry] = None,
                 corrupt: Optional[Callable[[str, float], float]]
                 = None):
        self._registry = registry or metrics.default_registry
        self._corrupt = corrupt
        self._prev_counters: Dict[str, float] = {}
        self._prev_hist: Dict[str, List] = {}
        self._stalled_ticks = 0
        self._primed = False

    def set_corrupt(self, corrupt) -> None:
        self._corrupt = corrupt

    # -- raw reads -------------------------------------------------------

    def _read(self, name: str, value: float,
              snap: SignalSnapshot) -> float:
        if self._corrupt is not None:
            value = self._corrupt(name, value)
        if not _finite(value):
            snap.anomalies.append(f"non-finite:{name}")
            return 0.0
        return value

    def _latency_window(self, klass: str, snap: SignalSnapshot
                        ) -> Optional[float]:
        """p99 of this tick's reconcile_latency_seconds observations
        for ``klass`` (bucket deltas summed over controllers)."""
        buckets: Dict[float, int] = {}
        for labels, series in self._registry.histogram_series(
                "reconcile_latency_seconds").items():
            if dict(labels).get("class") != klass:
                continue
            prev = dict(self._prev_hist.get(
                ("reconcile_latency_seconds",) + labels, []))
            for le, n in series:
                d = n - prev.get(le, 0)
                if d < 0:
                    snap.anomalies.append(
                        f"regressed:latency[{klass}]")
                    d = 0
                buckets[le] = buckets.get(le, 0) + d
            self._prev_hist[("reconcile_latency_seconds",) + labels] \
                = series
        p99 = _p99_from_hist(sorted(buckets.items()))
        if p99 is None:
            return None
        p99 = self._read(f"latency_p99.{klass}", p99, snap)
        if p99 < 0 or p99 > IMPLAUSIBLE_SECONDS:
            snap.anomalies.append(f"implausible:latency[{klass}]")
            return None
        return p99

    # -- the sample ------------------------------------------------------

    def sample(self, now: float) -> SignalSnapshot:
        snap = SignalSnapshot(now=now)
        reg = self._registry
        for key, metric in _COUNTERS.items():
            raw = self._read(key, reg.counter_value(metric), snap)
            prev = self._prev_counters.get(key)
            self._prev_counters[key] = raw
            if prev is None:
                continue
            d = raw - prev
            if d < 0:
                snap.anomalies.append(f"regressed:{key}")
                d = 0.0
            if d > IMPLAUSIBLE_DELTA:
                snap.anomalies.append(f"implausible:{key}")
                d = 0.0
            snap.deltas[key] = d
        snap.interactive_p99 = self._latency_window("interactive", snap)
        snap.background_p99 = self._latency_window("background", snap)
        depth = self._read("queue_depth",
                           reg.sample_gauges("workqueue_depth",
                                             skip_label="tier"), snap)
        age = self._read(
            "queue_oldest_age",
            reg.sample_gauges("workqueue_oldest_age_seconds",
                              max_over=True), snap)
        if depth < 0 or depth > IMPLAUSIBLE_DELTA:
            snap.anomalies.append("implausible:queue_depth")
            depth = 0.0
        if age < 0 or age > IMPLAUSIBLE_SECONDS:
            snap.anomalies.append("implausible:queue_oldest_age")
            age = 0.0
        snap.queue_depth = depth
        snap.queue_oldest_age = age
        snap.dominant_stage = self._dominant_stage()

        # stall detection: queues hold work but no counter moves —
        # the exporter (or the plane) is wedged; the tuner must not
        # keep steering on a frozen photograph
        if self._primed:
            moving = any(d > 0 for d in snap.deltas.values())
            if not moving and depth > 0:
                self._stalled_ticks += 1
                if self._stalled_ticks >= STALL_TICKS:
                    snap.anomalies.append("stalled:signals")
            else:
                self._stalled_ticks = 0
        self._primed = True
        return snap

    def _dominant_stage(self) -> Optional[str]:
        """The pipeline stage carrying the most cumulative seconds in
        stage_seconds (the PR-12 ledger attribution): names which knob
        family bounds the p99 — 'coalesced' points at the linger,
        'queued' at the scheduler knobs, 'inflight' at the wire."""
        sums: Dict[str, float] = {}
        for labels, (s, _c) in self._registry.histogram_sums(
                "stage_seconds").items():
            stage = dict(labels).get("stage", "")
            sums[stage] = sums.get(stage, 0.0) + s
        if not sums:
            return None
        return max(sums.items(), key=lambda kv: kv[1])[0]
