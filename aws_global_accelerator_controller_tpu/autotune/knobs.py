"""The knob catalog: every feedback-tunable control-plane constant,
with its canonical default, bounds and the parameter name it travels
under (ISSUE 15).

This module is the ONE place the control plane's scheduling constants
are spelled as numeric literals.  Every consumer — the write
coalescer's linger (cloudprovider/aws/batcher.py), the drift sweep
period (reconcile/fingerprint.py), the workqueue watermarks and aging
horizon (kube/workqueue.py), the circuit-breaker window
(resilience/wrapper.py), the digest exchange cadence
(topology/digest.py), the CLI flag defaults (cmd/root.py) — imports
its default from here, so "the default" means the same number on every
layer and the feedback controllers' snap-to-default freeze
(autotune/registry.py) provably restores the exact static
configuration.  Lint rule L117 (analysis/concurrency_lint.py) enforces
the ownership: a numeric literal re-hardcoding one of these parameter
names inside a clock-owned package is a finding.

The catalog is data, not behavior: registries (autotune/registry.py)
copy it, engines (autotune/engine.py) read bounds from it, and the
lint rule reads :data:`PARAM_NAMES` from it.  Nothing here imports the
subsystems that consume the knobs (no cycles).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# canonical defaults (the ONLY numeric spellings; everything imports these)
# ---------------------------------------------------------------------------

# write coalescer (cloudprovider/aws/batcher.py CoalesceConfig): the
# leader's size-or-deadline linger and the warm-gap that shields
# interactive urgency from killing a bulk wave's batching
COALESCER_LINGER = 0.005
COALESCER_WARM_GAP = COALESCER_LINGER  # warm_gap=None defaults to linger
# the fake factory's profile: a shorter linger keeps single-writer unit
# tests sub-millisecond-ish while storms still coalesce across workers
FAKE_COALESCER_LINGER = 0.002

# tiered drift sweep (reconcile/fingerprint.py FingerprintConfig): one
# gate-bypassing deep verify per key per this many resync waves
SWEEP_EVERY = 10

# priority-tiered workqueue (kube/workqueue.py): anti-starvation aging
# horizon + the overload-shed watermarks
QUEUE_AGING_HORIZON = 2.0
QUEUE_DEPTH_WATERMARK = 512
QUEUE_AGE_WATERMARK = 1.0

# per-region circuit breaker (resilience/wrapper.py ResilienceConfig):
# the failure-rate observation window
BREAKER_WINDOW = 30.0
# the fake factory's 100x-speed profile window (wrapper.py
# FAKE_CLOUD_CONFIG)
FAKE_BREAKER_WINDOW = 5.0

# multi-region digest gate (topology/digest.py RegionDigestGate): one
# digest exchange per region per this many wave advances (1 = every
# wave, the pre-knob behavior; higher trades drift-detection lag for
# fewer cross-region reads)
DIGEST_EXCHANGE_EVERY = 1


@dataclass(frozen=True)
class KnobSpec:
    """One tunable's contract: the registry clamps every adjustment to
    ``[lo, hi]``, snaps to ``default`` on freeze, and rounds to an int
    when ``integer``.  ``param`` is the keyword/attribute name the knob
    travels under in consumer signatures — what lint rule L117 matches
    numeric re-hardcodings against."""

    name: str
    param: str
    default: float
    lo: float
    hi: float
    integer: bool = False
    description: str = ""

    def clamp(self, value: float) -> float:
        value = min(self.hi, max(self.lo, value))
        return float(round(value)) if self.integer else value


KNOBS: Dict[str, KnobSpec] = {
    spec.name: spec for spec in (
        KnobSpec(
            "coalescer.linger", "linger", COALESCER_LINGER,
            lo=0.0005, hi=0.25,
            description="write-coalescer flush linger seconds"),
        KnobSpec(
            "coalescer.warm_gap", "warm_gap", COALESCER_WARM_GAP,
            lo=0.0005, hi=0.25,
            description="inter-arrival gap that reads as a bulk wave"),
        KnobSpec(
            "sweep.every", "sweep_every", SWEEP_EVERY,
            lo=2, hi=50, integer=True,
            description="resync waves between per-key deep verifies"),
        KnobSpec(
            "queue.aging_horizon", "aging_horizon",
            QUEUE_AGING_HORIZON, lo=0.25, hi=20.0,
            description="background anti-starvation horizon seconds"),
        KnobSpec(
            "queue.depth_watermark", "depth_watermark",
            QUEUE_DEPTH_WATERMARK, lo=64, hi=16384, integer=True,
            description="backlog depth that sheds background work"),
        KnobSpec(
            "queue.age_watermark", "age_watermark",
            QUEUE_AGE_WATERMARK, lo=0.1, hi=15.0,
            description="oldest-interactive age that sheds background"),
        KnobSpec(
            "breaker.window", "breaker_window", BREAKER_WINDOW,
            lo=1.0, hi=120.0,
            description="circuit-breaker failure-rate window seconds"),
        KnobSpec(
            "digest.exchange_every", "exchange_every",
            DIGEST_EXCHANGE_EVERY, lo=1, hi=10, integer=True,
            description="wave advances between region digest exchanges"),
    )
}

# the parameter names L117 polices: a numeric literal bound to one of
# these (keyword argument, signature default, assignment target suffix)
# inside a clock-owned package re-hardcodes a registry-owned knob
PARAM_NAMES = frozenset(spec.param for spec in KNOBS.values())


def spec_for_param(param: str) -> Optional[KnobSpec]:
    for spec in KNOBS.values():
        if spec.param == param:
            return spec
    return None


def default_values() -> Dict[str, float]:
    return {name: spec.default for name, spec in KNOBS.items()}


def bounds(name: str) -> Tuple[float, float]:
    spec = KNOBS[name]
    return spec.lo, spec.hi
