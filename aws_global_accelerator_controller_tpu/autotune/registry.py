"""TunableRegistry: the owner of every feedback-tunable knob's bounds,
default, current value and pin/freeze state (ISSUE 15).

The registry is the ONLY write path onto the live knobs: controllers
(autotune/controllers.py) propose moves, the registry clamps them to
the catalog bounds (autotune/knobs.py), quantizes integer knobs,
rejects moves on pinned or frozen knobs, pushes the new value onto the
live targets (autotune/targets.py appliers) and the
``autotune_knob_value{knob}`` gauge, and counts every applied move in
``autotune_adjustments_total{knob,direction}``.

Freeze semantics (the lying-signal safety contract): ``freeze(name,
reason)`` snaps the knob back to its DEFAULT — which the assembling
manager seeds from the plane's actual static configuration (the fake
profile's 2ms linger, a CLI override), so a frozen plane is provably
the static plane — and holds it there for a cooldown during which
every adjustment is rejected.  ``freeze_all`` is what the engine fires
when the signal stream itself is anomalous: a corrupted, stalled or
regressing signal can never wedge the plane, because the worst the
tuner can then do is exactly nothing.
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, replace as dc_replace
from typing import Callable, Dict, List, Optional

from .. import metrics
from ..simulation import clock as simclock
from . import knobs as knobcat
from . import targets

logger = logging.getLogger(__name__)

# seconds a frozen knob refuses adjustments before the controller may
# resume (virtual seconds under simulation)
DEFAULT_FREEZE_COOLDOWN = 30.0


@dataclass
class Tunable:
    """One knob's live state inside a registry."""

    spec: knobcat.KnobSpec
    default: float
    value: float
    pinned: bool = False
    frozen_until: float = 0.0
    freeze_reason: str = ""
    adjustments: int = 0


# ---------------------------------------------------------------------------
# appliers: knob name -> push the value onto every live target
# ---------------------------------------------------------------------------

def _apply_linger(value: float) -> None:
    for c in targets.coalescers():
        c.config = dc_replace(c.config, linger=value)


def _apply_warm_gap(value: float) -> None:
    for c in targets.coalescers():
        c.config = dc_replace(c.config, warm_gap=value)


def _apply_sweep_every(value: float) -> None:
    for cache in targets.fingerprint_caches():
        cache.set_sweep_every(int(value))


def _apply_queue_attr(attr: str, value: float) -> None:
    for q in targets.queues():
        setter = getattr(q, "set_scheduling", None)
        if setter is not None:
            setter(**{attr: value})
        else:
            setattr(q, attr, value)


def _apply_breaker_window(value: float) -> None:
    for b in targets.breakers():
        b.set_window(value)


def _apply_exchange_every(value: float) -> None:
    for g in targets.digest_gates():
        g.set_exchange_every(int(value))


_APPLIERS: Dict[str, Callable[[float], None]] = {
    "coalescer.linger": _apply_linger,
    "coalescer.warm_gap": _apply_warm_gap,
    "sweep.every": _apply_sweep_every,
    "queue.aging_horizon":
        lambda v: _apply_queue_attr("aging_horizon", v),
    "queue.depth_watermark":
        lambda v: _apply_queue_attr("depth_watermark", int(v)),
    "queue.age_watermark":
        lambda v: _apply_queue_attr("age_watermark", v),
    "breaker.window": _apply_breaker_window,
    "digest.exchange_every": _apply_exchange_every,
}


class TunableRegistry:
    """Owns the knob states; see the module docstring for the write
    contract.  ``defaults`` overrides catalog defaults per knob so the
    registry mirrors the plane it governs (the fake profile's shorter
    linger, CLI-overridden watermarks): snap-to-default then means
    "exactly the static configuration", not "the catalog's idea of
    it".  ``pins`` are operator-fixed values applied immediately and
    never moved (the CLI's per-knob pin flags)."""

    def __init__(self,
                 defaults: Optional[Dict[str, float]] = None,
                 pins: Optional[Dict[str, float]] = None,
                 clock: Callable[[], float] = simclock.monotonic,
                 freeze_cooldown: float = DEFAULT_FREEZE_COOLDOWN):
        self._clock = clock
        self._freeze_cooldown = freeze_cooldown
        self._lock = threading.Lock()
        self._knobs: Dict[str, Tunable] = {}
        for name, spec in knobcat.KNOBS.items():
            default = spec.clamp((defaults or {}).get(name,
                                                      spec.default))
            self._knobs[name] = Tunable(spec=spec, default=default,
                                        value=default)
        for name, value in (pins or {}).items():
            self.pin(name, value)
        self._publish_all()

    # -- reads -----------------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._knobs)

    def current(self, name: str) -> float:
        with self._lock:
            return self._knobs[name].value

    def default(self, name: str) -> float:
        with self._lock:
            return self._knobs[name].default

    def is_frozen(self, name: str) -> bool:
        with self._lock:
            t = self._knobs[name]
            return t.pinned or self._clock() < t.frozen_until

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {name: t.value for name, t in self._knobs.items()}

    def trajectory(self) -> Dict[str, dict]:
        """Per-knob {initial, final, adjustments, frozen_reason} — what
        the adaptive-soak bench records into reconcile_history.jsonl so
        future readers see what the tuner actually did."""
        with self._lock:
            return {name: {"initial": t.default, "final": t.value,
                           "adjustments": t.adjustments,
                           **({"frozen": t.freeze_reason}
                              if t.freeze_reason else {})}
                    for name, t in self._knobs.items()}

    # -- writes ----------------------------------------------------------

    def set(self, name: str, value: float,
            direction: Optional[str] = None) -> float:
        """Move ``name`` to ``value`` (clamped, quantized); returns the
        value in force afterwards.  A pinned or frozen knob refuses the
        move (current value returned).  ``direction`` ("up"/"down")
        labels the adjustment counter when the value actually moved."""
        with self._lock:
            t = self._knobs[name]
            if t.pinned or self._clock() < t.frozen_until:
                return t.value
            new = t.spec.clamp(value)
            if new == t.value:
                return t.value
            t.value = new
            t.adjustments += 1
        _APPLIERS[name](new)
        metrics.record_knob_value(name, new)
        if direction is not None:
            metrics.record_knob_adjustment(name, direction)
        return new

    def pin(self, name: str, value: float) -> float:
        """Operator override: fix ``name`` at ``value`` (clamped) and
        refuse every controller move for the registry's lifetime."""
        with self._lock:
            t = self._knobs[name]
            new = t.spec.clamp(value)
            t.value = new
            t.pinned = True
        _APPLIERS[name](new)
        metrics.record_knob_value(name, new)
        return new

    def freeze(self, name: str, reason: str,
               cooldown: Optional[float] = None) -> None:
        """Snap ``name`` back to its default and refuse adjustments for
        the cooldown (pins are already stronger — left alone)."""
        with self._lock:
            t = self._knobs[name]
            if t.pinned:
                return
            t.frozen_until = self._clock() + (
                self._freeze_cooldown if cooldown is None else cooldown)
            t.freeze_reason = reason
            moved = t.value != t.default
            t.value = t.default
        if moved:
            _APPLIERS[name](t.default)
        metrics.record_knob_value(name, t.default)
        metrics.record_knob_freeze(name, reason)

    def freeze_all(self, reason: str,
                   cooldown: Optional[float] = None) -> None:
        """The anomalous-signal response: every knob snaps to default
        and holds — the plane becomes exactly its static self."""
        for name in self.names():
            self.freeze(name, reason, cooldown=cooldown)
        logger.warning("autotune: all knobs frozen to defaults (%s)",
                       reason)

    def reset(self) -> None:
        """Re-apply every knob's default and clear freeze state (bench
        A/B legs restore the plane between arms; pins survive)."""
        for name in self.names():
            with self._lock:
                t = self._knobs[name]
                if t.pinned:
                    continue
                t.value = t.default
                t.frozen_until = 0.0
                t.freeze_reason = ""
            _APPLIERS[name](t.default)
        self._publish_all()

    def _publish_all(self) -> None:
        for name, value in self.snapshot().items():
            metrics.record_knob_value(name, value)
