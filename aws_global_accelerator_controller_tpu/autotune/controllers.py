"""Per-knob feedback control laws: AIMD and bounded hill-climb
(ISSUE 15).

Both laws share the safety scaffolding the registry cannot provide on
its own:

- **hysteresis**: a deadband around "no pressure" where the controller
  holds, so signal noise never saw-tooths a knob;
- **cooldown**: a minimum interval between applied moves, so one tick's
  transient cannot slew a knob across its whole range;
- **decay**: with no pressure for long enough, the knob relaxes back
  toward its default — an adaptation earned under a storm is not
  carried into the quiet that follows it.

``AIMDController`` is the AdaptiveTokenBucket law generalized (the
in-tree precedent, resilience/breaker.py): multiplicative move on
pressure in the knob's "responsive" direction, additive (or decaying)
recovery.  TCP's argument applies unchanged — many independent signals
steering one shared resource converge without coordinating when
backoff is multiplicative.

``HillClimbController`` is for knobs with a measurable OBJECTIVE
rather than a directional pressure (the coalescer linger: fold
efficiency rises with linger until cohorts saturate, then flattens
while latency keeps paying): bounded steps, direction reversal when
the objective worsens, and the same deadband/cooldown scaffolding.
Every proposal goes through the registry, which clamps to the catalog
bounds and refuses moves on pinned/frozen knobs.
"""
from __future__ import annotations

import logging
from typing import Callable, Optional

from .registry import TunableRegistry
from .signals import SignalSnapshot

logger = logging.getLogger(__name__)

# pressure verdicts a sense function may return
RAISE = "raise"
LOWER = "lower"
HOLD = "hold"


class AIMDController:
    """Additive-increase/multiplicative-decrease (or the mirrored
    shape) on one knob.

    ``sense(snapshot) -> RAISE | LOWER | HOLD`` maps this tick's
    signals to pressure.  RAISE multiplies by ``up_factor`` (the
    responsive direction — for a knob like sweep.every whose
    "responsive" move is DOWN, pass up_factor < 1 and the decay takes
    it back up); LOWER multiplies by ``down_factor``; HOLD counts
    toward the decay: after ``decay_after`` consecutive holds the
    value relaxes halfway back to its default each cooldown.
    """

    def __init__(self, registry: TunableRegistry, knob: str,
                 sense: Callable[[SignalSnapshot], str],
                 up_factor: float = 1.5, down_factor: float = 0.5,
                 cooldown: float = 2.0, decay_after: int = 10,
                 decay_rate: float = 0.5):
        self.registry = registry
        self.knob = knob
        self.sense = sense
        self.up_factor = up_factor
        self.down_factor = down_factor
        self.cooldown = cooldown
        self.decay_after = decay_after
        self.decay_rate = decay_rate
        self._last_move = float("-inf")
        self._holds = 0

    def update(self, snap: SignalSnapshot) -> Optional[str]:
        """One tick; returns the applied direction ("up"/"down") or
        None.  The registry clamps and may refuse (pin/freeze)."""
        if snap.now - self._last_move < self.cooldown:
            return None
        verdict = self.sense(snap)
        current = self.registry.current(self.knob)
        if verdict == HOLD:
            self._holds += 1
            if self._holds >= self.decay_after:
                default = self.registry.default(self.knob)
                if current == default:
                    return None
                target = current + (default - current) * self.decay_rate
                # close enough: land exactly on the default so the
                # decay terminates instead of asymptoting forever
                if abs(target - default) <= 0.05 * abs(default):
                    target = default
                applied = self.registry.set(
                    self.knob, target,
                    direction="down" if target < current else "up")
                if applied != current:
                    self._last_move = snap.now
                    return "down" if applied < current else "up"
            return None
        self._holds = 0
        factor = self.up_factor if verdict == RAISE else self.down_factor
        target = current * factor
        if factor > 1.0 and current == 0:
            target = self.registry.default(self.knob)
        applied = self.registry.set(
            self.knob, target,
            direction="up" if target > current else "down")
        if applied != current:
            self._last_move = snap.now
            return "up" if applied > current else "down"
        return None


class HillClimbController:
    """Bounded hill-climb maximizing a RATIO objective.

    ``objective(snapshot)`` returns ``(numerator, denominator)`` for
    this tick, or None when nothing flowed.  Samples ACCUMULATE
    between moves and each decision uses the volume-weighted ratio
    over its whole window — a single tick's phase noise (a cohort
    enqueued this tick, flushed the next) must not steer the climb.

    Keeps the last applied step's direction; a windowed worsening
    beyond the deadband reverses, otherwise the climb keeps exploring
    the same direction (a plateau is not a stop — the objective often
    cannot move until the knob travels further).  Steps are
    multiplicative (``step_factor``) and clamped by the registry, so
    the climb is bounded by the catalog range at every move.
    ``guard(snapshot) -> bool`` vetoes climbing entirely (retreat
    toward the default); ``explore_up_at`` marks the response curve's
    known-monotone region (see __init__).
    """

    def __init__(self, registry: TunableRegistry, knob: str,
                 objective: Callable[[SignalSnapshot],
                                     Optional[float]],
                 step_factor: float = 1.5, cooldown: float = 2.0,
                 deadband: float = 0.05,
                 guard: Optional[Callable[[SignalSnapshot], bool]]
                 = None,
                 decay_after: int = 10, decay_rate: float = 0.5,
                 explore_up_at: Optional[float] = None):
        self.registry = registry
        self.knob = knob
        self.objective = objective
        self.step_factor = step_factor
        self.cooldown = cooldown
        self.deadband = deadband
        self.guard = guard
        self.decay_after = decay_after
        self.decay_rate = decay_rate
        # response-curve floor hint: at or below this objective value
        # the climb direction is KNOWN to be up (e.g. fold efficiency
        # pinned at 1 means no folding at all — only a longer linger
        # can start it; exploring down there is a random walk to the
        # bound).  None disables the hint.
        self.explore_up_at = explore_up_at
        self._direction = 1          # +1 = raising, -1 = lowering
        self._best: Optional[float] = None
        self._idle = 0
        self._last_move = float("-inf")
        self._window_num = 0.0
        self._window_den = 0.0

    def _decay(self, now: float, current: float) -> Optional[str]:
        default = self.registry.default(self.knob)
        self._best = None
        if current == default:
            return None
        target = current + (default - current) * self.decay_rate
        if abs(target - default) <= 0.05 * abs(default):
            target = default
        applied = self.registry.set(
            self.knob, target,
            direction="down" if target < current else "up")
        if applied != current:
            self._last_move = now
            return "down" if applied < current else "up"
        return None

    def update(self, snap: SignalSnapshot) -> Optional[str]:
        sample = self.objective(snap)
        if sample is not None:
            self._window_num += sample[0]
            self._window_den += sample[1]
            self._idle = 0
        else:
            self._idle += 1
        if snap.now - self._last_move < self.cooldown:
            return None
        current = self.registry.current(self.knob)
        if self.guard is not None and not self.guard(snap):
            # vetoed: retreat toward the default and restart the climb
            self._window_num = self._window_den = 0.0
            self._best = None
            self._direction = 1
            default = self.registry.default(self.knob)
            if current == default:
                return None
            applied = self.registry.set(
                self.knob, current + (default - current) * 0.5,
                direction="down" if default < current else "up")
            if applied != current:
                self._last_move = snap.now
                return "down" if applied < current else "up"
            return None
        if self._window_den <= 0.0:
            # nothing flowed since the last move: after enough idle
            # ticks the knob relaxes toward its default (decay leg)
            if self._idle >= self.decay_after:
                return self._decay(snap.now, current)
            return None
        measured = self._window_num / self._window_den
        self._window_num = self._window_den = 0.0
        if self._best is not None:
            rel = (measured - self._best) / max(abs(self._best), 1e-9)
            # hysteresis guards the REVERSAL only: a windowed
            # worsening beyond the deadband turns the climb around,
            # while a plateau keeps exploring in the same direction —
            # holding on plateaus would wedge the climb exactly where
            # the objective cannot improve until the knob moves
            # further
            if rel < -self.deadband:
                self._direction = -self._direction   # worse: reverse
        if (self.explore_up_at is not None
                and measured <= self.explore_up_at):
            # the known-monotone region: fold efficiency this far
            # under target cannot be improved by a SHORTER linger —
            # exploring down here is a random walk to the bound
            self._direction = 1
        self._best = measured
        factor = (self.step_factor if self._direction > 0
                  else 1.0 / self.step_factor)
        applied = self.registry.set(
            self.knob, current * factor,
            direction="up" if factor > 1.0 else "down")
        if applied != current:
            self._last_move = snap.now
            return "up" if applied > current else "down"
        # clamped at a bound: flip so the next measured window probes
        # back into the range instead of pushing the wall forever
        self._direction = -self._direction
        return None
