"""Self-tuning control plane (ISSUE 15): feedback controllers that
steer the live scheduling knobs from the signals the system already
exports, owned by a :class:`TunableRegistry` whose snap-to-default
freeze makes a lying signal's worst case the static plane.

Layering: ``knobs`` (the catalog — canonical defaults + bounds, the
one home of the numeric literals L117 polices) → ``targets`` (weak
registries the knob-owning subsystems self-register into) →
``registry`` (the clamped, freezable write path onto the targets) →
``controllers`` (AIMD + bounded hill-climb laws) → ``engine`` (the
per-manager tick loop wiring signals to policies).
"""
from . import knobs
from .controllers import AIMDController, HillClimbController
from .engine import AutotuneConfig, AutotuneEngine
from .registry import TunableRegistry
from .signals import SignalReader, SignalSnapshot

__all__ = [
    "AIMDController",
    "AutotuneConfig",
    "AutotuneEngine",
    "HillClimbController",
    "SignalReader",
    "SignalSnapshot",
    "TunableRegistry",
    "knobs",
]
