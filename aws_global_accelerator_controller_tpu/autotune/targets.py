"""Live tuning targets: weak registries the knob-owning subsystems
self-register into at construction (ISSUE 15).

The knobs live scattered across objects built at different times by
different layers — workqueues inside controllers, coalescer cohorts
inside the factory's sharded write path, breakers inside each region's
resilient wrapper, the digest gate on the factory.  Rather than thread
a registry handle through every constructor, each subsystem notes
itself here (one line at its construction chokepoint) and the
:class:`~.registry.TunableRegistry` appliers iterate whatever is LIVE
when a knob moves.  WeakSets keep tuning from pinning dead clusters:
a shut-down test cluster's queues vanish from the apply surface with
their last strong reference.

Scope note (documented in ARCHITECTURE.md): the apply surface is
process-wide — every live object of a kind, whichever control plane
built it.  One AutotuneEngine runs per manager and engines are
opt-in, so planes without an engine never have their knobs moved; two
ENGINES in one process would fight over shared targets and is
unsupported (the multi-replica shape is separate OS processes, the
bench-worker precedent).

Import discipline: this module imports nothing from the knob-owning
packages (they import it), so registration can never cycle.
"""
from __future__ import annotations

import threading
import weakref
from typing import List

_lock = threading.Lock()
_queues: "weakref.WeakSet" = weakref.WeakSet()
_coalescers: "weakref.WeakSet" = weakref.WeakSet()
_breakers: "weakref.WeakSet" = weakref.WeakSet()
_digest_gates: "weakref.WeakSet" = weakref.WeakSet()


def note_queue(queue) -> None:
    """A rate-limiting workqueue was built (kube/workqueue.py
    ``new_rate_limiting_queue`` — both implementations)."""
    with _lock:
        _queues.add(queue)


def note_coalescer(coalescer) -> None:
    """A write-coalescer cohort was built (cloudprovider/aws/batcher.py
    ``MutationCoalescer``)."""
    with _lock:
        _coalescers.add(coalescer)


def note_breaker(breaker) -> None:
    """A per-region circuit breaker was built (resilience/breaker.py)."""
    with _lock:
        _breakers.add(breaker)


def note_digest_gate(gate) -> None:
    """A region digest gate was built (topology/digest.py)."""
    with _lock:
        _digest_gates.add(gate)


def queues() -> List:
    with _lock:
        return list(_queues)


def coalescers() -> List:
    with _lock:
        return list(_coalescers)


def breakers() -> List:
    with _lock:
        return list(_breakers)


def digest_gates() -> List:
    with _lock:
        return list(_digest_gates)


def fingerprint_caches() -> List:
    """The fingerprint gates' own live-cache registry
    (reconcile/fingerprint.py keeps it for circuit invalidation) —
    read lazily so importing this module never pulls reconcile/."""
    from ..reconcile import fingerprint

    with fingerprint._caches_lock:
        return list(fingerprint._caches)
