"""CLI: aws-global-accelerator-controller-tpu {controller|webhook|version}.

Mirrors the reference's cobra command tree (cmd/root.go:13-30,
cmd/controller/controller.go:24-98, cmd/webhook/webhook.go:17-41,
cmd/version.go:15-26) with argparse.

``controller`` has two interchangeable backends (proven by the contract
suite, tests/test_store_contract.py): ``--fake`` (default here) runs
against the in-process fake API server; ``--real`` speaks HTTP to a
cluster API server resolved from ``--kubeconfig``/``--master`` or the
in-cluster service env (kube/http_store.py, kube/kubeconfig.py) — the
stdlib-only analogue of the reference's client-go wiring
(cmd/controller/controller.go:50, pkg/manager/manager.go:43-50).
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

from . import compute
from .. import BUILD, REVISION, VERSION
from ..autotune import knobs as knobcat
from ..cloudprovider.aws.factory import BotoCloudFactory, FakeCloudFactory
from ..controller.endpointgroupbinding import EndpointGroupBindingConfig
from ..controller.globalaccelerator import GlobalAcceleratorConfig
from ..controller.route53 import Route53Config
from ..kube.apiserver import FakeAPIServer
from ..kube.client import KubeClient, OperatorClient
from ..leaderelection import LeaderElection
from ..manager import ControllerConfig, Manager
from ..metrics import HealthServer
from ..signals import setup_signal_handler
from ..webhook import WebhookServer

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="aws-global-accelerator-controller-tpu",
        description=("Manage AWS Global Accelerator and Route53 from "
                     "Kubernetes"))
    parser.add_argument("-v", "--verbosity", type=int, default=1,
                        help="Log verbosity (klog-style; >=4 is debug).")
    sub = parser.add_subparsers(dest="command", required=True)

    controller = sub.add_parser("controller", help="Start controller")
    controller.add_argument("-w", "--workers", type=int, default=1,
                            help="Concurrent workers number for controller.")
    controller.add_argument("-c", "--cluster-name", default="default",
                            help="Owner cluster name used in resource tags.")
    controller.add_argument("--kubeconfig", default="",
                            help="Path to a kubeconfig (out-of-cluster).")
    controller.add_argument("--master", default="",
                            help="Kubernetes API server address override.")
    backend = controller.add_mutually_exclusive_group()
    backend.add_argument("--fake", dest="fake", action="store_true",
                         default=True,
                         help="Run against the in-process fake API "
                              "server and fake AWS cloud (default).")
    backend.add_argument("--real", dest="fake", action="store_false",
                         help="Connect to a real cluster over HTTP "
                              "(kubeconfig / in-cluster service "
                              "account; stdlib client, no kubernetes "
                              "package needed).")
    controller.add_argument("--fake-cloud", action="store_true",
                            help="With --real: keep the in-memory fake "
                                 "AWS cloud (stub-apiserver tests, "
                                 "dev).")
    controller.add_argument("--leader-elect", action="store_true",
                            default=True,
                            help="Run under Lease-based leader election.")
    controller.add_argument("--shards", type=int, default=1,
                            metavar="S",
                            help="Partition the reconcile key space "
                                 "into S shards (consistent hash of "
                                 "each resource's AWS-side container; "
                                 "sharding/).  1 (default) is the "
                                 "classic single-writer deployment; "
                                 "S>1 lets N replicas split the fleet "
                                 "under per-shard leases "
                                 "(leaderelection/shards.py).")
    controller.add_argument("--shard-id", default="auto",
                            metavar="K|auto",
                            help="With --shards S: 'auto' (default) "
                                 "runs the shard-lease manager — this "
                                 "replica acquires whatever shards "
                                 "the rendezvous map assigns it and "
                                 "rebalances on membership change; an "
                                 "integer K statically owns exactly "
                                 "shard K with no leases (bench "
                                 "workers, operator pinning).")
    controller.add_argument("--health-port", type=int, default=8081,
                            help="Port for /healthz, /readyz and /metrics "
                                 "(0 disables; the reference controller "
                                 "binary has no such endpoint).")
    controller.add_argument("--weight-policy",
                            choices=("static", "model"),
                            default="static",
                            help="Endpoint weight assignment: static = "
                                 "spec.weight everywhere (reference "
                                 "parity); model = TPU-planned "
                                 "per-endpoint weights for bindings "
                                 "with spec.weight: null "
                                 "(controller/weightpolicy.py).")
    controller.add_argument("--policy-checkpoint", default="",
                            metavar="DIR",
                            help="Orbax checkpoint directory (the "
                                 "train CLI's --ckpt output): the "
                                 "model weight policy plans with the "
                                 "trained params instead of the "
                                 "seed-0 init.  Requires "
                                 "--weight-policy model.")
    controller.add_argument("--policy-reload-seconds", type=float,
                            default=0.0, metavar="SECONDS",
                            help="With --policy-checkpoint: poll the "
                                 "checkpoint directory every SECONDS "
                                 "and hot-swap retrained weights into "
                                 "the running controller (a failed "
                                 "reload keeps the current weights). "
                                 "0 disables (default).")
    controller.add_argument("--no-fingerprints", action="store_true",
                            help="Disable the steady-state fingerprint "
                                 "fast path: every informer resync "
                                 "re-delivery takes a full provider-"
                                 "verifying sync (the pre-gate "
                                 "behavior; A/B escape hatch).")
    controller.add_argument("--drift-sweep-every", type=int,
                            default=knobcat.SWEEP_EVERY,
                            metavar="WAVES",
                            help="Deep-verify each object against AWS "
                                 "once per this many resync periods "
                                 "(the tiered drift sweep that "
                                 "catches out-of-band mutation; "
                                 "default %(default)s). 0 disables "
                                 "the sweep.")
    controller.add_argument("--queue-aging-horizon", type=float,
                            default=knobcat.QUEUE_AGING_HORIZON,
                            metavar="SECONDS",
                            help="Anti-starvation horizon of the "
                                 "priority-tiered workqueues: a "
                                 "background (resync/sweep) item's "
                                 "effective priority reaches a fresh "
                                 "interactive item's after waiting "
                                 "this long (default %(default)s; "
                                 "<=0 = strict interactive-first).")
    controller.add_argument("--queue-depth-watermark", type=int,
                            default=knobcat.QUEUE_DEPTH_WATERMARK,
                            metavar="N",
                            help="Overload shed trigger: with more "
                                 "than N items backlogged on a queue, "
                                 "background resync/sweep enqueues "
                                 "are dropped (re-delivered by the "
                                 "next wave; sheds_total counts "
                                 "them). 0 disables (default "
                                 "%(default)s).")
    controller.add_argument("--queue-age-watermark", type=float,
                            default=knobcat.QUEUE_AGE_WATERMARK,
                            metavar="SECONDS",
                            help="Overload shed trigger: when the "
                                 "oldest INTERACTIVE item has waited "
                                 "this long, background enqueues are "
                                 "shed first. 0 disables (default "
                                 "%(default)s).")
    autotune_group = controller.add_mutually_exclusive_group()
    autotune_group.add_argument(
        "--autotune", dest="autotune", action="store_true",
        default=True,
        help="Run the self-tuning control loops (default): feedback "
             "controllers steer the scheduling knobs — coalescer "
             "linger, drift-sweep period, queue watermarks, breaker "
             "window, digest cadence — from the exported signals, "
             "snapping to defaults on anomalous signals (autotune/).")
    autotune_group.add_argument(
        "--no-autotune", dest="autotune", action="store_false",
        help="Freeze every knob at its configured default (the "
             "static plane; the runbook's first move when a "
             "controller misbehaves — docs/operations.md).")
    controller.add_argument("--autotune-interval", type=float,
                            default=1.0, metavar="SECONDS",
                            help="Seconds between autotune signal "
                                 "samples (default %(default)s).")
    controller.add_argument("--autotune-pin", action="append",
                            default=[], metavar="KNOB=VALUE",
                            help="Pin one knob to a fixed value the "
                                 "controllers never move (repeatable; "
                                 "e.g. --autotune-pin "
                                 "coalescer.linger=0.01).  Knob names "
                                 "are the autotune catalog's "
                                 "(autotune/knobs.py; "
                                 "autotune_knob_value{knob} on "
                                 "/metrics).")
    controller.add_argument("--regions", default="",
                            help="Comma-separated region list arming "
                                 "the multi-region topology layer "
                                 "(topology/): per-region write "
                                 "aggregation, digest-based sweep "
                                 "reads, and the fake cloud's "
                                 "latency/partition model.  Empty "
                                 "(default) = flat fan-in, the "
                                 "pre-topology behavior.  "
                                 "Fake-cloud backends only.")
    controller.add_argument("--local-region", default="",
                            help="With --regions: the region this "
                                 "controller runs in (default: the "
                                 "first listed region).")
    controller.add_argument("--seed", action="append", default=[],
                            metavar="FILE",
                            help="Apply YAML manifests into the fake API "
                                 "server at startup (repeatable).")
    controller.add_argument("--demo", action="store_true",
                            help="Seed a demo fleet (fake LB + hosted zone "
                                 "+ annotated Service) and log convergence.")
    controller.add_argument("--smoke", type=int, default=0,
                            metavar="SECONDS",
                            help="With --demo: exit 0 once the demo "
                                 "fleet has converged (accelerator "
                                 "chain + DNS record), exit 1 if it "
                                 "has not within SECONDS. The image "
                                 "smoke gate in CI (e2e.yml).")

    webhook = sub.add_parser("webhook", help="Start webhook server")
    webhook.add_argument("--tls-cert-file", default="",
                         help="x509 certificate for HTTPS.")
    webhook.add_argument("--tls-private-key-file", default="",
                         help="x509 private key for --tls-cert-file.")
    webhook.add_argument("--port", type=int, default=8443,
                         help="Webhook server port.")
    ssl_group = webhook.add_mutually_exclusive_group()
    ssl_group.add_argument("--ssl", dest="ssl", action="store_true",
                           default=True, help="Serve over TLS (default).")
    ssl_group.add_argument("--no-ssl", dest="ssl", action="store_false",
                           help="Serve plain HTTP.")

    apiserver = sub.add_parser(
        "apiserver",
        help="Run the standalone dev apiserver (k8s REST wire protocol "
             "over the in-memory store) — `controller --real --master "
             "http://127.0.0.1:PORT` connects to it.")
    apiserver.add_argument("--port", type=int, default=8001,
                           help="Listen port (default 8001).")
    apiserver.add_argument("--host", default="127.0.0.1",
                           help="Bind address.")
    apiserver.add_argument("--tls-cert-file", default="",
                           help="Serve HTTPS with this certificate.")
    apiserver.add_argument("--tls-private-key-file", default="",
                           help="x509 private key for --tls-cert-file.")

    sub.add_parser("version", help="Print the version number")
    compute.register(sub)
    return parser


def run_controller(args) -> int:
    policy_instance = None
    reload_s = getattr(args, "policy_reload_seconds", 0.0)
    if reload_s < 0:
        raise SystemExit(
            "--policy-reload-seconds must be >= 0 (0 disables)")
    if reload_s and not getattr(args, "policy_checkpoint", ""):
        raise SystemExit(
            "--policy-reload-seconds needs --policy-checkpoint "
            "(a checkpoint directory to follow)")
    if getattr(args, "policy_checkpoint", ""):
        if getattr(args, "weight_policy", "static") != "model":
            raise SystemExit(
                "--policy-checkpoint requires --weight-policy model "
                "(static ignores model params)")
        # load EAGERLY: a bad checkpoint must abort startup here, not
        # crash the leader-run thread after election (where the process
        # would keep serving health checks while reconciling nothing).
        # With --policy-reload-seconds the SAME eager contract applies
        # to the first load; only subsequent reloads degrade softly.
        from ..controller.weightpolicy import (
            ModelWeightPolicy,
            ReloadingModelWeightPolicy,
        )

        try:
            if reload_s:
                policy_instance = ReloadingModelWeightPolicy(
                    args.policy_checkpoint, reload_s)
            else:
                policy_instance = ModelWeightPolicy.from_checkpoint(
                    args.policy_checkpoint)
        except (OSError, ValueError) as e:
            raise SystemExit(f"--policy-checkpoint: {e}")
    num_shards = getattr(args, "shards", 1)
    if num_shards < 1:
        raise SystemExit("--shards must be >= 1")
    shard_id = str(getattr(args, "shard_id", "auto"))
    if shard_id != "auto":
        try:
            static_shard = int(shard_id)
        except ValueError:
            raise SystemExit("--shard-id must be an integer or 'auto'")
        if not 0 <= static_shard < num_shards:
            raise SystemExit(
                f"--shard-id {static_shard} out of range "
                f"[0, {num_shards})")
    stop = setup_signal_handler()

    # multi-region topology (topology/): flat fan-in remains the
    # default until --regions is configured; the simulated region
    # model needs the fake cloud (the boto bundle has no gateway)
    from ..topology import parse_regions
    topology = parse_regions(
        getattr(args, "regions", ""),
        local_region=getattr(args, "local_region", "") or None)
    if topology is not None and not args.fake \
            and not args.fake_cloud:
        raise SystemExit("--regions requires the fake cloud "
                         "(--fake or --fake-cloud): the simulated "
                         "region gateway backs the topology layer")

    if args.fake:
        logger.info("using the in-process fake API server")
        api = FakeAPIServer()
        kube = KubeClient(api)
        operator = OperatorClient(api)
        cloud_factory = FakeCloudFactory(num_shards=num_shards,
                                         topology=topology)
    else:
        from ..kube.http_store import HTTPAPIServer
        from ..kube.kubeconfig import KubeConfigError, build_config

        try:
            # build_config owns the full resolution order (flag >
            # $KUBECONFIG > in-cluster > ~/.kube/config); passing the
            # raw flag keeps the in-cluster branch reachable
            rest_config = build_config(args.kubeconfig, args.master)
        except KubeConfigError as e:
            raise SystemExit(str(e))
        logger.info("connecting to apiserver %s", rest_config.server)
        api = HTTPAPIServer(rest_config)
        kube = KubeClient(api)
        operator = OperatorClient(api)
        cloud_factory = (FakeCloudFactory(num_shards=num_shards,
                                          topology=topology)
                         if args.fake_cloud
                         else BotoCloudFactory(num_shards=num_shards))

    from ..reconcile.fingerprint import FingerprintConfig
    fingerprints = FingerprintConfig(
        enabled=not getattr(args, "no_fingerprints", False),
        sweep_every=max(0, getattr(args, "drift_sweep_every",
                                   knobcat.SWEEP_EVERY)))
    # overload scheduler knobs, shared by every controller queue
    # (kube/workqueue.py priority tiers; docs/operations.md runbook)
    scheduler = dict(
        aging_horizon=getattr(args, "queue_aging_horizon",
                              knobcat.QUEUE_AGING_HORIZON),
        depth_watermark=max(0, getattr(
            args, "queue_depth_watermark",
            knobcat.QUEUE_DEPTH_WATERMARK)),
        age_watermark=max(0.0, getattr(
            args, "queue_age_watermark",
            knobcat.QUEUE_AGE_WATERMARK)))
    # self-tuning control loops (autotune/): on by default, frozen to
    # the static plane with --no-autotune, per-knob pins parsed here
    # so a typo'd knob name aborts startup instead of being ignored
    from ..autotune import AutotuneConfig
    pins = {}
    for spec_arg in getattr(args, "autotune_pin", []):
        knob, sep, raw = spec_arg.partition("=")
        if not sep:
            raise SystemExit(
                f"--autotune-pin wants KNOB=VALUE, got {spec_arg!r}")
        if knob not in knobcat.KNOBS:
            raise SystemExit(
                f"--autotune-pin: unknown knob {knob!r} "
                f"(known: {', '.join(sorted(knobcat.KNOBS))})")
        try:
            pins[knob] = float(raw)
        except ValueError:
            raise SystemExit(
                f"--autotune-pin {knob}: {raw!r} is not a number")
    autotune_interval = getattr(args, "autotune_interval", 1.0)
    if autotune_interval <= 0:
        raise SystemExit("--autotune-interval must be > 0")
    autotune_cfg = AutotuneConfig(
        enabled=getattr(args, "autotune", True),
        interval=autotune_interval, pins=pins)
    config = ControllerConfig(
        autotune=autotune_cfg,
        global_accelerator=GlobalAcceleratorConfig(
            workers=args.workers, cluster_name=args.cluster_name,
            fingerprints=fingerprints, **scheduler),
        route53=Route53Config(
            workers=args.workers, cluster_name=args.cluster_name,
            fingerprints=fingerprints, **scheduler),
        endpoint_group_binding=EndpointGroupBindingConfig(
            workers=args.workers,
            weight_policy=getattr(args, "weight_policy", "static"),
            weight_policy_instance=policy_instance,
            fingerprints=fingerprints, **scheduler),
    )

    namespace = os.environ.get("POD_NAMESPACE", "default")

    if args.demo:
        if not hasattr(cloud_factory, "cloud"):
            raise SystemExit(
                "--demo needs the fake AWS cloud (--fake or --fake-cloud)")
        _seed_demo(kube, cloud_factory)
    if args.smoke:
        if not args.demo:
            raise SystemExit("--smoke requires --demo")
        _start_smoke_watchdog(args.smoke, cloud_factory, stop)
    if args.seed:
        from ..kube.apply import apply_files
        # lenient: config kinds that can't be installed on this backend
        # (webhook configs without a resolver, CRDs on a real cluster)
        # are logged and skipped, like the pre-config-kind behavior
        applied = apply_files(kube.api, args.seed, lenient=True)
        logger.info("seeded %d objects from %s", len(applied), args.seed)

    health = None
    if args.health_port != 0:
        health = HealthServer(port=args.health_port)
        health.start_background()

    # arm the chaos flight recorder for the process's life (flight.py):
    # baselines the metrics delta and enables the runtime triggers
    # (circuit open, rollout rollback, overload shed) — the operator's
    # black box for "what led up to this" (docs/operations.md)
    from .. import flight
    flight.default_recorder.arm()

    def run_manager(leader_stop):
        handle = Manager().run(kube, operator, cloud_factory, config,
                               leader_stop, block=False)
        if health is not None:
            # readiness = informer caches synced; leadership is NOT a
            # readiness concern (standby replicas must be Ready)
            health.add_ready_probe("informers", handle.informers_synced)
        leader_stop.wait()
        # ordered, fenced shutdown: fence new mutation intents, drain
        # the write coalescer, seal, drain workqueues + join workers,
        # flush events — all under one deadline (manager/manager.py).
        # The lease is released LAST, by the elector's own finally.
        handle.stop(deadline=10.0)

    try:
        if shard_id != "auto":
            # statically pinned: own exactly shard K, no leases — the
            # bench-worker / operator-pinned replica shape
            cloud_factory.shards.set_static_owner(static_shard)
            logger.info("statically owning shard %d of %d",
                        static_shard, num_shards)
            run_manager(stop)
        elif num_shards > 1 and args.leader_elect:
            # sharded fleet: every replica runs its manager (the read
            # plane is shared); WRITE authority is per shard, governed
            # by the shard-lease manager's rendezvous rebalance —
            # there is no process-wide leader to elect
            from ..leaderelection.shards import ShardLeaseManager

            import uuid as uuid_mod
            # flip to managed mode SYNCHRONOUSLY, before any informer
            # or worker starts: the ShardSet is born standalone
            # (owning every shard), and leaving the flip to the lease
            # loop's thread would give this replica a window where it
            # writes every key with no lease held — on N replicas at
            # once, the exact split-brain the leases forbid
            cloud_factory.shards.set_managed()
            slm = ShardLeaseManager(
                "aws-global-accelerator-controller", namespace, kube,
                cloud_factory.shards,
                identity=os.environ.get("POD_NAME",
                                        str(uuid_mod.uuid4())),
                drain=cloud_factory.drain_shard)
            slm_thread = slm.start_background(stop)
            run_manager(stop)
            # let the lease loop finish its graceful handoffs (seal
            # before release, per shard) before the process exits
            slm_thread.join(timeout=10.0)
        elif args.leader_elect:
            # the elector arms the factory's mutation fence per
            # leadership term (token = lease_transitions) and seals it
            # on loss BEFORE the callback below exits the process — a
            # deposed replica's queued mutations are rejected, never
            # issued concurrently with the successor's
            le = LeaderElection("aws-global-accelerator-controller",
                                namespace, kube,
                                fence=cloud_factory.fence)
            le.run(stop, on_started_leading=run_manager,
                   on_stopped_leading=lambda: os._exit(0))
            if le.run_failed:
                # the manager crashed while leading (elector already
                # logged the traceback and released the lease)
                return 1
        else:
            run_manager(stop)
    finally:
        if health is not None:
            health.shutdown()
        if policy_instance is not None and hasattr(policy_instance,
                                                  "close"):
            policy_instance.close()
    return 0


def _start_smoke_watchdog(budget_s: int, cloud_factory, stop) -> None:
    """Poll the fake cloud until the demo fleet has fully converged
    (accelerator + listener + endpoint group + Route53 A record), then
    stop the process with exit 0; a budget overrun exits 1."""
    import threading
    import time

    cloud = cloud_factory.cloud

    def converged() -> bool:
        # bare fake-cloud reads below: this watchdog OBSERVES the demo
        # fleet's desired state, it is not a control-path AWS caller
        accs = cloud.ga.list_accelerators()  # race: fake observation
        if len(accs) != 1:
            return False
        listeners = cloud.ga.list_listeners(  # race: fake observation
            accs[0].accelerator_arn)
        if len(listeners) != 1:
            return False
        for zone in cloud.route53.list_hosted_zones():  # race: fake observation
            for rec in cloud.route53.list_resource_record_sets(  # race: fake observation
                    zone.id):
                if rec.type == "A":
                    return True
        return False

    def watch():
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            try:
                if converged():
                    logger.info("smoke: demo fleet converged")
                    stop.set()
                    return
            except Exception:
                logger.debug("smoke probe error", exc_info=True)
            time.sleep(0.25)
        logger.error("smoke: demo fleet did not converge in %ds",
                     budget_s)
        os._exit(1)

    threading.Thread(target=watch, daemon=True,
                     name="smoke-watchdog").start()


def _seed_demo(kube, cloud_factory) -> None:
    """Demo fleet: a fake active NLB, a hosted zone, and an annotated
    LoadBalancer Service -- the controllers then converge the accelerator
    chain and DNS records, observable via logs and /metrics."""
    from ..apis import (
        AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION,
        AWS_LOAD_BALANCER_TYPE_ANNOTATION,
        ROUTE53_HOSTNAME_ANNOTATION,
    )
    from ..kube.objects import (
        LoadBalancerIngress,
        LoadBalancerStatus,
        ObjectMeta,
        Service,
        ServicePort,
        ServiceSpec,
        ServiceStatus,
    )

    region = "ap-northeast-1"
    hostname = f"demo-0123456789abcdef.elb.{region}.amazonaws.com"
    cloud_factory.cloud.elb.register_load_balancer("demo", hostname, region)
    cloud_factory.cloud.route53.create_hosted_zone("demo.example.com")
    kube.services.create(Service(
        metadata=ObjectMeta(
            name="demo", namespace="default",
            annotations={
                AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external",
                AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
                ROUTE53_HOSTNAME_ANNOTATION: "www.demo.example.com",
            }),
        spec=ServiceSpec(type="LoadBalancer", ports=[ServicePort(port=80)]),
        status=ServiceStatus(load_balancer=LoadBalancerStatus(
            ingress=[LoadBalancerIngress(hostname=hostname)])),
    ))
    logger.info("demo seeded: Service default/demo behind %s", hostname)


def run_webhook(args) -> int:
    if args.ssl and (not args.tls_cert_file or not args.tls_private_key_file):
        print("You must set --tls-cert-file and --tls-private-key-file "
              "when you use SSL", file=sys.stderr)
        return 2
    server = WebhookServer(
        port=args.port,
        tls_cert_file=args.tls_cert_file if args.ssl else "",
        tls_key_file=args.tls_private_key_file if args.ssl else "")
    stop = setup_signal_handler()
    server.start_background()
    stop.wait()
    server.shutdown()
    return 0


def run_apiserver(args) -> int:
    """Standalone dev apiserver (rest_server.py's second job): a
    miniature API server speaking the k8s REST wire protocol for local
    development without a cluster."""
    from ..kube.rest_server import KubeRestServer

    if bool(args.tls_cert_file) != bool(args.tls_private_key_file):
        print("You must set both --tls-cert-file and "
              "--tls-private-key-file for TLS", file=sys.stderr)
        return 2
    server = KubeRestServer(
        host=args.host, port=args.port,
        tls_cert_file=args.tls_cert_file,
        tls_key_file=args.tls_private_key_file).start()
    logger.info("dev apiserver ready at %s (connect with: controller "
                "--real --master %s)", server.url, server.url)
    stop = setup_signal_handler()
    stop.wait()
    server.shutdown()
    return 0


def run_version(args) -> int:
    print(f"Version : {VERSION}")
    print(f"Revision: {REVISION}")
    print(f"Build   : {BUILD}")
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 4 else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    if args.command == "controller":
        return run_controller(args)
    if args.command == "webhook":
        return run_webhook(args)
    if args.command == "apiserver":
        return run_apiserver(args)
    if args.command == "version":
        return run_version(args)
    if args.command == "train":
        return compute.run_train(args)
    if args.command == "plan":
        return compute.run_plan(args)
    if args.command == "eval":
        return compute.run_eval(args)
    return 2
