"""CLI process entry (reference main.go + cmd/)."""
from .root import main
