"""CLI subcommands for the TPU compute track: train | eval | plan.

The reference CLI has only {controller|webhook|version} (cmd/root.go:
13-30) because the reference has no compute.  These commands make the
compute track user-facing: ``train`` fits the traffic policy model on
synthetic fleet telemetry with orbax checkpointing (resumable,
preemption-safe), ``eval`` scores a checkpoint on held-out fleets
(mean loss + plan-quality L1 vs the uniform baseline), ``plan`` loads
a checkpoint (or a fresh init) and emits Global Accelerator endpoint
weights for a fleet as JSON.

JAX is imported lazily inside the run functions so `controller`/
`webhook`/`version` never pay for (or hang on) accelerator backend
initialisation.
"""
from __future__ import annotations

import json
import logging
import sys

logger = logging.getLogger(__name__)


def register(sub) -> None:
    train = sub.add_parser(
        "train", help="Train the traffic policy model (TPU compute track)")
    train.add_argument("--model",
                       choices=("mlp", "temporal", "moe", "deep"),
                       default="mlp",
                       help="mlp: snapshot MLP; temporal: causal "
                            "attention over a telemetry window; moe: "
                            "per-region expert MLPs with a learned "
                            "top-1 gate; deep: residual stage stack "
                            "(pipeline-parallel under --sharded).")
    train.add_argument("--experts", type=int, default=4,
                       help="Expert count (moe model); with --sharded "
                            "must equal the expert mesh axis size.")
    train.add_argument("--supervision", choices=("last", "sequence"),
                       default="last",
                       help="Temporal objective: last = final-step "
                            "scores only (O(T) last-query attention); "
                            "sequence = every step supervised against "
                            "per-step targets (full causal flash/ring "
                            "attention, richer signal; both loaders "
                            "produce the per-step law).")
    train.add_argument("--layout", choices=("contiguous", "zigzag"),
                       default="contiguous",
                       help="Time-axis placement for --sharded "
                            "temporal with --supervision sequence: "
                            "zigzag pairs chunk i with chunk 2n-1-i "
                            "per shard, balancing the causal ring so "
                            "every step costs half a block on every "
                            "device (~2x attention wall time at "
                            "scale); the planner handles window/"
                            "target placement and serving.")
    train.add_argument("--top-k", type=int, default=1, dest="top_k",
                       help="Experts per group (moe): 1 = switch "
                            "routing, 2 = GShard-style top-2 (gate-"
                            "probability-weighted sum).")
    train.add_argument("--capacity-factor", type=float, default=None,
                       dest="capacity_factor",
                       help="Per-expert assignment budget multiplier "
                            "(moe): assignments past "
                            "ceil(cf*groups*k/experts) per dispatch "
                            "block are dropped (standard MoE "
                            "load-imbalance regime).  Default: "
                            "unbounded.")
    train.add_argument("--stages", type=int, default=4,
                       help="Residual stage count (deep model); with "
                            "--sharded must equal the device count.")
    train.add_argument("--microbatches", type=int, default=4,
                       help="GPipe microbatches (deep --sharded); must "
                            "divide --groups.")
    train.add_argument("--loader", choices=("synthetic", "native"),
                       default="synthetic",
                       help="Batch source (mlp/deep/temporal): "
                            "synthetic = reproducible JAX batches; "
                            "native = the C++ background pipeline "
                            "(native/telemetry.cpp), higher input "
                            "throughput, not bit-reproducible.")
    train.add_argument("--remat", action="store_true",
                       help="Rematerialise activations with "
                            "jax.checkpoint: deep --sharded wraps "
                            "each pipeline stage block; temporal "
                            "--supervision sequence wraps the "
                            "per-step head (the [T, S, H] hidden "
                            "activations dominate HBM at long "
                            "windows).  Identical numerics, lower "
                            "HBM.")
    train.add_argument("--attention-chunk", type=int, default=0,
                       dest="attention_chunk", metavar="HEADS",
                       help="Temporal: split the G*E streams axis "
                            "into chunks of at most HEADS per flash "
                            "call (exact — attention is per-head "
                            "independent).  Chunks of <=32 ride the "
                            "fused one-sweep flash backward, which "
                            "wide stream counts otherwise exceed.  "
                            "0 = one call (default).")
    train.add_argument("--optimizer", choices=("adam", "flat_adam"),
                       default="adam",
                       help="All families: adam = optax per-leaf tree "
                            "(required for sharded optimizer-state "
                            "layouts); flat_adam = one raveled-vector "
                            "update (f32 moments, fewer tiny kernels "
                            "— the single-chip fast path).")
    train.add_argument("--profile", default="", metavar="DIR",
                       help="Capture a jax.profiler trace of the "
                            "training loop into DIR (view with "
                            "TensorBoard / xprof).")
    train.add_argument("--guard", action="store_true",
                       help="Divergence guard: check every loss for "
                            "non-finite values (forces a per-step "
                            "device sync); on NaN/inf restore the "
                            "last checkpoint (or re-init without "
                            "--ckpt), skip to the next batch, and "
                            "abort after 5 restores.  The reported "
                            "step counts APPLIED updates, so discarded "
                            "batches don't inflate checkpoint labels.")
    train.add_argument("--window", type=int, default=64,
                       help="Telemetry window length (temporal model); "
                            "the default reaches the Pallas flash "
                            "kernel (FLASH_MIN_WINDOW).")
    train.add_argument("--steps", type=int, default=100,
                       help="Optimisation steps to run this invocation.")
    train.add_argument("--ckpt", default="",
                       help="Checkpoint directory (enables save/resume).")
    train.add_argument("--save-every", type=int, default=50,
                       help="Checkpoint cadence in steps.")
    train.add_argument("--eval-every", type=int, default=0,
                       dest="eval_every",
                       help="Log held-out loss every N applied steps "
                            "(a fixed eval batch from a key stream "
                            "disjoint from training's; 0 disables).")
    train.add_argument("--preempt-exit", type=int, default=0,
                       dest="preempt_exit",
                       help="Exit code after a SIGTERM-triggered "
                            "clean checkpoint (default 0).  Under a "
                            "k8s Job with restartPolicy OnFailure, "
                            "pass a nonzero code (75 = EX_TEMPFAIL) "
                            "so an interrupted run restarts and "
                            "resumes instead of being recorded as "
                            "complete.")
    train.add_argument("--groups", type=int, default=256,
                       help="Endpoint groups per synthetic batch.")
    train.add_argument("--endpoints", type=int, default=32,
                       help="Endpoints per group.")
    train.add_argument("--hidden", type=int, default=128,
                       help="Model hidden width.")
    train.add_argument("--lr", type=float, default=1e-3,
                       help="Adam learning rate.")
    train.add_argument("--seed", type=int, default=0,
                       help="PRNG seed for init and batches.")
    train.add_argument("--sharded", action="store_true",
                       help="Shard over all visible devices: temporal "
                            "-> data x seq mesh with ring attention "
                            "over the window; mlp -> data x model "
                            "mesh (dp x tp); moe -> data x expert "
                            "mesh with all_to_all dispatch; deep -> "
                            "stage pipeline (GPipe).")

    ev = sub.add_parser(
        "eval", help="Evaluate a checkpoint on held-out synthetic "
                     "fleets (JSON out)")
    ev.add_argument("--model", choices=("mlp", "temporal", "moe",
                                        "deep"),
                    default="mlp",
                    help="Must match the model the ckpt was trained "
                         "with.")
    ev.add_argument("--ckpt", default="",
                    help="Checkpoint directory (default: fresh init — "
                         "the untrained baseline).")
    ev.add_argument("--batches", type=int, default=16,
                    help="Held-out batches to average over.")
    ev.add_argument("--groups", type=int, default=64,
                    help="Endpoint groups per eval batch.")
    ev.add_argument("--endpoints", type=int, default=16,
                    help="Endpoints per group.")
    ev.add_argument("--hidden", type=int, default=128,
                    help="Model hidden width (must match the ckpt).")
    ev.add_argument("--window", type=int, default=64,
                    help="Telemetry window length (temporal).")
    ev.add_argument("--experts", type=int, default=4,
                    help="Expert count (moe; must match the ckpt).")
    ev.add_argument("--top-k", type=int, default=1, dest="top_k",
                    help="Experts per group (moe; must match the "
                         "ckpt's training config).")
    ev.add_argument("--capacity-factor", type=float, default=None,
                    dest="capacity_factor",
                    help="Per-expert budget (moe; must match the "
                         "ckpt's training config).")
    ev.add_argument("--capacity-blocks", type=int, default=None,
                    dest="capacity_blocks",
                    help="Capacity enforcement granularity (moe): the "
                         "device count the ckpt trained --sharded on "
                         "(capacity is per dispatch block, so eval "
                         "must match it to score the same routing "
                         "function).  Default: 1 (unsharded "
                         "training).")
    ev.add_argument("--stages", type=int, default=4,
                    help="Stage count (deep; must match the ckpt).")
    ev.add_argument("--microbatches", type=int, default=4,
                    help="GPipe microbatches (deep).")
    ev.add_argument("--supervision", choices=("last", "sequence"),
                    default="last",
                    help="Temporal objective to evaluate under.")
    ev.add_argument("--seed", type=int, default=0,
                    help="PRNG seed; eval batches use an offset "
                         "stream disjoint from training's.")

    plan = sub.add_parser(
        "plan", help="Plan GA endpoint weights for a fleet (JSON out)")
    plan.add_argument("--model",
                      choices=("mlp", "temporal", "moe", "deep"),
                      default="mlp",
                      help="Must match the model the ckpt was trained "
                           "with.")
    plan.add_argument("--experts", type=int, default=4,
                      help="Expert count (moe model; must match the "
                           "ckpt).")
    plan.add_argument("--top-k", type=int, default=1, dest="top_k",
                      help="Experts per group (moe; must match the "
                           "ckpt's training config or the planned "
                           "weights come from a different routing "
                           "function).")
    plan.add_argument("--capacity-factor", type=float, default=None,
                      dest="capacity_factor",
                      help="Per-expert assignment budget (moe; must "
                           "match the ckpt's training config).")
    plan.add_argument("--stages", type=int, default=4,
                      help="Residual stage count (deep model; must "
                           "match the ckpt).")
    plan.add_argument("--microbatches", type=int, default=4,
                      help="GPipe microbatches (deep --sharded); must "
                           "divide --groups.")
    plan.add_argument("--window", type=int, default=64,
                      help="Telemetry window length (temporal model); "
                           "the default reaches the Pallas flash "
                           "kernel (FLASH_MIN_WINDOW).")
    plan.add_argument("--ckpt", default="",
                      help="Checkpoint directory to load params from "
                           "(default: fresh init).")
    plan.add_argument("--groups", type=int, default=8,
                      help="Endpoint groups in the synthetic fleet.")
    plan.add_argument("--endpoints", type=int, default=16,
                      help="Endpoints per group.")
    plan.add_argument("--hidden", type=int, default=128,
                      help="Model hidden width (must match the ckpt).")
    plan.add_argument("--seed", type=int, default=0,
                      help="PRNG seed for the synthetic telemetry.")
    plan.add_argument("--sharded", action="store_true",
                      help="Shard planning over all visible devices "
                           "(see train --sharded).")


def _compat_rung() -> str:
    """Resolve the accelerator degradation rung for this process, as
    a NAMED CLI error when no rung works.

    Every compute entry point (train/eval/plan) calls this before
    building a model: an unusable backend surfaces as the capability
    registry's structured verdict (which probe failed, with the
    underlying exception) instead of an AttributeError at trace time
    minutes into a run."""
    from ..compat import BackendCapabilityError, registry

    try:
        rung = registry.attention_rung()
    except BackendCapabilityError as e:
        raise SystemExit(
            f"accelerator backend unusable — no degradation rung "
            f"available (compat/capability.py):\n{e}")
    logger.info("accelerator compat rung: %s", rung)
    return rung


def _build_model(args):
    """The single model-family dispatch point.

    Returns (model, run_step, run_plan_fwd): ``run_step(params, opt,
    key)`` performs one training step on a fresh synthetic batch;
    ``run_plan_fwd(params, key)`` plans weights for a synthetic fleet.
    """
    from ..jaxenv import import_jax
    jax = import_jax()
    _compat_rung()

    lr = getattr(args, "lr", 1e-3)
    sharded = getattr(args, "sharded", False)
    loader_kind = getattr(args, "loader", "synthetic")
    if loader_kind != "synthetic" and args.model == "moe":
        raise SystemExit(
            f"--loader {loader_kind} supports the mlp, deep and "
            f"temporal families; moe generates its own batch law")
    if (getattr(args, "layout", "contiguous") == "zigzag"
            and not (args.model == "temporal" and sharded)):
        # silently training a non-ring path would let the user believe
        # they exercised the balanced ring — reject for EVERY branch,
        # not just single-chip temporal
        raise SystemExit(
            "--layout zigzag only applies to --sharded temporal "
            "training (it balances the ring across sequence shards)")
    optimizer = getattr(args, "optimizer", "adam")
    if sharded and optimizer != "adam":
        # the raveled state has no axes for the planners'
        # NamedShardings to map (models.common.flat_adam) — every
        # family's sharded path needs the per-leaf adam tree
        raise SystemExit(
            "--optimizer flat_adam is the single-chip fast path; "
            "--sharded training needs the per-leaf adam state")
    if args.model != "temporal" and getattr(args, "attention_chunk", 0):
        # inert elsewhere — a user benchmarking this lever must not
        # conclude from a configuration that never ran (same posture
        # as the zigzag and sharded guards)
        raise SystemExit(
            "--attention-chunk applies to the temporal family only "
            f"(got --model {args.model})")
    if args.model == "temporal":
        from ..models.temporal import TemporalTrafficModel, synthetic_window

        supervision = getattr(args, "supervision", "last")
        chunk = getattr(args, "attention_chunk", 0)
        if sharded and chunk:
            # the sharded planner attends through the ring (its own
            # _attend seam) — chunking would be silently inert, and a
            # user benchmarking the fused-backward head gate must not
            # conclude from a configuration that never ran
            raise SystemExit(
                "--attention-chunk applies to single-chip temporal "
                "training only; --sharded attends through the ring")
        if chunk < 0:
            raise SystemExit("--attention-chunk must be >= 0")
        model = TemporalTrafficModel(
            hidden_dim=args.hidden, learning_rate=lr,
            supervision=supervision,
            remat=getattr(args, "remat", False),
            attention_chunk=chunk, optimizer=optimizer)

        if loader_kind == "synthetic":
            def make_data(key):
                return synthetic_window(
                    key, steps=args.window, groups=args.groups,
                    endpoints=args.endpoints,
                    per_step=supervision == "sequence")
        else:
            # window-mode C++ pipeline (native/telemetry.cpp steps=T):
            # batches stream from worker threads, key is ignored
            from ..models.loader import make_loader

            loader = make_loader(loader_kind, args.groups,
                                 args.endpoints, seed=args.seed,
                                 steps=args.window,
                                 per_step=supervision == "sequence")
            _open_loaders.append(loader)

            def make_data(key):
                return loader.next_window()

        if sharded:
            planner = _temporal_planner(args, model)

            def run_step(params, opt_state, key):
                window, batch = make_data(key)
                return planner.train_step(
                    params, opt_state, planner.shard_window(window),
                    planner.shard_batch(batch))

            def run_plan_fwd(params, key):
                window, batch = make_data(key)
                return planner.forward(
                    params, planner.shard_window(window), batch.mask)
        else:
            # (--layout zigzag already rejected by the top-of-dispatch
            # guard: a single device has no ring)
            # donation: params/Adam state update in place on device
            # (the guard's restore path never reuses pre-step buffers)
            step_fn = jax.jit(model.train_step, donate_argnums=(0, 1))
            fwd = jax.jit(model.forward)

            def run_step(params, opt_state, key):
                window, batch = make_data(key)
                return step_fn(params, opt_state, window, batch)

            def run_plan_fwd(params, key):
                window, batch = make_data(key)
                return fwd(params, window, batch.mask)
    elif args.model == "moe":
        from ..models.moe import MoETrafficModel, synthetic_moe_batch

        cf = getattr(args, "capacity_factor", None)
        # capacity is enforced per dispatch block: the model's block
        # granularity must match the batch shard count
        # (ShardedMoEPlanner validates the same law); eval passes
        # --capacity-blocks explicitly to score a sharded-trained
        # checkpoint's exact routing function
        blocks = getattr(args, "capacity_blocks", None)
        if blocks is None:
            blocks = (len(jax.devices())
                      if cf is not None and sharded else 1)
        model = MoETrafficModel(n_experts=args.experts,
                                hidden_dim=args.hidden,
                                learning_rate=lr,
                                top_k=getattr(args, "top_k", 1),
                                capacity_factor=cf,
                                capacity_blocks=blocks,
                                optimizer=optimizer)
        run_step, run_plan_fwd = _snapshot_runners(
            jax, model,
            lambda key: synthetic_moe_batch(
                key, groups=args.groups, endpoints=args.endpoints,
                n_regions=args.experts),
            lambda: _moe_planner(args, model), sharded)
    elif args.model == "deep":
        from ..models.deep import DeepTrafficModel

        model = DeepTrafficModel(n_stages=args.stages,
                                 hidden_dim=args.hidden,
                                 learning_rate=lr,
                                 optimizer=optimizer)
        run_step, run_plan_fwd = _snapshot_runners(
            jax, model, _batch_source(args, loader_kind),
            lambda: _pipeline_planner(args, model), sharded)
    else:
        from ..models.traffic import TrafficPolicyModel

        model = TrafficPolicyModel(hidden_dim=args.hidden,
                                   learning_rate=lr,
                                   optimizer=optimizer)
        run_step, run_plan_fwd = _snapshot_runners(
            jax, model, _batch_source(args, loader_kind),
            lambda: _mlp_planner(args, model), sharded)
    return model, run_step, run_plan_fwd


def _batch_source(args, loader_kind: str):
    """make_batch(key) for the snapshot families.  synthetic keeps the
    historical contract (batches keyed by fold_in(key, step), so resume
    trajectories are unchanged); native streams from the C++ pipeline,
    ignoring the per-step key (worker streams are seeded once).  Native
    loaders register in _open_loaders; run_train/run_plan close them
    when the command finishes so in-process callers (tests) don't leak
    worker threads across invocations."""
    if loader_kind == "synthetic":
        from ..models.traffic import synthetic_batch

        return lambda key: synthetic_batch(
            key, groups=args.groups, endpoints=args.endpoints)
    from ..models.loader import make_loader

    loader = make_loader(loader_kind, args.groups, args.endpoints,
                         seed=args.seed)
    _open_loaders.append(loader)
    return lambda key: loader.next_batch()


_open_loaders: list = []


def _close_loaders() -> None:
    while _open_loaders:
        _open_loaders.pop().close()


def _snapshot_runners(jax, model, make_batch, make_planner, sharded):
    """run_step/run_plan_fwd wiring shared by the snapshot-batch
    families (mlp, moe, deep): one synthetic Batch per step,
    planner-sharded when requested.  The temporal family keeps its own
    wiring (its data is a (window, batch) pair)."""
    if sharded:
        planner = make_planner()

        def run_step(params, opt_state, key):
            batch = planner.shard_batch(make_batch(key))
            return planner.train_step(params, opt_state, batch)

        def run_plan_fwd(params, key):
            batch = planner.shard_batch(make_batch(key))
            return planner.forward(params, batch.features, batch.mask)
    else:
        # donation: params/Adam state update in place on device (the
        # guard's restore path never reuses pre-step buffers)
        step_fn = jax.jit(model.train_step, donate_argnums=(0, 1))
        fwd = jax.jit(model.forward)

        def run_step(params, opt_state, key):
            return step_fn(params, opt_state, make_batch(key))

        def run_plan_fwd(params, key):
            batch = make_batch(key)
            return fwd(params, batch.features, batch.mask)
    return run_step, run_plan_fwd


def _temporal_planner(args, model):
    """data x seq mesh over all visible devices; validates divisibility
    so shard_map sees even blocks."""
    from ..parallel import ShardedTemporalPlanner
    from ..parallel.mesh import make_mesh

    mesh = make_mesh(axis_names=("data", "seq"))
    n_seq, n_data = mesh.shape["seq"], mesh.shape["data"]
    if args.window % n_seq or args.groups % n_data:
        raise SystemExit(
            f"--sharded needs --window divisible by the seq axis "
            f"({n_seq}) and --groups by the data axis ({n_data}); got "
            f"window={args.window} groups={args.groups}")
    layout = getattr(args, "layout", "contiguous")
    if layout == "zigzag":
        if args.supervision != "sequence":
            raise SystemExit(
                "--layout zigzag requires --supervision sequence "
                "(last supervision never runs the ring it balances)")
        if args.window % (2 * n_seq):
            raise SystemExit(
                f"--layout zigzag needs --window divisible by "
                f"2x the seq axis ({2 * n_seq}); got {args.window}")
    logger.info("temporal mesh: data=%d seq=%d layout=%s", n_data,
                n_seq, layout)
    return ShardedTemporalPlanner(model, mesh, window=args.window,
                                  layout=layout)


def _moe_planner(args, model):
    """data x expert mesh: one expert per device along the expert axis,
    batch sharded over both axes."""
    from ..parallel import ShardedMoEPlanner
    from ..parallel.mesh import make_mesh

    import jax

    n_dev = len(jax.devices())
    if n_dev % args.experts:
        raise SystemExit(
            f"--sharded moe needs --experts to divide the device count "
            f"({n_dev}); got experts={args.experts}")
    mesh = make_mesh(axis_shapes={"data": n_dev // args.experts,
                                  "expert": args.experts})
    n_total = mesh.shape["data"] * mesh.shape["expert"]
    if args.groups % n_total:
        raise SystemExit(
            f"--sharded moe needs --groups divisible by the device "
            f"count ({n_total}); got groups={args.groups}")
    logger.info("moe mesh: data=%d expert=%d", mesh.shape["data"],
                mesh.shape["expert"])
    return ShardedMoEPlanner(model, mesh)


def _pipeline_planner(args, model):
    """Stage mesh (one residual block per device, GPipe schedule);
    when --stages divides the device count with room left over, the
    spare factor becomes a 'data' axis — dp x pp on a 2-D mesh."""
    import jax

    from ..parallel import ShardedPipelinePlanner
    from ..parallel.mesh import make_mesh
    from ..parallel.ring import make_mesh_1d

    n_dev = len(jax.devices())
    if args.stages < 1 or n_dev % args.stages:
        raise SystemExit(
            f"--sharded deep needs --stages (>= 1) to divide the "
            f"device count ({n_dev}); got stages={args.stages}")
    if args.groups % args.microbatches:
        raise SystemExit(
            f"--sharded deep needs --groups divisible by "
            f"--microbatches; got groups={args.groups} "
            f"microbatches={args.microbatches}")
    n_data = n_dev // args.stages
    if n_data > 1:
        if args.groups % n_data:
            raise SystemExit(
                f"--sharded deep with {n_data} data replicas needs "
                f"--groups divisible by {n_data}; got "
                f"groups={args.groups}")
        mesh = make_mesh(axis_shapes={"data": n_data,
                                      "stage": args.stages})
        data_axis = "data"
    else:
        mesh, data_axis = make_mesh_1d(n_dev, "stage"), None
    logger.info("pipeline mesh: data=%d stage=%d microbatches=%d "
                "remat=%s", n_data, args.stages, args.microbatches,
                getattr(args, "remat", False))
    return ShardedPipelinePlanner(model, mesh,
                                  n_microbatches=args.microbatches,
                                  remat=getattr(args, "remat", False),
                                  data_axis=data_axis)


def _mlp_planner(args, model):
    from ..parallel import ShardedTrafficPlanner
    from ..parallel.mesh import make_mesh

    mesh = make_mesh(axis_names=("data", "model"))
    n_data, n_model = mesh.shape["data"], mesh.shape["model"]
    if args.groups % n_data or args.hidden % n_model:
        raise SystemExit(
            f"--sharded needs --groups divisible by the data axis "
            f"({n_data}) and --hidden by the model axis ({n_model}); "
            f"got groups={args.groups} hidden={args.hidden}")
    logger.info("mlp mesh: data=%d model=%d", n_data, n_model)
    return ShardedTrafficPlanner(model, mesh)


def run_train(args) -> int:
    try:
        return _run_train(args)
    finally:
        _close_loaders()


def _run_train(args) -> int:
    from ..jaxenv import import_jax
    jax = import_jax()

    from ..signals import ScopedStopSignal

    with ScopedStopSignal() as stop:
        return _run_train_loop(args, jax, stop)


def _run_train_loop(args, jax, stop) -> int:
    # preemption safety (ScopedStopSignal in _run_train): SIGTERM/
    # SIGINT (k8s eviction, TPU-pod maintenance) breaks the loop
    # cleanly so the final checkpoint save below runs — training
    # resumes from the exact step instead of losing everything since
    # the last --save-every; a second signal still hard-exits
    from ..models.checkpoint import TrainCheckpointer

    model, run_step, _ = _build_model(args)
    start_step = 0
    key = jax.random.PRNGKey(args.seed)
    params = model.init_params(key)
    opt_state = model.init_opt_state(params)

    ckpt = TrainCheckpointer(args.ckpt) if args.ckpt else None
    if ckpt is not None and ckpt.latest_step() is not None:
        try:
            start_step, params, opt_state = ckpt.restore(model)
        except (SystemExit, KeyboardInterrupt):
            raise
        except Exception as e:
            # the common trip is resuming with a different --optimizer
            # than the checkpoint was trained with: the opt_state tree
            # structures disagree (FlatAdamState vs optax per-leaf)
            # and orbax raises a structure mismatch — name it instead
            # of dying in a raw traceback
            raise SystemExit(
                f"--ckpt: failed to resume from {args.ckpt}: {e} "
                f"(if the checkpoint was trained with a different "
                f"--optimizer, resume with the one that trained it)")
        logger.info("resumed from step %d (%s)", start_step, args.ckpt)

    profile_dir = getattr(args, "profile", "")
    if profile_dir:
        # device-level tracing (XLA ops, fusions, transfers) on top of
        # the framework's own span tracing (tracing.py); view in
        # TensorBoard / xprof
        jax.profiler.start_trace(profile_dir)
    guard = getattr(args, "guard", False)
    eval_every = max(getattr(args, "eval_every", 0) or 0, 0)
    eval_data, eval_loss = None, None
    if eval_every:
        make, eval_loss, _fwd = _eval_fns(args, model, jax)
        # double fold: the training stream is fold_in(key, batch_idx),
        # so a single fold_in(key, 10_000) would COLLIDE with training
        # batch 10_000 (run_eval uses the same double-folded stream)
        eval_data = make(jax.random.fold_in(jax.random.fold_in(
            jax.random.PRNGKey(args.seed), 10_000), 0))
    max_restores, restores = 5, 0
    # step_label counts APPLIED optimizer updates: checkpoint labels
    # and the reported step stay truthful under --guard rollbacks
    # (a checkpoint at step N always holds exactly N applied updates);
    # without --guard it advances every iteration, as before
    step_label = start_step
    loss = None  # last ACCEPTED step's loss (never non-finite)
    preempted = False
    try:
        for batch_idx in range(start_step, start_step + args.steps):
            if stop.is_set():
                preempted = True
                logger.info(
                    "stop signal: checkpointing at step %d and "
                    "exiting cleanly", step_label)
                break
            try:
                new_params, new_opt, new_loss = run_step(
                    params, opt_state,
                    jax.random.fold_in(key, batch_idx))
            except (SystemExit, KeyboardInterrupt):
                raise
            except Exception as e:
                # Mosaic/Pallas-specific signatures ONLY: a plain HBM
                # RESOURCE_EXHAUSTED (model simply too big for the
                # chip) must surface as itself, not be misattributed
                # to --attention-chunk (r5 ADVICE low) — so no bare
                # "resource_exhausted"/"scoped" matches here
                compile_like = any(
                    sig in str(e).lower() for sig in
                    ("mosaic", "vmem", "pallas"))
                if (batch_idx == start_step and compile_like
                        and getattr(args, "attention_chunk", 0)):
                    # first step = compile.  --attention-chunk 32
                    # lands exactly on the fused backward's head-gate
                    # edge (_FUSED_BWD_MAX_HEADS); an on-chip Mosaic
                    # scoped-vmem rejection must surface as a named
                    # CLI error like the other temporal knobs, not a
                    # raw compiler traceback (r4 ADVICE #2).  Gated on
                    # the failure text so an unrelated first-step
                    # error is not misattributed to the knob
                    raise SystemExit(
                        f"--attention-chunk "
                        f"{args.attention_chunk}: the chunked "
                        f"attention program failed to compile on "
                        f"this backend: {e} (a Mosaic scoped-vmem "
                        f"rejection at the fused-backward head gate "
                        f"is the known trip — drop --attention-chunk "
                        f"to take the always-correct two-sweep "
                        f"route)")
                raise
            if guard and not _finite(new_loss):
                # divergence: discard this update, roll back to the
                # last durable state (its true step label comes back
                # with it), move on to the NEXT batch — the
                # controller-side analogue is the rate-limited requeue
                restores += 1
                logger.warning(
                    "non-finite loss on batch %d (restore %d/%d)",
                    batch_idx + 1, restores, max_restores)
                if restores > max_restores:
                    raise SystemExit(
                        f"training diverged: {max_restores} restores "
                        f"exhausted at batch {batch_idx + 1}")
                if ckpt is not None and ckpt.latest_step() is not None:
                    step_label, params, opt_state = ckpt.restore(model)
                else:
                    step_label = 0
                    params = model.init_params(key)
                    opt_state = model.init_opt_state(params)
                continue
            params, opt_state, loss = new_params, new_opt, new_loss
            step_label += 1
            if (ckpt is not None and args.save_every > 0
                    and step_label % args.save_every == 0):
                ckpt.save(step_label, params, opt_state)
            if eval_every and step_label % eval_every == 0:
                logger.info(
                    "step %d eval_loss %.5f", step_label,
                    float(eval_loss(params, *eval_data)))
            if (batch_idx + 1 - start_step) % max(
                    1, args.steps // 10) == 0:
                logger.info("step %d loss %.5f", step_label,
                            float(loss))
    finally:
        if profile_dir:
            jax.block_until_ready(loss)
            jax.profiler.stop_trace()
            logger.info("profiler trace written to %s", profile_dir)

    if ckpt is not None:
        # the periodic save may already hold this exact step (orbax
        # raises StepAlreadyExistsError on a duplicate save)
        if ckpt.latest_step() != step_label:
            ckpt.save(step_label, params, opt_state, wait=True)
        ckpt.close()
    from ..compat import registry as _compat_registry
    print(json.dumps({"step": step_label, "model": args.model,
                      "loss": float(loss) if loss is not None else None,
                      "backend": jax.default_backend(),
                      # which degradation rung the kernels actually ran
                      # on (compat/capability.py ladder)
                      "rung": _compat_registry.attention_rung(),
                      **({"preempted": True} if preempted else {})}))
    # --preempt-exit lets a k8s Job distinguish "cut short" from
    # "complete": with restartPolicy OnFailure an exit-0 preemption
    # would mark the Job Succeeded at step 100 of 5000 and training
    # would never resume (config/samples/train-job.yaml passes 75,
    # EX_TEMPFAIL, so the kubelet restarts the container and the run
    # resumes from the checkpoint); the interactive default stays 0
    if preempted:
        return getattr(args, "preempt_exit", 0)
    return 0


def _finite(loss) -> bool:
    import math

    return math.isfinite(float(loss))


def _eval_fns(args, model, jax):
    """(make(key) -> loss-argument tuple, jitted loss, jitted forward)
    for the family ``args`` selects — the single place the held-out
    batch law lives, shared by ``eval`` and ``train --eval-every``.
    ``make`` always returns the tuple ``loss(params, *data)`` expects
    (temporal: (window, batch); snapshot families: (batch,)), so
    callers never re-dispatch per family."""
    if args.model == "temporal":
        from ..models.temporal import synthetic_window

        def make(key):
            return synthetic_window(
                key, steps=args.window, groups=args.groups,
                endpoints=args.endpoints,
                per_step=model.supervision == "sequence")
    elif args.model == "moe":
        from ..models.moe import synthetic_moe_batch

        def make(key):
            return (synthetic_moe_batch(
                key, groups=args.groups, endpoints=args.endpoints,
                n_regions=args.experts),)
    else:
        from ..models.traffic import synthetic_batch

        def make(key):
            return (synthetic_batch(key, groups=args.groups,
                                    endpoints=args.endpoints),)

    return make, jax.jit(model.loss), jax.jit(model.forward)


def run_eval(args) -> int:
    """Held-out evaluation: mean loss + plan quality on fresh
    synthetic fleets drawn from a key stream disjoint from training's.

    Plan quality is the masked L1 distance between the NORMALIZED
    integer weight plan and the target weight distribution, with the
    uniform-over-valid plan as the baseline a trained model must beat
    — the number an operator checks before pointing
    ``controller --policy-checkpoint`` at a checkpoint."""
    import numpy as np

    from ..jaxenv import import_jax

    if args.batches < 1:
        raise SystemExit("--batches must be >= 1")
    jax = import_jax()
    import jax.numpy as jnp

    model, _, _ = _build_model(args)
    step = 0
    if args.ckpt:
        import os

        from ..models.checkpoint import TrainCheckpointer

        if not os.path.isdir(args.ckpt):
            raise SystemExit(
                f"--ckpt: no checkpoint found under {args.ckpt}")
        try:
            with TrainCheckpointer(args.ckpt, create=False) as ckpt:
                # params-only: eval must not care which optimizer
                # trained the checkpoint (flat_adam vs adam states
                # have different tree structures)
                step, params = ckpt.restore_params(model)
        except Exception as e:
            # same posture as --policy-checkpoint: a bad artifact gets
            # a named CLI error, not a raw orbax traceback (orbax can
            # raise KeyError/TypeError on tree mismatch, not just
            # OSError/ValueError)
            raise SystemExit(f"--ckpt: failed to restore from "
                             f"{args.ckpt}: {e}")
        logger.info("evaluating step-%d params from %s", step,
                    args.ckpt)
    else:
        params = model.init_params(jax.random.PRNGKey(args.seed))

    temporal = args.model == "temporal"
    make, loss_fn, fwd = _eval_fns(args, model, jax)

    @jax.jit
    def plan_l1(weights, mask, target):
        w = weights.astype(jnp.float32)
        denom = jnp.sum(jnp.where(mask, w, 0.0), axis=-1,
                        keepdims=True)
        p = jnp.where(mask & (denom > 0), w / jnp.maximum(denom, 1.0),
                      0.0)
        valid = jnp.sum(mask, axis=-1, keepdims=True)
        uniform = jnp.where(mask, 1.0 / jnp.maximum(valid, 1), 0.0)
        l1 = jnp.sum(jnp.abs(p - target) * mask, axis=-1)
        u1 = jnp.sum(jnp.abs(uniform - target) * mask, axis=-1)
        any_valid = jnp.any(mask, axis=-1)
        n = jnp.maximum(jnp.sum(any_valid), 1)
        return (jnp.sum(jnp.where(any_valid, l1, 0.0)) / n,
                jnp.sum(jnp.where(any_valid, u1, 0.0)) / n)

    losses, l1s, u1s = [], [], []
    base = jax.random.fold_in(jax.random.PRNGKey(args.seed), 10_000)
    for i in range(args.batches):
        data = make(jax.random.fold_in(base, i))
        batch = data[-1]
        losses.append(float(loss_fn(params, *data)))
        if temporal:
            weights = fwd(params, data[0], batch.mask)
            # plan quality is a LAST-step notion; under sequence
            # supervision compare against the final step's target
            target = (batch.target[-1]
                      if model.supervision == "sequence"
                      else batch.target)
        else:
            weights = fwd(params, batch.features, batch.mask)
            target = batch.target
        l1, u1 = plan_l1(weights, batch.mask, target)
        l1s.append(float(l1))
        u1s.append(float(u1))

    from ..compat import registry as _compat_registry
    out = {
        "model": args.model,
        "step": step,
        "batches": args.batches,
        "mean_loss": round(float(np.mean(losses)), 6),
        "plan_l1": round(float(np.mean(l1s)), 6),
        "uniform_l1": round(float(np.mean(u1s)), 6),
        "beats_uniform": bool(np.mean(l1s) < np.mean(u1s)),
        "rung": _compat_registry.attention_rung(),
    }
    json.dump(out, sys.stdout)
    print()
    return 0


def run_plan(args) -> int:
    try:
        return _run_plan(args)
    finally:
        _close_loaders()


def _run_plan(args) -> int:
    from ..jaxenv import import_jax
    jax = import_jax()

    model, _, run_plan_fwd = _build_model(args)
    if args.ckpt:
        import os

        from ..models.checkpoint import TrainCheckpointer
        if not os.path.isdir(args.ckpt):
            # create=False + pre-check: a typo'd path must neither
            # litter an empty orbax tree nor die in a raw traceback
            # (the run_eval posture)
            raise SystemExit(
                f"--ckpt: no checkpoint found under {args.ckpt}")
        try:
            with TrainCheckpointer(args.ckpt, create=False) as ckpt:
                # params-only (optimizer-structure agnostic)
                step, params = ckpt.restore_params(model)
        except (SystemExit, KeyboardInterrupt):
            raise
        except Exception as e:
            raise SystemExit(
                f"--ckpt: failed to restore from {args.ckpt}: {e}")
        logger.info("planning with step-%d params from %s", step,
                    args.ckpt)
    else:
        params = model.init_params(jax.random.PRNGKey(args.seed))

    weights = run_plan_fwd(params, jax.random.PRNGKey(args.seed + 1))
    from ..compat import registry as _compat_registry
    out = {
        "groups": args.groups,
        "endpoints": args.endpoints,
        "rung": _compat_registry.attention_rung(),
        # int weights in [0, 255], 0 on padded slots -- the values
        # UpdateEndpointWeight would apply per endpoint
        "weights": [[int(w) for w in row] for row in weights],
    }
    json.dump(out, sys.stdout)
    print()
    return 0
