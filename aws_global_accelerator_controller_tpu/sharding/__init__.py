"""Sharded fleet ownership: the key-space partition behind scale-out.

ROADMAP item 1: one process, one set of workqueues, one coalescer is
the last single-process bottleneck.  This package is the *ownership*
half of the fix — N replicas splitting the reconcile key space must
never produce two writers for one endpoint group or hosted zone,
across crashes, deposals and membership churn (the fault-tolerant
dynamic-membership shape of Prime's collective library, PAPERS.md:
peers join/leave mid-run, the group rebalances and continues).

Two layers:

- :mod:`.hashmap` — the pure math: a stable ``shard_of(key, S)``
  partition of container keys into S shards, and a rendezvous
  (highest-random-weight) ``shard → replica`` map over the live member
  set, so membership churn moves only the affected shards (~1/N of
  keys on a join; exactly the dead replica's shards on a leave).
- :mod:`.shardset` — the runtime object: one
  :class:`~..resilience.fence.MutationFence` per shard (armed per
  lease term by the shard-lease manager,
  leaderelection/shards.py), the owned-shard set, the dispatch route
  context, and ``check(container_key)`` — the write-side ownership
  assertion lint rule L110 keeps at every mutation chokepoint.

Routing contract (ARCHITECTURE.md "Sharded ownership"): every
mutation routes by the hash of its *AWS-side container* — the
endpoint-group ARN a binding names in its spec, the hosted-zone /
accelerator container falling back to the owning OBJECT key
pre-creation (and staying there for the container's life, so a
resource never migrates shards mid-operation).  Intents go to the
owning shard's coalescer cohort (cloudprovider/aws/batcher.py
``ShardedCoalescer``), the way Cloud Collectives (PAPERS.md) reorders
ranks so traffic stays inside cheap domains.
"""
from .hashmap import compute_assignment, rendezvous_owner, shard_of
from .shardset import (
    ShardNotOwnedError,
    ShardSet,
    current_route_shard,
)

__all__ = [
    "ShardNotOwnedError",
    "ShardSet",
    "compute_assignment",
    "current_route_shard",
    "rendezvous_owner",
    "shard_of",
]
