"""The shard map math: stable key partition + rendezvous ownership.

Two independent mappings compose into "which replica owns this key":

1. ``shard_of(key, S)`` — container key → shard id.  A pure crc32
   partition (crc32, not ``hash()``: str hashes are salted per process
   and the whole point is that every replica, and every restart,
   computes the SAME shard for the same key).  S is a deployment
   constant (``--shards``), so a plain modulo is the consistent hash:
   keys never move between shards while the deployment shape holds.

2. ``rendezvous_owner(shard, members)`` — shard id → replica identity
   via highest-random-weight hashing over the live member set.  The
   property that makes rebalancing safe AND cheap: when a member
   joins, each shard independently re-evaluates and only the shards
   whose max moved to the newcomer migrate (~S/N of them); when a
   member dies, exactly the dead member's shards move (every other
   shard's max is unchanged) — no global reshuffle, no coordination
   beyond agreeing on the member list.

Both are deterministic across processes — the chaos/e2e suites and the
multi-process shard-scaling bench rely on replicas agreeing on the map
without ever talking to each other about it.
"""
from __future__ import annotations

import zlib
from typing import Dict, Sequence


def shard_of(key: str, num_shards: int) -> int:
    """Stable shard id of a container key in ``[0, num_shards)``."""
    if num_shards <= 1:
        return 0
    return zlib.crc32(key.encode()) % num_shards


def rendezvous_owner(shard_id: int,
                     members: Sequence[str]) -> "str | None":
    """The member that owns ``shard_id`` under highest-random-weight
    hashing, or None when the member set is empty.  Ties (crc32
    collisions) break by identity so every replica agrees."""
    best = None
    best_weight = -1
    for member in members:
        weight = zlib.crc32(f"{member}\x00{shard_id}".encode())
        if weight > best_weight or (weight == best_weight
                                    and (best is None or member < best)):
            best = member
            best_weight = weight
    return best


def compute_assignment(num_shards: int,
                       members: Sequence[str]) -> Dict[int, "str | None"]:
    """shard id → owning member for the whole map (the rebalance
    target the shard-lease manager converges toward)."""
    return {s: rendezvous_owner(s, members) for s in range(num_shards)}
