"""The shard map math: stable key partition + rendezvous ownership.

Two independent mappings compose into "which replica owns this key":

1. ``shard_of(key, S)`` — container key → shard id.  A pure crc32
   partition (crc32, not ``hash()``: str hashes are salted per process
   and the whole point is that every replica, and every restart,
   computes the SAME shard for the same key).  S is a deployment
   constant (``--shards``), so a plain modulo is the consistent hash:
   keys never move between shards while the deployment shape holds.

2. ``rendezvous_owner(shard, members)`` — shard id → replica identity
   via highest-random-weight hashing over the live member set.  The
   property that makes rebalancing safe AND cheap: when a member
   joins, each shard independently re-evaluates and only the shards
   whose max moved to the newcomer migrate (~S/N of them); when a
   member dies, exactly the dead member's shards move (every other
   shard's max is unchanged) — no global reshuffle, no coordination
   beyond agreeing on the member list.

Both are deterministic across processes — the chaos/e2e suites and the
multi-process shard-scaling bench rely on replicas agreeing on the map
without ever talking to each other about it.

Topology-weighted placement (ISSUE 14): ``rendezvous_owner`` takes an
optional ``weights(shard_id, member)`` scoring term — WEIGHTED
highest-random-weight hashing (the -w/ln(u) construction), so a member
whose home region is near the regions a shard's keys mutate wins more
hash mass ("reorder ranks so traffic stays inside cheap domains",
Cloud Collectives via PAPERS.md; topology/placement.py computes the
weights from observed mutation profiles).  ``weights=None`` is the
EXACT pre-topology integer-compare path, byte-identical — the
contract tests/test_topology.py pins.  ``compute_assignment`` bounds
voluntary (affinity-driven) rebalance churn against a previous map;
moves forced by membership change are never capped.
"""
from __future__ import annotations

import math
import zlib
from typing import Callable, Dict, Optional, Sequence


def shard_of(key: str, num_shards: int) -> int:
    """Stable shard id of a container key in ``[0, num_shards)``."""
    if num_shards <= 1:
        return 0
    return zlib.crc32(key.encode()) % num_shards


def rendezvous_owner(shard_id: int, members: Sequence[str],
                     weights: Optional[Callable[[int, str], float]]
                     = None) -> "str | None":
    """The member that owns ``shard_id`` under highest-random-weight
    hashing, or None when the member set is empty.  Ties (crc32
    collisions) break by identity so every replica agrees.

    With ``weights``, the hash draw u = crc32/2^32 is stretched to
    score = -w / ln(u): monotone in both u and w, so the unweighted
    ordering is preserved at equal weights while a 2x weight wins ~2x
    the shards — and a weight change moves ONLY the shards whose max
    flips (the rendezvous minimal-disruption property survives
    weighting)."""
    if weights is None:
        best = None
        best_weight = -1
        for member in members:
            weight = zlib.crc32(f"{member}\x00{shard_id}".encode())
            if weight > best_weight or (weight == best_weight
                                        and (best is None
                                             or member < best)):
                best = member
                best_weight = weight
        return best
    best = None
    best_score = None
    for member in members:
        draw = zlib.crc32(f"{member}\x00{shard_id}".encode())
        # (draw + 0.5) / 2^32 is in (0, 1): ln never sees 0 or 1
        u = (draw + 0.5) / 2**32
        w = max(float(weights(shard_id, member)), 1e-9)
        score = -w / math.log(u)
        if best is None or score > best_score \
                or (score == best_score and member < best):
            best = member
            best_score = score
    return best


def compute_assignment(num_shards: int, members: Sequence[str],
                       weights: Optional[Callable[[int, str], float]]
                       = None,
                       prev: Optional[Dict[int, "str | None"]] = None,
                       max_moves: Optional[int] = None,
                       gain: Optional[Callable[[int, str], float]]
                       = None) -> Dict[int, "str | None"]:
    """shard id → owning member for the whole map (the rebalance
    target the shard-lease manager converges toward).

    ``prev`` + ``max_moves`` bound VOLUNTARY churn: a shard whose
    previous owner is still a live member only moves when it is among
    the ``max_moves`` highest-gain moves this pass (``gain(shard,
    member)`` scores the improvement; the affinity delta by default) —
    a learned-profile shift migrates the fleet incrementally instead
    of in one wave.  Shards whose previous owner left the member set
    always move (that is failure recovery, not tuning)."""
    want = {s: rendezvous_owner(s, members, weights)
            for s in range(num_shards)}
    if prev is None or max_moves is None:
        return want
    live = set(members)
    voluntary = [s for s, owner in want.items()
                 if prev.get(s) is not None and prev[s] != owner
                 and prev[s] in live]
    if len(voluntary) <= max_moves:
        return want
    score = gain if gain is not None else (
        weights if weights is not None else (lambda s, m: 0.0))

    def move_gain(s: int) -> float:
        new_owner = want[s]
        old_owner = prev[s]
        if new_owner is None:
            return 0.0
        return score(s, new_owner) - score(s, old_owner)

    voluntary.sort(key=lambda s: (-move_gain(s), s))
    for s in voluntary[max_moves:]:
        want[s] = prev[s]
    return want
