"""Runtime shard ownership: per-shard fences, the owned set, and the
write-side ownership assertion.

One :class:`ShardSet` per process (built by the cloud factory, shared
by the controllers, the sharded coalescer and the shard-lease
manager).  Three ownership modes:

- **standalone** (the default, ``num_shards=1`` or no manager): every
  shard is owned from birth with its fence armed at token 0 — the
  single-process deployment is the degenerate S=1 case and behaves
  byte-for-byte like the pre-sharding tree.
- **static** (``--shard-id K``): exactly shard K is owned, no leases —
  the bench worker / operator-pinned shape.
- **managed** (``--shard-id auto`` under ``--shards N > 1``): the
  shard-lease manager (leaderelection/shards.py) acquires and releases
  shards as membership changes; nothing is owned until a lease is won.

The write-side contract (lint rule L110): every mutation chokepoint —
the sharded coalescer's submit and every bare AWS write in the
provider — passes through :meth:`ShardSet.check`, which resolves the
container key to its shard, rejects it when this replica does not own
that shard (:class:`ShardNotOwnedError`, a no-retry drop: the owner
converges the key) and then consults the shard's
:class:`~..resilience.fence.MutationFence` — so a shard whose lease
was lost mid-flight rejects exactly like a deposed leader did in the
single-lease world (PR 6), per shard.

Route context: the reconcile dispatch wraps every sync in
:meth:`ShardSet.guard` with the controller's routing key.  The guard
(a) drops syncs for unowned keys before any provider call, (b) marks
the thread with the governing shard so mutation intents planned inside
resolve to the SAME shard their dispatch was routed by (the
GlobalAccelerator controller's endpoint groups hash by their owning
object's key — the pre-creation fallback kept for the container's
life), and (c) pushes the shard's fence into the resilient wrapper's
write-fence TLS so even a retry sleeping across a lease loss is
rejected per attempt (resilience/wrapper.py).
"""
from __future__ import annotations

import logging
import threading
from contextlib import contextmanager
from typing import Callable, List, Optional, Set

from ..analysis import locks
from ..errors import NoRetryError
from ..resilience.fence import MutationFence, push_write_fence
from .hashmap import shard_of

logger = logging.getLogger(__name__)

_route_tls = threading.local()


def current_route_shard() -> Optional[int]:
    """The shard governing the sync on this thread's stack (set by
    :meth:`ShardSet.guard`); None outside any routed dispatch."""
    return getattr(_route_tls, "shard", None)


class ShardNotOwnedError(NoRetryError):
    """A mutation (or a dispatched sync) targets a shard this replica
    does not own.  No-retry by type: requeueing would re-reject — the
    owning replica converges the key on its own watch."""

    def __init__(self, shard: int, key: str):
        super().__init__(
            f"shard {shard} not owned by this replica "
            f"(container key {key!r})")
        self.shard = shard
        self.key = key


class ShardSet:
    """Per-process shard ownership state (module docstring)."""

    def __init__(self, num_shards: int = 1,
                 process_fence: Optional[MutationFence] = None):
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.num_shards = num_shards
        # the process-lifecycle fence (ordered shutdown) — composed
        # with each shard's own fence at the write chokepoints
        self.process_fence = process_fence
        self._lock = locks.make_lock("shardset")
        # guarded-by: external: built once here; fences are
        # internally synchronized, the list is never rebound
        self._fences: List[MutationFence] = [
            MutationFence(name=f"shard-{i}") for i in range(num_shards)]
        # standalone until a manager (or --shard-id) claims otherwise:
        # everything owned, fences armed at token 0
        self._owned: Set[int] = set(range(num_shards))  # guarded-by: self._lock
        self._managed = False  # guarded-by: self._lock
        # listeners: fn(event, shard_id) with event "acquired"/"lost";
        # called OUTSIDE the lock, on the transitioning thread
        # guarded-by: self._lock
        self._listeners: List[Callable[[str, int], None]] = []

    # -- mode -----------------------------------------------------------

    def set_managed(self) -> None:
        """Enter lease-managed mode: nothing is owned until the shard
        lease manager acquires it."""
        with self._lock:
            self._managed = True
            self._owned.clear()

    def set_static_owner(self, shard_id: int) -> None:
        """Own exactly ``shard_id`` statically (``--shard-id K``)."""
        self._index(shard_id)
        with self._lock:
            self._managed = True
            self._owned = {shard_id}

    def is_managed(self) -> bool:
        with self._lock:
            return self._managed

    # -- map ------------------------------------------------------------

    def _index(self, shard_id: int) -> int:
        if not 0 <= shard_id < self.num_shards:
            raise ValueError(
                f"shard {shard_id} out of range [0, {self.num_shards})")
        return shard_id

    def shard_of(self, container_key: str) -> int:
        return shard_of(container_key, self.num_shards)

    def resolve(self, container_key: str) -> int:
        """The shard governing a mutation for ``container_key``: the
        dispatch route context when a routed sync is on this thread's
        stack (so a sync's writes ride the shard its dispatch was
        admitted under), else the container hash."""
        ctx = current_route_shard()
        return ctx if ctx is not None else self.shard_of(container_key)

    def fence(self, shard_id: int) -> MutationFence:
        return self._fences[self._index(shard_id)]

    def owns(self, shard_id: int) -> bool:
        with self._lock:
            return shard_id in self._owned

    def owns_key(self, container_key: str) -> bool:
        with self._lock:
            return self.shard_of(container_key) in self._owned

    def owned_shards(self) -> Set[int]:
        with self._lock:
            return set(self._owned)

    def token(self, shard_id: int) -> int:
        return self.fence(shard_id).token

    # -- ownership transitions (the shard-lease manager's surface) ------

    def add_listener(self, fn: Callable[[str, int], None]) -> None:
        """Register an ownership-change listener (``fn(event, shard)``
        with event ``"acquired"``/``"lost"``).  Controllers use this to
        re-deliver a freshly acquired shard's keys and to drop a lost
        shard's fingerprints/backlog."""
        with self._lock:
            self._listeners.append(fn)

    def _notify(self, event: str, shard_id: int) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(event, shard_id)
            except Exception:
                logger.exception("shard %s listener failed for shard %d",
                                 event, shard_id)

    def acquire(self, shard_id: int, token: int) -> None:
        """Own ``shard_id`` for a new lease term: arm its fence with
        the term's fencing token (monotone per shard — the lease's
        ``lease_transitions``), then mark owned and notify."""
        self.fence(shard_id).arm(token)
        with self._lock:
            already = shard_id in self._owned
            self._owned.add(shard_id)
        if not already:
            self._notify("acquired", shard_id)

    def release(self, shard_id: int) -> None:
        """Stop owning ``shard_id``.  The caller (the shard-lease
        manager) is responsible for the fence ordering — seal BEFORE
        release on every loss path, so no write can land between
        losing ownership and the successor's first."""
        self._index(shard_id)
        with self._lock:
            had = shard_id in self._owned
            self._owned.discard(shard_id)
        if had:
            self._notify("lost", shard_id)

    # -- the write-side assertion (lint rule L110) ----------------------

    def check(self, container_key: str, surface: str = "write") -> int:
        """The shard-ownership assertion every mutation chokepoint
        passes through: resolve the container's shard, reject when
        unowned, then consult the shard fence (and the process fence)
        — one lock acquisition each on the open path.  Returns the
        resolved shard id so callers route by EXACTLY the shard the
        assertion admitted (no second resolve to diverge from)."""
        sid = self.resolve(container_key)
        if not self.owns(sid):
            raise ShardNotOwnedError(sid, container_key)
        if self.process_fence is not None:
            self.process_fence.check(surface)
        self._fences[sid].check(surface)
        return sid

    @contextmanager
    def guard(self, route_key: str):
        """Wrap one routed dispatch: admit only owned keys, mark the
        thread with the governing shard, and arm the wrapper's
        per-attempt write gate with the shard's fence.  The governing
        shard and its armed fencing token are stamped onto the
        current span (tracing.py) so a trace names the ownership term
        each sync ran under — the shard-handoff debugging signal."""
        sid = self.shard_of(route_key)
        if not self.owns(sid):
            raise ShardNotOwnedError(sid, route_key)
        prior = getattr(_route_tls, "shard", None)
        _route_tls.shard = sid
        from ..tracing import default_tracer

        span = default_tracer.current()
        if span is not None:
            span.attributes["shard"] = sid
            span.attributes["fence_token"] = self._fences[sid].token
        try:
            with push_write_fence(self._fences[sid]):
                yield sid
        finally:
            _route_tls.shard = prior
