"""Seeded scenario fuzzer: deterministic workload scripts under
virtual time (ISSUE 15).

Every hand-written bench leg measures ONE workload shape — the shape
its knobs were tuned for.  The fuzzer closes that gap: a (family,
seed) pair expands to a fully deterministic *workload script* — a
time-ordered list of actions (creates, deletes, annotation flaps,
out-of-band drift edits, region partitions) over virtual seconds —
and the runner replays it against a fresh control plane under the
PR-13 virtual clock.  Same seed ⇒ byte-identical script, and (by the
determinism contract the virtual clock + seeded chaos engines carry)
byte-identical decision logs and convergence ledger when replayed:
``hack/fuzz_replay.py`` re-runs a recorded scenario from nothing but
its seed and diffs the ledgers.

Scenario families (the workload shapes ROADMAP item 5 names):

- ``bursty-creates``    quiet line punctuated by dense create bursts
- ``delete-waves``      a converged fleet hit by waves of deletions
                        (with partial recreates)
- ``flapping-updates``  annotation values flapping A→B→A in gusts
- ``zone-skewed-churn`` churn concentrated 80/20 onto one hosted
                        zone, under that zone's per-call rate limit
- ``slow-drip-drift``   out-of-band record re-points trickling in —
                        the workload the drift sweep's period is
                        tuned against
- ``mixed-region-storm``a 3-region fleet, fleet-wide touch storms,
                        one partition/heal cycle mid-storm

The script is pure data (``canonical_json``) generated from a
``random.Random`` seeded by crc32(family:seed) — no wall clock, no
ambient state — so generation itself is replayable cross-process.
The runner measures what the adaptive-vs-static A/B needs: makespan
to full convergence, p99 event→converged per class (the raw latency
sink), wire mutation calls, and per-drift repair lag.
"""
from __future__ import annotations

import json
import random
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import clock as simclock

FAMILIES = (
    "bursty-creates",
    "delete-waves",
    "flapping-updates",
    "zone-skewed-churn",
    "slow-drip-drift",
    "mixed-region-storm",
)

REGIONS = ("us-west-2", "eu-west-1", "ap-northeast-1")
FLAP_ANNOTATION = "fuzz.agac/round"


@dataclass(frozen=True)
class Action:
    """One scripted step: at virtual second ``t`` (from scenario
    start), apply ``op`` to service ``name``.  ``params`` is the
    op-specific payload as sorted (key, value) pairs — hashable,
    canonically serializable."""

    t: float
    op: str       # create | delete | update | drift_record |
    #               partition | heal
    name: str = ""
    params: Tuple = ()

    def param(self, key: str, default=None):
        return dict(self.params).get(key, default)


@dataclass
class ScenarioScript:
    """A generated workload: pure data, replayable from (family,
    seed) alone.  ``env`` carries the scenario's environment knobs
    (per-call latency, a zone rate limit, regions) — part of the
    script so a replay reconstructs the same world."""

    family: str
    seed: int
    duration: float
    n_services: int
    env: Dict[str, object] = field(default_factory=dict)
    actions: List[Action] = field(default_factory=list)

    @property
    def spec(self) -> str:
        """The replay handle: everything needed to regenerate."""
        return f"{self.family}:{self.seed}"

    def canonical_json(self) -> str:
        return json.dumps(
            {"family": self.family, "seed": self.seed,
             "duration": self.duration, "n_services": self.n_services,
             "env": self.env,
             "actions": [[round(a.t, 6), a.op, a.name,
                          list(map(list, a.params))]
                         for a in self.actions]},
            sort_keys=True)


def _hostname(name: str, region: str) -> str:
    return f"{name}-0123456789abcdef.elb.{region}.amazonaws.com"


def _rng(family: str, seed: int) -> random.Random:
    # crc32 folding keeps the derivation cross-process deterministic
    # and family-decorrelated (seed 7's bursty run shares nothing
    # with seed 7's delete waves)
    return random.Random(zlib.crc32(f"{family}:{seed}".encode()))


def generate(family: str, seed: int, n_services: int = 24,
             duration: float = 90.0) -> ScenarioScript:
    """Expand (family, seed) into a deterministic workload script.
    Pure: no clocks, no I/O, no ambient randomness."""
    if family not in FAMILIES:
        raise ValueError(f"unknown scenario family {family!r} "
                         f"(known: {', '.join(FAMILIES)})")
    rng = _rng(family, seed)
    script = ScenarioScript(family=family, seed=seed,
                            duration=duration, n_services=n_services)
    build = globals()["_gen_" + family.replace("-", "_")]
    build(script, rng)
    # time-ordered with a deterministic tiebreak: the runner replays
    # strictly by (t, sequence), so generation order never leaks into
    # replay order
    script.actions.sort(key=lambda a: (a.t, a.op, a.name, a.params))
    return script


# -- family generators ------------------------------------------------------


def _spread_creates(script: ScenarioScript, rng: random.Random,
                    t0: float, t1: float, zone_of=None,
                    region_of=None) -> None:
    for i in range(script.n_services):
        name = f"fz{i:04d}"
        region = region_of(i, rng) if region_of else REGIONS[0]
        zone = zone_of(i, rng) if zone_of else 0
        script.actions.append(Action(
            round(rng.uniform(t0, t1), 3), "create", name,
            (("hostname", _hostname(name, region)),
             ("region", region), ("zone", zone))))


def _gen_bursty_creates(script: ScenarioScript,
                        rng: random.Random) -> None:
    """Dense create bursts on a quiet line: the shape the coalescer's
    linger trades latency against — a fixed short linger flushes each
    burst as many tiny zone calls."""
    script.env = {"call_latency": 0.004, "zone_rate": 2.0,
                  "zones": 1}
    bursts = 4 + rng.randrange(3)
    per = max(1, script.n_services // bursts)
    i = 0
    for b in range(bursts):
        t = round(rng.uniform(2.0, script.duration * 0.6), 3)
        for _ in range(per):
            if i >= script.n_services:
                break
            name = f"fz{i:04d}"
            script.actions.append(Action(
                round(t + rng.uniform(0.0, 0.4), 3), "create", name,
                (("hostname", _hostname(name, REGIONS[0])),
                 ("region", REGIONS[0]), ("zone", 0))))
            i += 1
    while i < script.n_services:
        name = f"fz{i:04d}"
        script.actions.append(Action(
            round(rng.uniform(2.0, script.duration * 0.6), 3),
            "create", name,
            (("hostname", _hostname(name, REGIONS[0])),
             ("region", REGIONS[0]), ("zone", 0))))
        i += 1


def _gen_delete_waves(script: ScenarioScript,
                      rng: random.Random) -> None:
    """Converge a fleet, then delete it in waves (some services
    recreated between waves): record-set DELETE batches per zone."""
    script.env = {"call_latency": 0.004, "zone_rate": 2.0,
                  "zones": 1}
    _spread_creates(script, rng, 1.0, 6.0)
    waves = 3
    names = [f"fz{i:04d}" for i in range(script.n_services)]
    rng.shuffle(names)
    per = max(1, len(names) // waves)
    for w in range(waves):
        t = round(20.0 + w * 18.0 + rng.uniform(0.0, 3.0), 3)
        chunk = names[w * per:(w + 1) * per]
        for name in chunk:
            script.actions.append(Action(
                round(t + rng.uniform(0.0, 0.5), 3), "delete", name))
        # a few come back: churn, not a clean teardown
        for name in rng.sample(chunk, max(1, len(chunk) // 4)):
            script.actions.append(Action(
                round(t + 6.0 + rng.uniform(0.0, 1.0), 3),
                "create", name,
                (("hostname", _hostname(name, REGIONS[0])),
                 ("region", REGIONS[0]), ("zone", 0))))


def _gen_flapping_updates(script: ScenarioScript,
                          rng: random.Random) -> None:
    """Annotation values flapping in gusts over a converged fleet:
    most record re-ensures FOLD (last-writer-wins) when the linger
    holds a gust's cohort together."""
    script.env = {"call_latency": 0.004, "zone_rate": 2.0,
                  "zones": 1}
    _spread_creates(script, rng, 1.0, 6.0)
    gusts = 6
    for g in range(gusts):
        t = round(18.0 + g * 9.0 + rng.uniform(0.0, 2.0), 3)
        flappers = rng.sample(range(script.n_services),
                              max(2, script.n_services // 3))
        for i in flappers:
            for r in range(2 + rng.randrange(2)):
                script.actions.append(Action(
                    round(t + r * 0.3 + rng.uniform(0.0, 0.2), 3),
                    "update", f"fz{i:04d}",
                    (("annotation", FLAP_ANNOTATION),
                     ("value", f"g{g}r{r}"))))


def _gen_zone_skewed_churn(script: ScenarioScript,
                           rng: random.Random) -> None:
    """Create/delete churn with 80% of services homed in ONE hosted
    zone that enforces its per-call rate limit: the workload where
    per-zone batching is the difference between converging and
    thrashing."""
    script.env = {"call_latency": 0.004, "zone_rate": 2.5,
                  "zones": 3}

    def zone_of(i, r):
        return 0 if r.random() < 0.8 else 1 + r.randrange(2)

    _spread_creates(script, rng, 1.0, 8.0, zone_of=zone_of)
    for _ in range(script.n_services):
        i = rng.randrange(script.n_services)
        t = round(rng.uniform(20.0, script.duration * 0.75), 3)
        name = f"fz{i:04d}"
        script.actions.append(Action(t, "delete", name))
        script.actions.append(Action(
            round(t + 4.0 + rng.uniform(0.0, 2.0), 3), "create", name,
            (("hostname", _hostname(name, REGIONS[0])),
             ("region", REGIONS[0]), ("zone", zone_of(i, rng)))))


def _gen_slow_drip_drift(script: ScenarioScript,
                         rng: random.Random) -> None:
    """A converged, quiet fleet whose records an outside hand keeps
    re-pointing, one every few virtual seconds: repair latency is
    bounded by the drift-sweep period — the knob this family
    pressures."""
    script.env = {"call_latency": 0.002, "zone_rate": 0.0,
                  "zones": 1}
    _spread_creates(script, rng, 1.0, 5.0)
    t = 25.0
    while t < script.duration * 0.85:
        i = rng.randrange(script.n_services)
        script.actions.append(Action(
            round(t, 3), "drift_record", f"fz{i:04d}",
            (("rogue", f"rogue-{int(t)}"),)))
        t += rng.uniform(3.0, 7.0)


def _gen_mixed_region_storm(script: ScenarioScript,
                            rng: random.Random) -> None:
    """Three regions, zone per region, fleet-wide annotation storms,
    one partial partition/heal mid-storm."""
    script.env = {"call_latency": 0.002, "zone_rate": 0.0,
                  "zones": 3, "regions": list(REGIONS)}

    def region_of(i, r):
        return REGIONS[i % len(REGIONS)]

    _spread_creates(script, rng, 1.0, 8.0,
                    zone_of=lambda i, r: i % len(REGIONS),
                    region_of=region_of)
    for storm in range(2):
        t = round(25.0 + storm * 25.0 + rng.uniform(0.0, 2.0), 3)
        for i in range(script.n_services):
            script.actions.append(Action(
                round(t + rng.uniform(0.0, 1.5), 3), "update",
                f"fz{i:04d}",
                (("annotation", FLAP_ANNOTATION),
                 ("value", f"storm{storm}"))))
    dark = REGIONS[1 + rng.randrange(len(REGIONS) - 1)]
    t_cut = round(30.0 + rng.uniform(0.0, 5.0), 3)
    script.actions.append(Action(
        t_cut, "partition", "", (("region", dark), ("rate", 0.8))))
    script.actions.append(Action(
        round(t_cut + 12.0, 3), "heal", "", (("region", dark),)))


# -- the runner -------------------------------------------------------------


def _record_alias(cloud, zone_id: str, rname: str):
    """Current alias target DNS name of the A record ``rname`` in
    ``zone_id`` — lock-direct fake read: observing the answer must not
    consume fault-schedule draws (the determinism contract)."""
    r53 = cloud.route53
    with r53._lock:  # race: fuzz observation, lock-direct
        for rec in r53._records.get(zone_id, []):
            if rec.type == "A" \
                    and rec.name.rstrip(".") == rname.rstrip("."):
                alias = rec.alias_target
                return alias.dns_name if alias is not None else None
    return None


class ScenarioRunner:
    """Replay one script against a fresh control plane under an
    ACTIVE virtual clock (the caller owns activation — the A/B bench
    and the determinism suite both need to bracket several runs).

    Builds the world the script's ``env`` names (zones, regions,
    per-call latency, zone rate limit), registers load balancers up
    front (LB registration is the cloud's state, not workload), then
    applies actions at their virtual timestamps and waits for full
    convergence.  Returns the measurement dict described in the
    module docstring."""

    def __init__(self, script: ScenarioScript, workers: int = 2,
                 autotune=None, resync_period: float = 2.0,
                 fault_seed: Optional[int] = None,
                 fingerprints=None,
                 signal_corruption: float = 0.0):
        self.script = script
        self.workers = workers
        self.autotune = autotune
        self.resync_period = resync_period
        self.fault_seed = (script.seed if fault_seed is None
                           else fault_seed)
        self.fingerprints = fingerprints
        # lying-signal chaos (ISSUE 15): garble the autotune signal
        # stream at this rate (FaultInjector.set_signal_corruption) —
        # the e2e proving a corrupted stream freezes, never steers
        self.signal_corruption = signal_corruption

    # the monitor's poll stride (virtual seconds): lock-direct cloud
    # reads, no API draws consumed — cheap and determinism-neutral
    MONITOR_POLL = 0.25

    # REAL seconds to wait for a previous cluster's daemon threads to
    # exit before activating this scenario's machinery: a straggler
    # wandering into the fresh virtual clock perturbs scheduler
    # sequence numbers and breaks replay-identity (the determinism
    # suite's _drain_stragglers, owned here so every caller gets it)
    STRAGGLER_DRAIN_S = 8.0

    @classmethod
    def _drain_stragglers(cls) -> None:
        import threading
        import time as _t

        names = ("-worker-", "informer-", "workqueue-waker-",
                 "event-broadcaster", "-controller",
                 "autotune-engine", "fuzz-monitor")
        deadline = _t.monotonic() + cls.STRAGGLER_DRAIN_S
        while _t.monotonic() < deadline:
            if not [t for t in threading.enumerate()
                    if any(n in (t.name or "") for n in names)]:
                return
            _t.sleep(0.05)

    def run(self) -> dict:
        import sys
        import time

        sys.path.insert(0, "tests")
        from harness import Cluster, wait_until

        from .. import metrics
        from ..apis import (
            AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION,
            AWS_LOAD_BALANCER_TYPE_ANNOTATION,
            ROUTE53_HOSTNAME_ANNOTATION,
        )
        from ..kube.objects import (
            LoadBalancerIngress,
            LoadBalancerStatus,
            ObjectMeta,
            Service,
            ServicePort,
            ServiceSpec,
            ServiceStatus,
        )

        from ..tracing import default_ledger

        # convergence-ledger window: the records this scenario adds
        # are the replay tool's diff surface (hack/fuzz_replay.py) —
        # the same byte-identical contract the determinism suite
        # asserts (tests/chaos/test_chaos_determinism.py)
        ledger_before = len(default_ledger.snapshot(limit=100000))
        self._drain_stragglers()
        script = self.script
        env = script.env
        regions = env.get("regions")
        topology = None
        if regions:
            from ..topology import RegionTopology

            topology = RegionTopology(list(regions),
                                      seed=self.fault_seed,
                                      intra_latency=0.0005,
                                      cross_latency=0.01)
        cluster = Cluster(workers=self.workers, queue_qps=1e6,
                          queue_burst=10**6,
                          resync_period=self.resync_period,
                          fault_seed=self.fault_seed,
                          topology=topology,
                          fingerprints=self.fingerprints,
                          autotune=self.autotune)
        cloud = cluster.cloud
        n_zones = int(env.get("zones", 1))
        zones = []
        for z in range(n_zones):
            region = (regions[z % len(regions)] if regions
                      else None)
            zones.append(cloud.route53.create_hosted_zone(
                f"z{z}.fuzz.example.com",
                **({"region": region} if region else {})))
        # LB registration is world state: everything the script may
        # ever create gets its NLB up front, so action replay is pure
        # kube-plane traffic
        for a in script.actions:
            if a.op == "create":
                cloud.elb.register_load_balancer(
                    a.name, a.param("hostname"),
                    a.param("region", REGIONS[0]))
        if env.get("call_latency"):
            cloud.faults.set_latency("*", float(env["call_latency"]))
        if env.get("zone_rate"):
            cloud.faults.set_zone_throttle(float(env["zone_rate"]))
        if self.signal_corruption > 0.0:
            cloud.faults.set_signal_corruption(self.signal_corruption)

        def svc_for(a: Action) -> Service:
            name = a.name
            host = f"{name}.z{a.param('zone', 0)}.fuzz.example.com"
            return Service(
                metadata=ObjectMeta(
                    name=name, namespace="default",
                    annotations={
                        AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external",
                        AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION:
                            "true",
                        ROUTE53_HOSTNAME_ANNOTATION: host}),
                spec=ServiceSpec(type="LoadBalancer",
                                 ports=[ServicePort(port=80)]),
                status=ServiceStatus(
                    load_balancer=LoadBalancerStatus(ingress=[
                        LoadBalancerIngress(
                            hostname=a.param("hostname"))])))

        # -- drift monitoring (slow-drip families) ----------------------
        # target record -> (injected_at, expected alias); a monitor
        # thread samples repair lag with lock-direct reads.
        # good_aliases remembers each record's converged alias across
        # injections: a record re-drifted BEFORE its repair landed
        # must not have the rogue value read back as "good" (the
        # monitor would then wait for the corruption forever)
        pending_drift: Dict[Tuple[str, str], Tuple[float, str]] = {}
        good_aliases: Dict[Tuple[str, str], str] = {}
        drift_lags: List[float] = []
        drift_lock = simclock.make_condition()
        monitor_stop = simclock.make_event()

        def record_alias(zone_id: str, rname: str) -> Optional[str]:
            return _record_alias(cloud, zone_id, rname)

        def monitor():
            while not monitor_stop.is_set():
                with drift_lock:
                    items = list(pending_drift.items())
                now = simclock.monotonic()
                for (zone_id, rname), (t0, expected) in items:
                    got = record_alias(zone_id, rname)
                    if got is not None \
                            and got.rstrip(".") == expected.rstrip("."):
                        with drift_lock:
                            pending_drift.pop((zone_id, rname), None)
                        drift_lags.append(now - t0)
                monitor_stop.wait(self.MONITOR_POLL)

        live: Dict[str, Action] = {}
        drift_count = 0
        wall0 = time.perf_counter()
        samples = metrics.arm_latency_sampler()
        reg = metrics.default_registry
        flushes0 = reg.counter_value("provider_mutation_flushes_total")
        enq0 = reg.counter_value("provider_mutations_enqueued_total")
        try:
            cluster.start()
            wait_until(lambda: cluster.handle.informers_synced(),
                       timeout=60.0, message="informers synced")
            mon = simclock.start_thread(monitor, daemon=True,
                                        name="fuzz-monitor")
            t_start = simclock.monotonic()
            for a in script.actions:
                dt = (t_start + a.t) - simclock.monotonic()
                if dt > 0:
                    simclock.sleep(dt)
                self._apply(a, cluster, cloud, zones, topology,
                            svc_for, live, pending_drift, drift_lock,
                            good_aliases)
                if a.op == "drift_record":
                    drift_count += 1

            # -- convergence: every live service's accelerator exists
            # and every injected drift is repaired -------------------
            ga = cloud.ga

            def converged() -> bool:
                with ga._lock:  # race: fuzz observation, lock-direct
                    n_acc = len(ga._accelerators)
                if n_acc != len(live):
                    return False
                with drift_lock:
                    return not pending_drift

            try:
                wait_until(converged, timeout=script.duration * 40,
                           interval=0.5,
                           message=f"{script.family}:{script.seed} "
                                   f"fleet converged")
            except AssertionError as e:
                with ga._lock:  # race: fuzz observation, lock-direct
                    n_acc = len(ga._accelerators)
                with drift_lock:
                    stuck = list(pending_drift)
                raise AssertionError(
                    f"{e}: accelerators={n_acc} live={len(live)} "
                    f"unrepaired_drift={stuck}") from None
            makespan = simclock.monotonic() - t_start
            monitor_stop.set()
            simclock.join_thread(mon, timeout=5.0)
            # the engine's story, captured BEFORE shutdown resets the
            # knobs: what the tuner actually did this scenario (the
            # bench records it into reconcile_history.jsonl)
            engine = cluster.handle.autotune_engine
            knob_trajectory = (engine.registry.trajectory()
                               if engine is not None else None)
            tuner_log = (engine.decision_log()
                         if engine is not None else [])
            chaos_log = cloud.faults.decision_log()
            cluster.shutdown(ordered=True, deadline=15.0)
        finally:
            metrics.disarm_latency_sampler()
            cloud.faults.set_latency("*", 0.0)
            try:
                cluster.shutdown()
            except Exception:
                pass

        interactive = sorted(s for _, k, s in samples
                             if k == "interactive")
        background = sorted(s for _, k, s in samples
                            if k == "background")

        def p99(xs: List[float]) -> Optional[float]:
            if not xs:
                return None
            return xs[min(len(xs) - 1, int(0.99 * len(xs)))]

        events = sum(1 for a in script.actions
                     if a.op in ("create", "delete", "update",
                                 "drift_record"))
        return {
            "family": script.family,
            "seed": script.seed,
            "events": events,
            "services": len(live),
            "makespan_sim_s": round(makespan, 3),
            "throughput_events_per_sim_s":
                round(events / max(makespan, 1e-9), 2),
            "p99_interactive_s": (round(p99(interactive), 4)
                                  if interactive else None),
            "p99_background_s": (round(p99(background), 4)
                                 if background else None),
            "mutation_calls": round(
                reg.counter_value("provider_mutation_flushes_total")
                - flushes0),
            "mutation_intents": round(
                reg.counter_value("provider_mutations_enqueued_total")
                - enq0),
            "drift_injected": drift_count,
            "drift_repair_mean_s": (round(
                sum(drift_lags) / len(drift_lags), 3)
                if drift_lags else None),
            "drift_repair_max_s": (round(max(drift_lags), 3)
                                   if drift_lags else None),
            "wall_s": round(time.perf_counter() - wall0, 2),
            "knob_trajectory": knob_trajectory,
            "tuner_log": tuner_log,
            # the AWS fault engine's ordered decision stream (virtual
            # timestamps): byte-identical across replays of one seed
            "chaos_log": chaos_log,
            # canonical, order-stable ledger slice: what a replay of
            # the same (family, seed) must reproduce byte-identically
            "ledger": [
                [r["key"], r["controller"], r["origin"],
                 sorted(r["stages"].items()), r["total_s"]]
                for r in default_ledger.snapshot(
                    limit=100000)[ledger_before:]],
        }

    def _apply(self, a: Action, cluster, cloud, zones, topology,
               svc_for, live, pending_drift, drift_lock,
               good_aliases) -> None:
        if a.op == "create":
            if a.name in live:
                return   # overlapping churn picked the name twice
            cluster.kube.services.create(svc_for(a))
            live[a.name] = a
        elif a.op == "delete":
            if a.name in live:
                try:
                    cluster.kube.services.delete("default", a.name)
                except Exception:
                    pass
                live.pop(a.name, None)
        elif a.op == "update":
            if a.name not in live:
                return
            try:
                svc = cluster.kube.services.get(
                    "default", a.name).deep_copy()
                svc.metadata.annotations[a.param("annotation")] = \
                    a.param("value")
                cluster.kube.services.update(svc)
            except Exception:
                pass
        elif a.op == "drift_record":
            created = live.get(a.name)
            if created is None:
                return
            zone = zones[int(created.param("zone", 0))]
            rname = f"{a.name}.z{created.param('zone', 0)}" \
                    f".fuzz.example.com"
            # the GOOD state is whatever the controller converged the
            # record to (an alias to the accelerator's DNS name, not
            # the NLB's): read it before corrupting — but a record
            # RE-drifted before its repair landed reuses the
            # remembered good value, never the live rogue one.  A
            # record not converged yet is skipped — nothing to drift.
            with drift_lock:
                good = good_aliases.get((zone.id, rname))
            if good is None:
                good = _record_alias(cloud, zone.id, rname)
            if good is None:
                return
            rogue = f"{a.param('rogue')}.elb.{REGIONS[0]}" \
                    f".amazonaws.com"
            try:
                cloud.faults.edit_record_set(
                    zone.id, rname, "A", alias_dns_name=rogue)
            except Exception:
                return
            with drift_lock:
                good_aliases[(zone.id, rname)] = good
                # a re-drift of a still-unrepaired record keeps the
                # ORIGINAL injection time: the measured lag covers
                # the whole corrupted window
                if (zone.id, rname) not in pending_drift:
                    pending_drift[(zone.id, rname)] = (
                        simclock.monotonic(), good)
        elif a.op == "partition":
            if topology is not None:
                topology.partition_region(a.param("region"),
                                          rate=a.param("rate", 1.0))
        elif a.op == "heal":
            if topology is not None:
                topology.heal_region(a.param("region"))
