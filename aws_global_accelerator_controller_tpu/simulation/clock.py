"""Deterministic virtual time: the process-wide clock every timing
surface reads (ISSUE 13; ROADMAP item 2).

Real-time tests and production run on the **system clock** — the
module-level :func:`monotonic` / :func:`wall` / :func:`sleep` delegate
straight to :mod:`time`, and the factory helpers
(:func:`make_event` / :func:`make_condition` / :func:`make_queue`)
return primitives that behave exactly like their :mod:`threading` /
:mod:`queue` counterparts.  Installing a :class:`VirtualClock`
(``with VirtualClock().activate():``) flips the whole process into
**discrete-event simulation**, FoundationDB-style:

- ``monotonic()`` returns *virtual* seconds; ``wall()`` a virtual
  epoch offset by the same amount.
- every blocking wait — ``sleep``, ``SimEvent.wait``,
  ``SimCondition.wait``, ``SimQueue.get``, ``join_thread`` — PARKS the
  calling thread in the clock instead of the OS.
- the scheduler advances virtual time **to the next due waiter only
  when every sim thread is parked**: no busy-waiting, no real-time
  races, and a 5-minute lease expiry costs microseconds of wall time.
- execution is SERIAL and cooperative: at most one sim thread runs at
  a time, resumed in deterministic order (FIFO for notified waiters,
  ``(deadline, park-sequence)`` for timers), so a seeded chaos
  scenario replays with an identical interleaving — the determinism
  proof test (tests/chaos/test_chaos_determinism.py) asserts the
  decision logs byte-identical across runs.

Park/advance rule (the contract ARCHITECTURE.md documents):

1. A thread becomes a *sim thread* the first time it parks (or when
   spawned via :func:`start_thread`, which parks the child until the
   scheduler resumes it — a spawn never races its parent).
2. Time NEVER advances while any sim thread runs.  When the last one
   parks: first resume notified waiters FIFO; only when none are
   runnable, pop the earliest timer, advance ``now`` to its deadline
   and resume exactly that waiter.
3. All parked, no runnable, no timer = the simulation is wedged —
   :class:`SimStallError` is raised in the most recently parked
   thread, naming every parked thread (a real deadlock surfaces
   loudly instead of hanging the test).

What stays wall-clock: the native C++ workqueue (its ``get`` parks
outside the GIL where the clock cannot see it — ``kube/workqueue.py``
``new_rate_limiting_queue`` falls back to the Python queue while a
virtual clock is active), the HTTP backends (``kube/http_store.py``,
``kube/rest_server.py``, ``kube/kubeconfig.py``), boto
(``cloudprovider/aws/real.py``) and subprocess drivers — real I/O is
the simulation boundary.  Lint rule L115 keeps every other timing
surface on this module: a bare ``time.sleep`` in a clock-owned
package is a wall-clock leak that silently breaks virtual-time
determinism.

Locks are deliberately NOT virtualized: the concurrency contracts
(L102 — never block while holding a lock) guarantee no sim thread
parks with a lock held, so real locks only ever see uncontended or
momentary waits.
"""
from __future__ import annotations

import heapq
import queue as queue_mod
import threading
import time as _time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

_real_monotonic = _time.monotonic
_real_time = _time.time
_real_sleep = _time.sleep

# the installed virtual clock (None = system time).  Written only by
# VirtualClock.activate/deactivate; read on every clock call.
_installed: "Optional[VirtualClock]" = None
_install_lock = threading.Lock()


class SimStallError(RuntimeError):
    """Every sim thread is parked, nothing is runnable and no timer is
    pending: the simulated program deadlocked (or the driver forgot a
    timed wait).  Raised in the most recently parked thread so the
    wedge surfaces as a test failure instead of a hang."""


# ---------------------------------------------------------------------------
# module-level clock surface (what the plumbed call sites use)
# ---------------------------------------------------------------------------


def active() -> "Optional[VirtualClock]":
    """The installed virtual clock, or None under system time."""
    return _installed


def virtual_active() -> bool:
    return _installed is not None


def monotonic() -> float:
    """Monotonic now: virtual seconds under a VirtualClock, else
    ``time.monotonic()``."""
    clk = _installed
    return clk.now() if clk is not None else _real_monotonic()


def wall() -> float:
    """Wall-clock now: the virtual epoch under a VirtualClock, else
    ``time.time()``."""
    clk = _installed
    return clk.wall_now() if clk is not None else _real_time()


def sleep(seconds: float) -> None:
    """Park for ``seconds``: virtual (zero wall cost) under a
    VirtualClock, else ``time.sleep``."""
    clk = _installed
    if clk is None:
        _real_sleep(seconds)
    else:
        clk.sleep(seconds)


def make_event() -> "SimEvent":
    """A clock-aware :class:`threading.Event` — identical behavior
    under system time, parks in the clock under virtual time."""
    return SimEvent()


def make_condition(lock=None) -> "SimCondition":
    """A clock-aware :class:`threading.Condition` over ``lock``."""
    return SimCondition(lock)


def make_queue(maxsize: int = 0):
    """A watch-subscription / event-buffer queue: stdlib
    :class:`queue.Queue` under system time (its internal timed waits
    use real monotonic arithmetic, which a virtual clock would
    starve), a :class:`SimQueue` while a virtual clock is active."""
    if _installed is not None:
        return SimQueue(maxsize)
    return queue_mod.Queue(maxsize)


def start_thread(target: Callable, name: Optional[str] = None,
                 daemon: bool = True, args: tuple = (),
                 kwargs: Optional[dict] = None) -> threading.Thread:
    """Spawn a thread that participates in the active clock.  Under
    system time this is a plain started :class:`threading.Thread`;
    under a virtual clock the child registers as a sim thread and
    PARKS until the scheduler resumes it, so a spawn never races its
    parent."""
    clk = _installed
    if clk is None:
        t = threading.Thread(target=target, args=args,
                             kwargs=kwargs or {}, daemon=daemon,
                             name=name)
        t.start()
        return t
    return clk.spawn(target, name=name, daemon=daemon, args=args,
                     kwargs=kwargs or {})


def join_thread(thread: threading.Thread,
                timeout: Optional[float] = None) -> None:
    """Join a thread without stalling the simulation: a sim-spawned
    thread is awaited via its clock-aware done event (then reaped with
    a short real join); anything else joins normally."""
    done = getattr(thread, "_sim_done", None)
    if _installed is None or done is None:
        thread.join(timeout)
        return
    done.wait(timeout)
    if done.is_set():
        # past its target; only deregistration remains — a bounded
        # REAL join reaps it so is_alive() reads False for callers
        thread.join(1.0)


def wait_until(predicate: Callable[[], bool], timeout: float,
               poll: float = 0.01) -> bool:
    """Poll ``predicate`` until true or ``timeout`` — on the active
    clock, so a virtual-time driver parks between polls (letting the
    machinery run) instead of burning wall time."""
    deadline = monotonic() + timeout
    while monotonic() < deadline:
        if predicate():
            return True
        sleep(poll)
    return predicate()


# ---------------------------------------------------------------------------
# the virtual clock
# ---------------------------------------------------------------------------

_RUNNING = "running"
_PARKED = "parked"


class _Waiter:
    """One parked thread's resume token.  ``fired`` flips exactly once
    (under the clock lock) when the waiter is claimed — by a notify
    (``notified=True``), its timer, a stall, or a pre-park set — so a
    racing set() and deadline can never double-resume."""

    __slots__ = ("event", "clock", "tid", "fired", "parked",
                 "notified", "stall")

    def __init__(self, clock: "Optional[VirtualClock]"):
        self.event = threading.Event()
        self.clock = clock
        self.tid: Optional[int] = None
        self.fired = False
        self.parked = False
        self.notified = False
        self.stall: Optional[str] = None


class VirtualClock:
    """Monotone event-driven time source with a waiter heap (module
    docstring has the park/advance rule).  ``max_virtual`` bounds how
    far ``now`` may advance — a runaway simulation (a loop that only
    ever sleeps) stalls loudly instead of spinning forever."""

    def __init__(self, start: float = 0.0,
                 wall_epoch: float = 1_600_000_000.0,
                 max_virtual: Optional[float] = None):
        self._lock = threading.Lock()
        self._now = float(start)  # guarded-by: self._lock
        self._wall_offset = wall_epoch - float(start)
        self._max_virtual = max_virtual
        # tid -> _RUNNING | _PARKED for every sim thread
        self._threads: Dict[int, str] = {}
        self._names: Dict[int, str] = {}  # guarded-by: self._lock
        # tid -> threading.Thread, for liveness pruning: an
        # AUTO-registered thread (a leftover worker from an earlier
        # abruptly-stopped cluster that wandered into this clock) may
        # exit without deregistering — counted RUNNING forever, it
        # would freeze the scheduler, so the advance step prunes dead
        # members before concluding someone is still running
        self._members: Dict[int, threading.Thread] = {}  # guarded-by: self._lock
        self._running = 0  # guarded-by: self._lock
        self._runnable: "deque[_Waiter]" = deque()  # guarded-by: self._lock
        self._timers: List[Tuple[float, int, _Waiter]] = []  # guarded-by: self._lock
        self._parked_waiters: Dict[int, _Waiter] = {}  # guarded-by: self._lock
        self._seq = 0  # guarded-by: self._lock
        # stats (sim_time_ratio, the bench's simulated-vs-wall story)
        # guarded-by: external: stamped by activate() on the driver
        # thread before any sim thread exists
        self._started_real = _real_monotonic()
        # guarded-by: external: stamped by activate() on the driver
        # thread before any sim thread exists
        self._started_virtual = float(start)
        self.parks = 0  # guarded-by: self._lock
        self.advances = 0  # guarded-by: self._lock
        # real-time watchdog (started by activate): a FOREIGN thread —
        # auto-registered because it wandered into a clock wait — can
        # die without deregistering, leaving the run count pinned > 0
        # after the last sim park, which wedges the scheduler with no
        # one left to kick it.  The watchdog prunes dead members on a
        # coarse REAL cadence and re-runs the advance step; it never
        # touches live state, so determinism is unaffected (it only
        # acts on a condition that is already outside the
        # deterministic model).
        self._watchdog_stop = threading.Event()  # guarded-by: internal
        # guarded-by: external: activate()/deactivate() run on the
        # driver thread (the install lock serializes them)
        self._watchdog: Optional[threading.Thread] = None

    # -- install ------------------------------------------------------

    def activate(self, register: bool = True) -> "VirtualClock":
        """Install this clock process-wide (``register=True`` also
        makes the calling thread a sim thread, so the driver's waits
        participate from the first call).  Returns self; use as a
        context manager for scoped installs."""
        global _installed
        with _install_lock:
            if _installed is not None and _installed is not self:
                raise RuntimeError("another VirtualClock is active")
            _installed = self
        self._started_real = _real_monotonic()
        self._started_virtual = self._now  # race: driver-only setup read
        if register:
            self.register_current("driver")
        if self._watchdog is None or not self._watchdog.is_alive():
            # re-activation after deactivate(): the previous watchdog
            # observed the stop flag and exited — clear it and start a
            # fresh one, or dead-foreign-thread pruning is silently off
            self._watchdog_stop.clear()
            self._watchdog = threading.Thread(
                target=self._watch_loop, daemon=True,
                name="simclock-watchdog")
            self._watchdog.start()
        return self

    def deactivate(self) -> None:
        global _installed
        with _install_lock:
            if _installed is self:
                _installed = None
        self._watchdog_stop.set()
        with self._lock:
            self._threads.pop(threading.get_ident(), None)
            # any thread still parked would hang forever with the
            # clock gone: resume them all (their waits read as timed
            # out and their loops re-check state on the system clock).
            # The runnable queue too — a waiter already claimed for
            # resume (fired=True) but not yet handed the turn has an
            # unset event, and dropping it would strand its thread.
            for w in self._runnable:
                w.event.set()
            self._runnable.clear()
            for w in list(self._parked_waiters.values()):
                if not w.fired:
                    w.fired = True
                w.event.set()
            self._parked_waiters.clear()
            self._threads.clear()
            self._members.clear()
            self._names.clear()
            self._running = 0
            self._runnable.clear()
            self._timers = []

    def __enter__(self) -> "VirtualClock":
        return self

    def __exit__(self, *exc) -> None:
        self.deactivate()

    # -- reading time -------------------------------------------------

    def now(self) -> float:
        return self._now  # race: lock-free hot read; float load is atomic under the GIL

    def wall_now(self) -> float:
        return self._now + self._wall_offset  # race: lock-free hot read, as now()

    def stats(self) -> dict:
        """Simulated-vs-wall accounting for the scale bench:
        ``sim_seconds``, ``wall_seconds``, ``sim_time_ratio``,
        ``parks``, ``advances``."""
        wall_s = max(1e-9, _real_monotonic() - self._started_real)
        sim_s = self._now - self._started_virtual  # race: stats snapshot; torn reads acceptable
        return {"sim_seconds": sim_s, "wall_seconds": wall_s,
                "sim_time_ratio": sim_s / wall_s,
                "parks": self.parks, "advances": self.advances}  # race: stats snapshot

    # -- thread registry ----------------------------------------------

    def register_current(self, name: str = "") -> None:
        """Make the calling thread a sim thread NOW (before its first
        park) so time cannot advance while it still runs."""
        tid = threading.get_ident()
        with self._lock:
            if tid not in self._threads:
                self._threads[tid] = _RUNNING
                self._names[tid] = name or threading.current_thread().name
                self._members[tid] = threading.current_thread()
                self._running += 1

    def unregister_current(self) -> None:
        """Withdraw the calling thread from the simulation (a driver
        handing off to real-time teardown)."""
        tid = threading.get_ident()
        with self._lock:
            if self._threads.pop(tid, None) == _RUNNING:
                self._running -= 1
            self._names.pop(tid, None)
            self._members.pop(tid, None)
            self._parked_waiters.pop(tid, None)
            self._maybe_advance_locked()

    def _ensure_registered_locked(self, tid: int) -> None:
        if tid not in self._threads:
            self._threads[tid] = _RUNNING
            self._names[tid] = threading.current_thread().name
            self._members[tid] = threading.current_thread()
            self._running += 1

    def _prune_dead_locked(self) -> None:
        """Drop members that exited while counted RUNNING (possible
        only for auto-registered foreign threads; spawn()-ed threads
        deregister in their finally) — without this, one dead
        straggler freezes the scheduler forever."""
        for tid, state in list(self._threads.items()):
            if state != _RUNNING:
                continue
            member = self._members.get(tid)
            if member is not None and not member.is_alive():
                del self._threads[tid]
                self._members.pop(tid, None)
                self._names.pop(tid, None)
                self._running -= 1

    # -- park / wake / advance ----------------------------------------

    def _watch_loop(self) -> None:
        while not self._watchdog_stop.wait(0.25):
            with self._lock:
                if self._running > 0:
                    self._prune_dead_locked()
                    if self._running == 0:
                        self._maybe_advance_locked()

    def park(self, waiter: _Waiter, timeout: Optional[float] = None
             ) -> bool:
        """Block the calling thread until the waiter is notified or
        ``timeout`` virtual seconds elapse; returns True iff notified.
        The heart of every sim wait — callers must hold NO lock the
        waker needs (the L102 contract)."""
        tid = threading.get_ident()
        waiter.tid = tid
        with self._lock:
            self._ensure_registered_locked(tid)
            if waiter.fired:
                return True  # set()/notify landed before the park
            waiter.parked = True
            self._threads[tid] = _PARKED
            self._running -= 1
            self._parked_waiters[tid] = waiter
            self.parks += 1
            if timeout is not None:
                self._seq += 1
                heapq.heappush(
                    self._timers,
                    (self._now + max(0.0, timeout), self._seq, waiter))
            self._maybe_advance_locked(stall_waiter=waiter)
        waiter.event.wait()
        if waiter.stall is not None:
            raise SimStallError(waiter.stall)
        return waiter.notified

    def wake(self, waiter: _Waiter) -> None:
        """Mark a parked waiter notified-and-runnable (FIFO).  Called
        by SimEvent.set / SimCondition.notify — from sim threads AND
        from unregistered (external) threads, in which case the
        scheduler may need a kick here."""
        with self._lock:
            if waiter.fired:
                return
            waiter.fired = True
            waiter.notified = True
            if not waiter.parked:
                return  # pre-park: its park() will return immediately
            self._runnable.append(waiter)
            if self._running == 0:
                self._maybe_advance_locked()

    def sleep(self, seconds: float) -> None:
        """Virtual sleep; ``sleep(0)`` is a cooperative yield (other
        runnable threads get the turn first)."""
        self.park(_Waiter(self), timeout=max(0.0, seconds))

    def _resume_locked(self, waiter: _Waiter) -> None:
        tid = waiter.tid
        if tid is not None and self._threads.get(tid) == _PARKED:
            self._threads[tid] = _RUNNING
            self._running += 1
            self._parked_waiters.pop(tid, None)
        waiter.event.set()

    def _maybe_advance_locked(
            self, stall_waiter: Optional[_Waiter] = None) -> None:
        """The scheduler step (caller holds the clock lock): resume
        the next runnable, else advance time to the earliest live
        timer, else stall."""
        if self._running > 0:
            # dead-member pruning is the WATCHDOG's job (real-time
            # cadence): doing it here would put an O(threads)
            # is_alive sweep on every park of a busy simulation
            return
        if self._runnable:
            self._resume_locked(self._runnable.popleft())
            return
        while self._timers:
            deadline, _, w = heapq.heappop(self._timers)
            if w.fired:
                continue  # notified (or stalled) before its deadline
            if (self._max_virtual is not None
                    and deadline > self._max_virtual):
                heapq.heappush(self._timers, (deadline, 0, w))
                break  # past the cap: treat as a stall below
            w.fired = True
            w.notified = False
            if deadline > self._now:
                self._now = deadline
                self.advances += 1
            self._resume_locked(w)
            return
        target = stall_waiter
        if target is None or target.fired:
            target = next((w for w in self._parked_waiters.values()
                           if not w.fired), None)
        if target is None:
            return  # no sim thread left to inform — nothing to do
        names = ", ".join(
            f"{self._names.get(t, t)}" for t in self._parked_waiters)
        target.fired = True
        target.stall = (
            f"virtual clock stalled at t={self._now:.3f}: every sim "
            f"thread is parked with no runnable waiter and no pending "
            f"timer (parked: {names or 'none'}"
            + (f"; max_virtual={self._max_virtual}s reached"
               if self._max_virtual is not None and self._timers
               else "") + ")")
        self._resume_locked(target)

    # -- spawning -----------------------------------------------------

    def spawn(self, target: Callable, name: Optional[str] = None,
              daemon: bool = True, args: tuple = (),
              kwargs: Optional[dict] = None) -> threading.Thread:
        """start_thread's virtual half: the child registers parked and
        joins the runnable queue — it first runs when the scheduler
        hands it the turn, never concurrently with its parent."""
        done = SimEvent()

        def _run():
            tid = threading.get_ident()
            latch = _Waiter(self)
            latch.tid = tid
            latch.fired = True  # born runnable, resumed by the queue
            with self._lock:
                self._threads[tid] = _PARKED
                self._names[tid] = name or threading.current_thread().name
                self._members[tid] = threading.current_thread()
                self._parked_waiters[tid] = latch
                self._runnable.append(latch)
                if self._running == 0:
                    self._maybe_advance_locked()
            latch.event.wait()
            try:
                target(*args, **(kwargs or {}))
            finally:
                done.set()  # joiners become runnable first...
                with self._lock:  # ...then this thread leaves the sim
                    t = threading.get_ident()
                    if self._threads.pop(t, None) == _RUNNING:
                        self._running -= 1
                    self._names.pop(t, None)
                    self._members.pop(t, None)
                    self._parked_waiters.pop(t, None)
                    self._maybe_advance_locked()

        t = threading.Thread(target=_run, daemon=daemon, name=name)
        t._sim_done = done  # type: ignore[attr-defined]
        t.start()
        return t


# ---------------------------------------------------------------------------
# clock-aware primitives
# ---------------------------------------------------------------------------


class SimEvent(threading.Event):
    """threading.Event that parks in (and is woken through) the active
    virtual clock.  Under system time it IS a threading.Event; built
    before a clock is installed it still participates afterwards —
    the wait path consults the installed clock per call."""

    def __init__(self):
        super().__init__()
        self._sim_lock = threading.Lock()
        self._sim_waiters: "deque[_Waiter]" = deque()

    def set(self) -> None:
        super().set()
        with self._sim_lock:
            waiters = list(self._sim_waiters)
            self._sim_waiters.clear()
        for w in waiters:
            w.clock.wake(w)

    def wait(self, timeout: Optional[float] = None) -> bool:
        clk = _installed
        if clk is None:
            return super().wait(timeout)
        if super().is_set():
            return True
        w = _Waiter(clk)
        with self._sim_lock:
            if super().is_set():
                return True
            self._sim_waiters.append(w)
        notified = clk.park(w, timeout)
        if not notified:
            with self._sim_lock:
                try:
                    self._sim_waiters.remove(w)
                except ValueError:
                    pass
        return super().is_set()


class SimCondition(threading.Condition):
    """threading.Condition that parks in the active virtual clock.
    The sim waiter list is guarded by the condition's own lock (the
    caller holds it across wait/notify, per the Condition contract)."""

    def __init__(self, lock=None):
        super().__init__(lock)
        self._sim_waiters: "deque[_Waiter]" = deque()

    def wait(self, timeout: Optional[float] = None) -> bool:
        clk = _installed
        if clk is None:
            return super().wait(timeout)
        w = _Waiter(clk)
        self._sim_waiters.append(w)
        state = self._release_save()
        try:
            notified = clk.park(w, timeout)
        finally:
            self._acquire_restore(state)
            if not notified:
                try:
                    self._sim_waiters.remove(w)
                except ValueError:
                    pass
        return notified

    def wait_for(self, predicate: Callable[[], Any],
                 timeout: Optional[float] = None):
        clk = _installed
        if clk is None:
            return super().wait_for(predicate, timeout)
        # stock wait_for computes its deadline on REAL monotonic,
        # which never advances while the sim waits — redo it virtual
        endtime = None if timeout is None else clk.now() + timeout
        result = predicate()
        while not result:
            waittime = None
            if endtime is not None:
                waittime = endtime - clk.now()
                if waittime <= 0:
                    break
            self.wait(waittime)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        woken = 0
        while woken < n and self._sim_waiters:
            w = self._sim_waiters.popleft()
            w.clock.wake(w)
            woken += 1
        if woken < n:
            super().notify(n - woken)

    def notify_all(self) -> None:
        while self._sim_waiters:
            w = self._sim_waiters.popleft()
            w.clock.wake(w)
        super().notify_all()


class SimQueue:
    """Minimal queue.Queue stand-in whose blocking ``get`` parks in
    the virtual clock (watch subscriptions under simulation — built by
    :func:`make_queue`).  Deliberately NOT stdlib Queue: its timed get
    re-arms from REAL monotonic, which a virtual clock starves."""

    def __init__(self, maxsize: int = 0):
        self.maxsize = maxsize
        self._items: deque = deque()
        self._cond = SimCondition(threading.Lock())
        self.unfinished_tasks = 0

    def put(self, item: Any) -> None:
        with self._cond:
            # a bounded queue blocks (virtually) when full, matching
            # queue.Queue.put under the system clock — the consumer's
            # task_done/get notifies this same condition
            while self.maxsize > 0 and len(self._items) >= self.maxsize:
                self._cond.wait()
            self._items.append(item)
            self.unfinished_tasks += 1
            self._cond.notify()

    def put_nowait(self, item: Any) -> None:
        with self._cond:
            if self.maxsize > 0 and len(self._items) >= self.maxsize:
                raise queue_mod.Full
            self._items.append(item)
            self.unfinished_tasks += 1
            self._cond.notify()

    def task_done(self) -> None:
        with self._cond:
            if self.unfinished_tasks > 0:
                self.unfinished_tasks -= 1

    def get(self, block: bool = True, timeout: Optional[float] = None):
        with self._cond:
            if not block:
                if not self._items:
                    raise queue_mod.Empty
                return self._items.popleft()
            if timeout is None:
                while not self._items:
                    self._cond.wait()
            else:
                deadline = monotonic() + timeout
                while not self._items:
                    remaining = deadline - monotonic()
                    if remaining <= 0:
                        raise queue_mod.Empty
                    self._cond.wait(remaining)
            item = self._items.popleft()
            if self.maxsize > 0:
                self._cond.notify()   # a slot freed: wake a blocked put
            return item

    def get_nowait(self):
        return self.get(block=False)

    def empty(self) -> bool:
        with self._cond:
            return not self._items

    def qsize(self) -> int:
        with self._cond:
            return len(self._items)
