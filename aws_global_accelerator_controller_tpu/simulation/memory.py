"""Per-service memory accounting: the million-key diet's measuring
stick (ISSUE 13).

``deep_sizeof`` is a cycle-safe recursive ``sys.getsizeof`` that
understands dicts/sequences/slotted dataclasses; ``fleet_bytes``
samples the big per-service stores (apiserver store, informer caches,
fake cloud state, fingerprint records, fleet index) instead of walking
all of them — at 100k services an exact walk would cost more than the
storm it measures — and reports bytes/service per component plus the
process peak RSS.  The scale-storm bench records the result to
reconcile_history.jsonl and feeds the ``per_service_bytes`` gauge
(metrics.py).
"""
from __future__ import annotations

import itertools
import sys
from typing import Any, Dict, Iterable, Optional

_ATOMIC = (int, float, bool, complex, type(None), type, bytes, str)


def deep_sizeof(obj: Any, _seen: Optional[set] = None) -> int:
    """Recursive ``sys.getsizeof`` with shared-object dedup: an
    interned ARN referenced from five indexes is charged once — which
    is exactly the accounting that makes the interning win visible."""
    seen = _seen if _seen is not None else set()
    oid = id(obj)
    if oid in seen:
        return 0
    seen.add(oid)
    size = sys.getsizeof(obj)
    if isinstance(obj, _ATOMIC):
        return size
    if isinstance(obj, dict):
        for k, v in obj.items():
            size += deep_sizeof(k, seen) + deep_sizeof(v, seen)
        return size
    if isinstance(obj, (list, tuple, set, frozenset)):
        for item in obj:
            size += deep_sizeof(item, seen)
        return size
    # slotted objects (the diet's object shape) and plain instances
    slots = getattr(type(obj), "__slots__", None)
    if slots is not None:
        for cls in type(obj).__mro__:
            for name in getattr(cls, "__slots__", ()) or ():
                try:
                    size += deep_sizeof(getattr(obj, name), seen)
                except AttributeError:
                    pass
    d = getattr(obj, "__dict__", None)
    if d is not None:
        size += deep_sizeof(d, seen)
    return size


def sampled_bytes(items: Iterable[Any], total: int,
                  sample: int = 64) -> int:
    """Estimate the deep size of ``total`` homogeneous items from the
    first ``sample`` of them (0 when empty)."""
    measured = 0
    n = 0
    for item in itertools.islice(iter(items), sample):
        measured += deep_sizeof(item)
        n += 1
    if n == 0:
        return 0
    return int(measured / n * total)


def peak_rss_bytes() -> int:
    """The process's peak resident set (ru_maxrss is KiB on Linux)."""
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


def fleet_bytes(n_services: int,
                components: Dict[str, Any],
                sample: int = 64) -> Dict[str, Any]:
    """Per-service byte accounting over named component stores.

    ``components`` maps a component name to either a dict (sampled by
    value), an iterable of objects, or an integer byte count the
    caller already measured.  Returns per-component bytes, their sum,
    ``per_service_bytes`` and ``peak_rss_bytes``."""
    out: Dict[str, Any] = {}
    total = 0
    for name, store in components.items():
        if isinstance(store, int):
            size = store
        elif isinstance(store, dict):
            size = (sampled_bytes(store.values(), len(store), sample)
                    + sampled_bytes(store.keys(), len(store), sample))
        else:
            items = list(itertools.islice(iter(store), sample))
            # len() may not exist on a generator; re-materialize small
            try:
                count = len(store)  # type: ignore[arg-type]
            except TypeError:
                count = len(items)
            size = sampled_bytes(items, count, sample)
        out[f"{name}_bytes"] = size
        total += size
    out["accounted_bytes"] = total
    out["per_service_bytes"] = (total / n_services) if n_services else 0.0
    out["peak_rss_bytes"] = peak_rss_bytes()
    return out
