"""Deterministic virtual-time simulation + per-service memory
accounting (ISSUE 13; ROADMAP item 2).

``simulation.clock`` is the process-wide time source every timing
surface reads (lint rule L115 enforces it); installing a
:class:`~.clock.VirtualClock` flips the process into discrete-event
simulation.  ``simulation.memory`` is the million-key diet's
measuring stick.
"""
from .clock import (
    SimCondition,
    SimEvent,
    SimQueue,
    SimStallError,
    VirtualClock,
)
from .memory import deep_sizeof, fleet_bytes, peak_rss_bytes

__all__ = [
    "SimCondition", "SimEvent", "SimQueue", "SimStallError",
    "VirtualClock", "deep_sizeof", "fleet_bytes", "peak_rss_bytes",
]
