"""Validating admission webhook (reference pkg/webhoook/ -- sic)."""
from .server import WebhookServer
from .validator import validate_endpoint_group_binding
