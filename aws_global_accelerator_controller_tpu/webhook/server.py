"""Webhook HTTP(S) server.

Mirrors reference pkg/webhoook/webhook.go:14-85: a plain HTTP server (no
framework) with
- GET  /healthz                          -> 200
- POST /validate-endpointgroupbinding    -> AdmissionReview v1 in/out

Request validation before dispatch (webhook.go:61-85): Content-Type must
be application/json, body non-empty, request field present; failures are
400s.  TLS is enabled when cert+key files are given.
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler
from typing import Optional

from .validator import validate_endpoint_group_binding

logger = logging.getLogger(__name__)


class _Handler(BaseHTTPRequestHandler):
    # bound every socket op (incl. the deferred TLS handshake): a
    # client that connects and never speaks must not pin a handler
    # thread + fd forever
    timeout = 30

    def log_message(self, fmt, *args):  # route into logging, not stderr
        logger.debug("webhook: " + fmt, *args)

    def _respond(self, code: int, body: bytes,
                 content_type: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            self._respond(200, b"ok", "text/plain")
        else:
            self._respond(404, b"not found", "text/plain")

    def do_POST(self):
        if self.path != "/validate-endpointgroupbinding":
            self._respond(404, b"not found", "text/plain")
            return
        if self.headers.get("Content-Type") != "application/json":
            self._respond(400, b"invalid Content-Type", "text/plain")
            return
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        if not body:
            self._respond(400, b"empty body", "text/plain")
            return
        try:
            review = json.loads(body)
        except ValueError as e:
            self._respond(400, f"failed to unmarshal body: {e}".encode(),
                          "text/plain")
            return
        if not review.get("request"):
            self._respond(400, b"empty request", "text/plain")
            return
        response = validate_endpoint_group_binding(review)
        self._respond(200, json.dumps(response).encode())


class WebhookServer:
    """ThreadingHTTPServer wrapper with optional TLS and clean shutdown."""

    def __init__(self, port: int = 8443, tls_cert_file: str = "",
                 tls_key_file: str = "", host: str = ""):
        from ..kube.tlsutil import enable_tls, make_threading_http_server

        self._httpd = make_threading_http_server((host, port), _Handler,
                                                 logger, "webhook")
        # pass the flags through unchanged: half a TLS config (cert
        # without key or vice versa) is a misconfiguration enable_tls
        # rejects, not a cue to silently downgrade to plain HTTP
        try:
            self.ssl = enable_tls(self._httpd, tls_cert_file,
                                  tls_key_file)
        except Exception:
            # the listener is already bound: release the port before
            # surfacing the config error or a retry gets EADDRINUSE
            self._httpd.server_close()
            raise
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def serve_forever(self) -> None:
        logger.info("webhook listening on :%d, SSL is %s", self.port,
                    self.ssl)
        self._httpd.serve_forever(poll_interval=0.2)

    def start_background(self) -> None:
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True, name="webhook-server")
        self._thread.start()

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
