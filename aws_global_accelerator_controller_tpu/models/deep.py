"""Deep residual traffic model: the pipeline-parallel model family.

Fourth compute-track family.  A deep stack of residual [H, H] scoring
blocks — deep enough that on real fleets a single chip's HBM cannot
hold all stages' activations at once, which is exactly the regime
pipeline parallelism exists for.  ``parallel.pipeline_train`` trains
this model with the GPipe microbatch schedule over a 'stage' mesh axis;
this module is the dense single-chip form and the numerical oracle.

The reference repo has no compute path (SURVEY.md §2: pipeline
parallelism ABSENT upstream).

Design notes (TPU-first):
- every stage is h + relu(h @ w + b): activations stay well-scaled
  through arbitrarily many stages, and each stage is one MXU matmul;
- the dense forward is a python loop over stages UNDER jit — unrolled
  at trace time into a static chain, no dynamic control flow;
- parameters are stored stage-major ([S, H, H]) so the pipelined
  planner shards dim 0 over the stage axis without reshapes.
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from ..ops.weights import plan_weights
from .common import TrainableModel, make_optimizer, masked_ce_loss
from .traffic import Batch, synthetic_batch  # noqa: F401  (re-export)

Params = Dict[str, jax.Array]

N_STAGES = 4
FEATURE_DIM = 8
HIDDEN_DIM = 64


def stage_fn(h: jax.Array, w: jax.Array, b: jax.Array) -> jax.Array:
    """One pipeline stage: residual relu block (shared with the
    pipelined planner so dense and sharded cannot drift)."""
    return h + jnp.maximum(h @ w + b, 0.0)


class DeepTrafficModel(TrainableModel):
    def __init__(self, n_stages: int = N_STAGES,
                 feature_dim: int = FEATURE_DIM,
                 hidden_dim: int = HIDDEN_DIM,
                 learning_rate: float = 1e-3,
                 optimizer: str = "adam"):
        self.n_stages = n_stages
        self.feature_dim = feature_dim
        self.hidden_dim = hidden_dim
        self.optimizer = make_optimizer(optimizer, learning_rate)

    def init_params(self, key: jax.Array) -> Params:
        k1, k2, k3 = jax.random.split(key, 3)
        s, f, h = self.n_stages, self.feature_dim, self.hidden_dim
        scale = lambda fan_in: 1.0 / jnp.sqrt(fan_in)  # noqa: E731
        # float32 end to end: stage blocks are residual and deep —
        # bf16 drift compounds per stage, and the pipelined planner's
        # parity contract with this oracle is exact
        return {
            "w_in": jax.random.normal(k1, (f, h)) * scale(f),
            "stage_w": jax.random.normal(k2, (s, h, h)) * scale(h),
            "stage_b": jnp.zeros((s, h)),
            "w_out": jax.random.normal(k3, (h, 1)) * scale(h),
        }

    # -- forward --------------------------------------------------------

    def scores(self, params: Params, features: jax.Array) -> jax.Array:
        """[G, E, F] -> [G, E] f32 scores through all stages."""
        h = features.astype(jnp.float32) @ params["w_in"]
        for i in range(self.n_stages):
            h = stage_fn(h, params["stage_w"][i], params["stage_b"][i])
        return (h @ params["w_out"])[..., 0]

    def forward(self, params: Params, features: jax.Array,
                mask: jax.Array) -> jax.Array:
        """[G, E, F] + mask -> int32 GA weights [G, E]."""
        return plan_weights(self.scores(params, features), mask)

    # -- training -------------------------------------------------------

    def loss(self, params: Params, batch: Batch) -> jax.Array:
        return masked_ce_loss(self.scores(params, batch.features),
                              batch.mask, batch.target)
