"""Shared training machinery for the compute-track model families.

One implementation of the masked cross-entropy and the Adam update so
the families cannot drift apart (a fix to the eps guard or the
valid-group normalisation lands in both).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def masked_ce_loss(scores: jax.Array, mask: jax.Array,
                   target: jax.Array) -> jax.Array:
    """Cross-entropy between masked_softmax(scores) and the target
    weight distribution, averaged over groups with >=1 valid endpoint."""
    from ..ops.weights import masked_softmax

    p = masked_softmax(scores, mask)
    eps = 1e-9
    ce = -jnp.sum(jnp.where(mask, target * jnp.log(p + eps), 0.0),
                  axis=-1)
    valid = jnp.any(mask, axis=-1)
    return jnp.sum(jnp.where(valid, ce, 0.0)) / jnp.maximum(
        jnp.sum(valid), 1)


class TrainableModel:
    """Mixin: optimizer plumbing over a subclass-provided ``loss``.

    Subclasses set ``self.optimizer`` (an optax transformation) and
    implement ``loss(params, *data)``; ``train_step`` keeps whatever
    data arity the family uses (batch, or window + batch).
    """

    optimizer: optax.GradientTransformation

    def loss(self, params, *data) -> jax.Array:
        raise NotImplementedError

    def init_opt_state(self, params):
        return self.optimizer.init(params)

    def train_step_with(self, loss_fn, params, opt_state, *data):
        """The single optimizer-update implementation.  Sharded
        planners that swap in a distributed loss (moe dispatch, GPipe
        scores) call this with their own ``loss_fn`` so the update
        itself can never drift from the dense families'."""
        loss, grads = jax.value_and_grad(loss_fn)(params, *data)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        return optax.apply_updates(params, updates), opt_state, loss

    def train_step(self, params, opt_state, *data):
        return self.train_step_with(self.loss, params, opt_state, *data)
