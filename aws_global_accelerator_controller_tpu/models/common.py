"""Shared training machinery for the compute-track model families.

One implementation of the masked cross-entropy and the Adam update so
the families cannot drift apart (a fix to the eps guard or the
valid-group normalisation lands in both).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


def masked_ce_loss(scores: jax.Array, mask: jax.Array,
                   target: jax.Array) -> jax.Array:
    """Cross-entropy between masked_softmax(scores) and the target
    weight distribution, averaged over groups with >=1 valid endpoint."""
    from ..ops.weights import masked_softmax

    p = masked_softmax(scores, mask)
    eps = 1e-9
    ce = -jnp.sum(jnp.where(mask, target * jnp.log(p + eps), 0.0),
                  axis=-1)
    valid = jnp.any(mask, axis=-1)
    return jnp.sum(jnp.where(valid, ce, 0.0)) / jnp.maximum(
        jnp.sum(valid), 1)


class FlatAdamState(NamedTuple):
    count: jax.Array
    mu: jax.Array      # first moment, f32, one raveled vector
    nu: jax.Array      # second moment, f32, one raveled vector


def flat_adam(learning_rate: float, b1: float = 0.9,
              b2: float = 0.999,
              eps: float = 1e-8) -> optax.GradientTransformation:
    """Adam over ONE raveled parameter vector — an optax drop-in.

    ``optax.adam`` keeps per-leaf moment trees and emits ~6 elementwise
    ops per leaf per step; on a small-param model that is dozens of
    tiny kernels whose fixed costs dominate (measured 0.46 ms/step of
    the temporal benchmark's 12.4 ms against ~10 us of useful
    bandwidth).  Raveling collapses the update to a handful of fused
    ops over one contiguous vector.  Moments are f32 regardless of
    param dtype (optax's moments inherit the params' bf16 here — the
    flat state is the numerically stronger one); updates return in the
    grads' dtypes via the unravel closure.

    Meant for the UNSHARDED step: the raveled state has no axes for a
    ``NamedSharding`` to map, so under a sharded planner it rides
    replicated and every update gathers the sharded grads into one
    vector — correct but anti-scaling.  Models default to
    ``optax.adam``; this is the opt-in single-chip fast path.

    Hand-rolled rather than ``optax.flatten(optax.adam(...))``
    deliberately: the combinator's moments inherit the raveled grads'
    dtype (bf16 here; ``mu_dtype`` lifts only mu, nu stays bf16) and
    bf16 nu is exactly the accumulation this path wants rid of.  The
    update formula mirrors ``optax.scale_by_adam`` (bias-corrected
    moments, eps OUTSIDE the sqrt) — covered against optax
    trajectories and a NumPy reference in tests/test_flat_adam.py, so
    semantic drift from optax shows up in CI, not in training curves.
    """
    from jax.flatten_util import ravel_pytree

    def init(params):
        flat, _ = ravel_pytree(params)
        # mu and nu must be DISTINCT arrays: sharing one zeros buffer
        # makes a donating train step (donate_argnums on opt_state)
        # hand XLA the same buffer twice — runtime error on execute
        return FlatAdamState(count=jnp.zeros((), jnp.int32),
                             mu=jnp.zeros(flat.shape, jnp.float32),
                             nu=jnp.zeros(flat.shape, jnp.float32))

    def update(grads, state, params=None):
        del params
        flat_g, unravel = ravel_pytree(grads)
        g = flat_g.astype(jnp.float32)
        count = state.count + 1
        mu = b1 * state.mu + (1.0 - b1) * g
        nu = b2 * state.nu + (1.0 - b2) * (g * g)
        c = count.astype(jnp.float32)
        mu_hat = mu / (1.0 - b1 ** c)
        nu_hat = nu / (1.0 - b2 ** c)
        step = -learning_rate * mu_hat / (jnp.sqrt(nu_hat) + eps)
        return (unravel(step.astype(flat_g.dtype)),
                FlatAdamState(count=count, mu=mu, nu=nu))

    return optax.GradientTransformation(init, update)


def make_optimizer(name: str,
                   learning_rate: float) -> optax.GradientTransformation:
    """The one optimizer dispatch every family shares: ``"adam"`` =
    optax per-leaf tree (required for sharded optimizer-state
    layouts); ``"flat_adam"`` = the raveled single-vector update
    above (single-chip fast path)."""
    if name == "flat_adam":
        return flat_adam(learning_rate)
    if name == "adam":
        return optax.adam(learning_rate)
    raise ValueError(f"unknown optimizer {name!r}")


class TrainableModel:
    """Mixin: optimizer plumbing over a subclass-provided ``loss``.

    Subclasses set ``self.optimizer`` (an optax transformation) and
    implement ``loss(params, *data)``; ``train_step`` keeps whatever
    data arity the family uses (batch, or window + batch).
    """

    optimizer: optax.GradientTransformation

    def loss(self, params, *data) -> jax.Array:
        raise NotImplementedError

    def init_opt_state(self, params):
        return self.optimizer.init(params)

    def train_step_with(self, loss_fn, params, opt_state, *data):
        """The single optimizer-update implementation.  Sharded
        planners that swap in a distributed loss (moe dispatch, GPipe
        scores) call this with their own ``loss_fn`` so the update
        itself can never drift from the dense families'."""
        loss, grads = jax.value_and_grad(loss_fn)(params, *data)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        return optax.apply_updates(params, updates), opt_state, loss

    def train_step(self, params, opt_state, *data):
        return self.train_step_with(self.loss, params, opt_state, *data)
