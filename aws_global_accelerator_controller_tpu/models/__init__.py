"""Models: the TPU-native traffic-policy track (no reference analogue --
SURVEY.md §2 records the reference as 100% Go with zero ML components)."""
from .checkpoint import TrainCheckpointer
from .deep import DeepTrafficModel
from .moe import MoETrafficModel
from .temporal import TemporalTrafficModel
from .traffic import TrafficPolicyModel
