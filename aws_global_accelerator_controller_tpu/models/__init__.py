"""Models: the TPU-native traffic-policy track (no reference analogue --
SURVEY.md §2 records the reference as 100% Go with zero ML components)."""
from .checkpoint import TrainCheckpointer  # noqa: F401
from .deep import DeepTrafficModel  # noqa: F401
from .moe import MoETrafficModel  # noqa: F401
from .temporal import TemporalTrafficModel  # noqa: F401
from .traffic import TrafficPolicyModel  # noqa: F401
