"""Telemetry batch loaders: the framework's input pipeline.

Two implementations behind one surface:

- :class:`NativeTelemetryLoader` — the C++ pipeline
  (``native/telemetry.cpp``): a worker-thread pool fills a bounded ring
  of ready batches; ``next_batch`` pops with the GIL released, so batch
  N+1 is generated while the device runs step N.  Per-thread
  deterministic streams, but ring ordering depends on scheduling — use
  it for throughput, not bit-exact reproducibility.
- :class:`SyntheticTelemetryLoader` — the JAX path
  (``traffic.synthetic_batch`` keyed by ``fold_in(seed, step)``):
  bit-exact reproducible, what checkpoint-resume tests rely on.

``make_loader("native"|"synthetic", ...)`` picks one; "native" degrades
to synthetic (with a warning) when no C++ toolchain is available, the
same policy as ``kube.workqueue.new_rate_limiting_queue``.
"""
from __future__ import annotations

import ctypes
import logging
import threading
from typing import Optional

import numpy as np

from .traffic import Batch, synthetic_batch

logger = logging.getLogger(__name__)

_lib = None
_lib_lock = threading.Lock()
_lib_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_failed:
            return None
        from ..native import ensure_library

        path = ensure_library("telemetry")
        if path is None:
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            _lib_failed = True
            return None
        lib.aga_tl_new.restype = ctypes.c_void_p
        lib.aga_tl_new.argtypes = [ctypes.c_int, ctypes.c_int,
                                   ctypes.c_int, ctypes.c_int,
                                   ctypes.c_int, ctypes.c_uint64,
                                   ctypes.c_int, ctypes.c_int]
        lib.aga_tl_next.restype = ctypes.c_int
        lib.aga_tl_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.aga_tl_stats.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_uint64),
                                     ctypes.POINTER(ctypes.c_int)]
        lib.aga_tl_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


class SyntheticTelemetryLoader:
    """JAX-keyed reproducible batches (the CLI default).

    ``steps=0``: snapshot batches (``synthetic_batch``); ``steps=T``:
    ``next_window`` yields (window [T, G, E, F], Batch) via the
    temporal family's ``synthetic_window`` law."""

    def __init__(self, groups: int, endpoints: int,
                 feature_dim: int = 8, seed: int = 0, steps: int = 0,
                 per_step: bool = False):
        import jax

        if per_step and not steps:
            # same contract as the native loader: a per-step request
            # silently downgraded to snapshot targets would train a
            # different objective than asked
            raise ValueError("per_step targets need window mode "
                             "(steps > 0)")
        self._jax = jax
        self.groups, self.endpoints = groups, endpoints
        self.feature_dim = feature_dim
        self.steps = steps
        self.per_step = per_step
        self._key = jax.random.PRNGKey(seed)
        self._step = 0

    def _next_key(self):
        key = self._jax.random.fold_in(self._key, self._step)
        self._step += 1
        return key

    def next_batch(self) -> Batch:
        if self.steps:
            raise RuntimeError(
                "loader is in window mode (steps > 0); use next_window")
        return synthetic_batch(self._next_key(), groups=self.groups,
                               endpoints=self.endpoints,
                               feature_dim=self.feature_dim)

    def next_window(self):
        from .temporal import synthetic_window

        if not self.steps:
            raise RuntimeError(
                "loader is in snapshot mode (steps == 0); use "
                "next_batch")
        return synthetic_window(self._next_key(), steps=self.steps,
                                groups=self.groups,
                                endpoints=self.endpoints,
                                feature_dim=self.feature_dim,
                                per_step=self.per_step)

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativeTelemetryLoader:
    """C++ background pipeline; see module docstring for the contract.

    ``steps=0`` (default): ``next_batch`` pops snapshot batches.
    ``steps=T``: ``next_window`` pops temporal windows — the C++
    workers generate the window law of ``temporal.synthetic_window``
    (trend-based targets) with [T, G, E, F] features."""

    def __init__(self, groups: int, endpoints: int,
                 feature_dim: int = 8, seed: int = 0,
                 capacity: int = 4, n_threads: int = 2, steps: int = 0,
                 per_step: bool = False):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native telemetry loader unavailable (no g++ / build "
                "failed); use make_loader which degrades gracefully")
        if per_step and not steps:
            raise ValueError("per_step targets need window mode "
                             "(steps > 0)")
        self._lib = lib
        self.groups, self.endpoints = groups, endpoints
        self.feature_dim = feature_dim
        self.steps = steps
        self.per_step = per_step
        self._h = lib.aga_tl_new(groups, endpoints, feature_dim,
                                 capacity, n_threads,
                                 ctypes.c_uint64(seed or 1), steps,
                                 int(per_step))
        if not self._h:
            raise RuntimeError("native telemetry loader init failed")
        self._closed = False

    def _pop(self, features: np.ndarray):
        g, e = self.groups, self.endpoints
        mask = np.empty((g, e), np.uint8)
        target = np.empty((self.steps, g, e) if self.per_step
                          else (g, e), np.float32)
        ok = self._lib.aga_tl_next(
            self._h,
            features.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            target.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if not ok:
            raise RuntimeError("telemetry loader stopped")
        return mask, target

    def next_batch(self) -> Batch:
        import jax.numpy as jnp

        if self._closed:
            raise RuntimeError("telemetry loader is closed")
        if self.steps:
            raise RuntimeError(
                "loader is in window mode (steps > 0); use next_window")
        g, e, f = self.groups, self.endpoints, self.feature_dim
        features = np.empty((g, e, f), np.float32)
        mask, target = self._pop(features)
        return Batch(features=jnp.asarray(features, jnp.bfloat16),
                     mask=jnp.asarray(mask.astype(bool)),
                     target=jnp.asarray(target))

    def next_window(self):
        """(window [T, G, E, F] f32, Batch) — the temporal contract of
        ``SyntheticTelemetryLoader.next_window``."""
        import jax.numpy as jnp

        if self._closed:
            raise RuntimeError("telemetry loader is closed")
        if not self.steps:
            raise RuntimeError(
                "loader is in snapshot mode (steps == 0); use "
                "next_batch")
        t, g, e, f = (self.steps, self.groups, self.endpoints,
                      self.feature_dim)
        features = np.empty((t, g, e, f), np.float32)
        mask, target = self._pop(features)
        window = jnp.asarray(features)
        return window, Batch(features=window[-1].astype(jnp.bfloat16),
                             mask=jnp.asarray(mask.astype(bool)),
                             target=jnp.asarray(target))

    def stats(self) -> dict:
        if self._closed:
            raise RuntimeError("telemetry loader is closed")
        produced = ctypes.c_uint64()
        depth = ctypes.c_int()
        self._lib.aga_tl_stats(self._h, ctypes.byref(produced),
                               ctypes.byref(depth))
        return {"produced": produced.value, "ring_depth": depth.value}

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._lib.aga_tl_free(self._h)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def make_loader(kind: str, groups: int, endpoints: int,
                feature_dim: int = 8, seed: int = 0, **kw):
    """"native" -> C++ pipeline (degrades to synthetic with a warning
    when unbuildable); "synthetic" -> reproducible JAX batches."""
    if kind == "native":
        if native_available():
            return NativeTelemetryLoader(groups, endpoints, feature_dim,
                                         seed, **kw)
        logger.warning("native telemetry loader unavailable; "
                       "falling back to synthetic")
    elif kind != "synthetic":
        raise ValueError(f"unknown loader kind {kind!r}")
    return SyntheticTelemetryLoader(groups, endpoints, feature_dim, seed,
                                    steps=kw.get("steps", 0),
                                    per_step=kw.get("per_step", False))
