"""Traffic policy model: endpoint telemetry -> endpoint weights.

The flagship (and only) model of this framework.  A small MLP scores each
endpoint from its telemetry features (health, latency, capacity, ...);
``ops.weights.plan_weights`` turns scores into Global Accelerator weight
allocations.  Everything is jittable with static shapes: inputs are
[G, E, F] (groups x endpoints x features) in bfloat16 with a [G, E]
validity mask.

Design notes (TPU-first):
- the two matmuls are over the whole [G*E, F] batch so XLA tiles them
  onto the MXU; activations stay bfloat16, reductions in float32;
- no data-dependent control flow; padded groups ride along masked;
- ``train_step`` is pure (params, opt_state, batch) -> (params,
  opt_state, loss) and shards over a mesh (see parallel.plan).
"""
from __future__ import annotations

from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp

from ..ops.weights import plan_weights
from .common import TrainableModel, make_optimizer, masked_ce_loss

Params = Dict[str, jax.Array]

FEATURE_DIM = 8
HIDDEN_DIM = 128


class Batch(NamedTuple):
    features: jax.Array  # [G, E, F] bfloat16
    mask: jax.Array      # [G, E] bool
    target: jax.Array    # [G, E] float32 target weight distribution (sums to 1)


class TrafficPolicyModel(TrainableModel):
    """``serve`` picks the single-chip inference path:

    - ``auto`` (default): the fused Pallas kernel
      (``ops.pallas_mlp.forward_pallas`` — all three matmuls + masked
      softmax + weight quantisation in one VMEM-resident kernel, one
      HBM round trip per group block) when running on TPU, the plain
      XLA path otherwise (off-TPU the kernel only exists in interpret
      mode);
    - ``dense``: always the plain XLA path (what the sharded planners
      jit — pallas_call does not self-partition under pjit);
    - ``fused``: always the kernel (tests prove the fused path off-TPU).

    Training always uses the dense path (the kernel is inference-only:
    integer weight outputs have no gradient)."""

    def __init__(self, feature_dim: int = FEATURE_DIM,
                 hidden_dim: int = HIDDEN_DIM,
                 learning_rate: float = 1e-3,
                 serve: str = "auto", optimizer: str = "adam"):
        if serve not in ("auto", "dense", "fused"):
            raise ValueError(f"unknown serve impl {serve!r}")
        self.feature_dim = feature_dim
        self.hidden_dim = hidden_dim
        self.serve = serve
        self.optimizer = make_optimizer(optimizer, learning_rate)

    def init_params(self, key: jax.Array) -> Params:
        k1, k2, k3 = jax.random.split(key, 3)
        f, h = self.feature_dim, self.hidden_dim
        scale = lambda fan_in: 1.0 / jnp.sqrt(fan_in)
        return {
            "w1": (jax.random.normal(k1, (f, h)) * scale(f)).astype(jnp.bfloat16),
            "b1": jnp.zeros((h,), jnp.bfloat16),
            "w2": (jax.random.normal(k2, (h, h)) * scale(h)).astype(jnp.bfloat16),
            "b2": jnp.zeros((h,), jnp.bfloat16),
            "w3": (jax.random.normal(k3, (h, 1)) * scale(h)).astype(jnp.bfloat16),
            "b3": jnp.zeros((1,), jnp.bfloat16),
        }

    # -- forward --------------------------------------------------------

    def scores(self, params: Params, features: jax.Array) -> jax.Array:
        """[G, E, F] -> [G, E] float32 scores (two MXU matmuls)."""
        x = features.astype(jnp.bfloat16)
        h = jnp.maximum(x @ params["w1"] + params["b1"], 0)
        h = jnp.maximum(h @ params["w2"] + params["b2"], 0)
        s = h @ params["w3"] + params["b3"]
        return s[..., 0].astype(jnp.float32)

    def score_rows(self, params: Params, rows: jax.Array) -> jax.Array:
        """[N, F] packed endpoint rows -> [N] float32 scores.

        The columnar fleet planner's scoring entry
        (parallel/fleet_plan.py): one row per VALID endpoint, no
        padding lanes.  ``scores`` already batches over arbitrary
        leading dims and the per-row dot over F is shape-independent,
        so a packed row scores bit-identically to the same endpoint's
        lane in the per-object ``[1, E, F]`` forward — the property
        the jnp-reference oracle tests pin.  This alias makes that
        contract explicit instead of leaving fleet_plan.py to lean on
        an incidental broadcasting behaviour.
        """
        return self.scores(params, rows)

    def forward(self, params: Params, features: jax.Array,
                mask: jax.Array) -> jax.Array:
        """[G, E, F] + mask -> int32 GA weights [G, E] (see ``serve``)."""
        from ..compat import registry
        use_fused = (self.serve == "fused"
                     or (self.serve == "auto"
                         and registry.on_tpu_rung()))
        if use_fused:
            from ..ops.pallas_mlp import forward_pallas

            return forward_pallas(params, features, mask)
        return self.forward_dense(params, features, mask)

    def forward_dense(self, params: Params, features: jax.Array,
                      mask: jax.Array) -> jax.Array:
        """The plain XLA forward — what the sharded planners jit."""
        return plan_weights(self.scores(params, features), mask)

    # -- training -------------------------------------------------------

    def loss(self, params: Params, batch: Batch) -> jax.Array:
        """Masked cross-entropy between the planned distribution and the
        target weight distribution (shared impl: models/common.py)."""
        return masked_ce_loss(self.scores(params, batch.features),
                              batch.mask, batch.target)


def synthetic_batch(key: jax.Array, groups: int = 64, endpoints: int = 32,
                    feature_dim: int = FEATURE_DIM) -> Batch:
    """Random fleet telemetry with a plausible target: weight ~ capacity
    among healthy endpoints."""
    k1, k2, k3 = jax.random.split(key, 3)
    features = jax.random.normal(k1, (groups, endpoints, feature_dim),
                                 dtype=jnp.float32)
    healthy = jax.random.bernoulli(k2, 0.9, (groups, endpoints))
    mask = jax.random.bernoulli(k3, 0.8, (groups, endpoints))
    capacity = jnp.exp(features[..., 0])
    raw = jnp.where(mask & healthy, capacity, 0.0)
    denom = jnp.sum(raw, axis=-1, keepdims=True)
    target = jnp.where(denom > 0, raw / jnp.maximum(denom, 1e-9), 0.0)
    return Batch(features=features.astype(jnp.bfloat16), mask=mask,
                 target=target)
