"""Checkpoint / resume for the TPU compute track (orbax-backed).

The reference's "checkpointing" story is external-state colocation: AWS
tags and Route53 TXT records let a restarted controller re-discover
everything it manages (SURVEY.md §5 "Checkpoint / resume"; reference
pkg/cloudprovider/aws/global_accelerator.go:24-28, route53.go:18-20).
The controller side of this rebuild reproduces that design; this module
is its analogue for the compute track — the traffic policy model's
training state (params + optimizer state + step) persists through orbax
so a restarted trainer resumes the exact trajectory.

Restore goes through a template tree (a freshly-initialised
params/opt_state of the same model config) so dtypes, shapes, and the
optax NamedTuple structure survive the round-trip bit-exactly.

All orbax access rides the version shim (compat/orbaxshim.py): handler
names, the no-template restore spelling and restored-array placement
drift across orbax releases, and the shim owns all three (L111).
"""
from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ..compat import orbaxshim
from .traffic import Params, TrafficPolicyModel


class TrainCheckpointer:
    """Orbax CheckpointManager wrapper for (params, opt_state) trees.

    ``directory`` is created if missing; ``max_to_keep`` bounds retained
    steps (oldest garbage-collected, like the manager's default policy).
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 create: bool = True):
        """``create=False`` opens for restore-only: no mkdir side
        effects (a typo'd --policy-checkpoint path must not litter an
        empty orbax tree, and a read-only parent must not crash on
        mkdir instead of reporting 'no checkpoint')."""
        self._mngr = orbaxshim.make_manager(
            os.path.abspath(directory), max_to_keep=max_to_keep,
            create=create)

    def save(self, step: int, params: Params, opt_state: Any,
             wait: bool = False) -> None:
        self._mngr.save(step, args=orbaxshim.save_args(
            {"params": params, "opt_state": opt_state}))
        if wait:
            self._mngr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, model: TrafficPolicyModel,
                step: Optional[int] = None) -> Tuple[int, Params, Any]:
        """Restore (step, params, opt_state); ``step=None`` means latest.

        The model provides the template tree — restoring into abstract
        shape/dtype structs keeps bf16 params bf16 and rebuilds the
        optax state NamedTuples instead of plain dicts.
        """
        if step is None:
            step = self._mngr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self._mngr.directory}")
        def template():
            params = model.init_params(jax.random.PRNGKey(0))
            return {"params": params,
                    "opt_state": model.init_opt_state(params)}

        # eval_shape: the abstract template costs no compute or HBM.
        # Deliberately NO sharding annotation: orbax then restores each
        # array with the sharding recorded at save time (it warns about
        # this path, but it is load-bearing — a sharded trainer's
        # resume gets params AND opt_state back in the mesh layout it
        # saved, tests/test_checkpoint.py sharded-roundtrip).  The shim
        # re-places host-memory-kind leaves on device (orbax 0.7
        # restores unannotated templates to unpinned_host, which kills
        # the donating train step inside XLA).
        abstract = jax.eval_shape(template)
        restored = orbaxshim.restore_tree(self._mngr, step, abstract)
        return step, restored["params"], restored["opt_state"]

    def restore_params(self, model: TrafficPolicyModel,
                       step: Optional[int] = None,
                       validate: bool = True) -> Tuple[int, Params]:
        """Restore (step, params) IGNORING the optimizer state.

        The params-only consumers — eval, plan, the controller's
        weight policy — must not depend on which optimizer trained
        the checkpoint (a ``flat_adam`` trainer saves a
        FlatAdamState where the full-template restore expects optax's
        per-leaf tree and fails on the structure mismatch).  Restores
        the raw saved tree with no template, then validates + casts
        the params against the model's own init shapes, which is the
        shape-fidelity the full restore provided.  ``validate=False``
        skips the key/shape check (still casts known keys) for
        callers with their own richer diagnostics — the controller's
        weight policy names the config AND the fix.

        Transient cost (r4 ADVICE #4): the whole checkpoint — params
        AND optimizer state (for flat_adam, moments ~2x the params in
        f32) — is materialised in HOST memory before the opt_state is
        dropped.  orbax 0.11's Standard handler offers no partial
        restore of a StandardSave'd tree (verified: StandardRestore
        with a params-only template raises a structure mismatch, and
        PyTreeRestore/PLACEHOLDER don't match the registered
        handler), so the eager no-template restore is the available
        minimum; the discarded moments never reach device memory and
        are freed on return."""
        if step is None:
            step = self._mngr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self._mngr.directory}")
        restored = orbaxshim.restore_raw(self._mngr, step)
        raw = restored["params"]
        # abstract template: shapes/dtypes only, no RNG compute or a
        # second params copy in device memory (restore()'s rationale)
        template = jax.eval_shape(
            lambda: model.init_params(jax.random.PRNGKey(0)))
        if validate and set(raw) != set(template):
            raise ValueError(
                f"checkpoint params keys {sorted(raw)} do not match "
                f"the model's {sorted(template)}")
        params = {}
        for name, got in raw.items():
            got = jnp.asarray(got)
            ref = template.get(name)
            if ref is not None and got.shape == ref.shape:
                got = got.astype(ref.dtype)
            elif validate:
                raise ValueError(
                    f"checkpoint param {name!r} has shape {got.shape}, "
                    f"model expects "
                    f"{None if ref is None else ref.shape}")
            params[name] = got
        return step, params

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()

    def __enter__(self) -> "TrainCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
