"""Mixture-of-experts traffic model: per-region expert MLPs + learned gate.

Third model family of the compute track.  Global Accelerator endpoint
groups are regional, and regional fleets have regionally distinct
telemetry statistics (different latency floors, capacity mixes) — a
single shared MLP averages those regimes away.  This model routes each
endpoint group to its best ``top_k`` of ``n_experts`` specialist MLPs
(top-1 switch-style by default; top-2 with a ``capacity_factor``
budget is the large-scale configuration — over-capacity assignments
are dropped, as in GShard/Switch), trained end-to-end with the
standard load-balancing auxiliary loss so experts don't collapse.

The reference repo has no compute path at all (SURVEY.md §2: expert
parallelism ABSENT upstream); the closest structural analogue is its
per-region AWS client bundle (pkg/cloudprovider/aws/aws.go:18-38 — one
client set per region), which this family mirrors as one scoring expert
per region.

Design notes (TPU-first):
- single-chip forward gathers the routed expert's weights per group
  (``w1[route]``) and runs ONE batched einsum over [G, E, F] — a big
  MXU matmul, no per-expert Python loop, no dynamic shapes;
- routing is argmax (non-differentiable, as in Switch Transformers);
  the gate learns through the selected-probability scaling of the
  expert output and through the auxiliary loss;
- expert-parallel training shards experts one-per-device over an
  ``expert`` mesh axis with all_to_all dispatch: see
  ``parallel.moe.ShardedMoEPlanner``.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..ops.weights import plan_weights
from .common import TrainableModel, make_optimizer, masked_ce_loss
from .traffic import Batch

Params = Dict[str, jax.Array]

N_EXPERTS = 4
FEATURE_DIM = 8
HIDDEN_DIM = 64


def expert_capacity(block_groups: int, top_k: int, n_experts: int,
                    capacity_factor: "float | None") -> int:
    """Per-(block, expert) assignment budget, the GShard/Switch formula:
    ``ceil(capacity_factor * block_groups * top_k / n_experts)``.
    ``None`` means unbounded (every assignment kept — the pre-capacity
    behavior, and the only sane default for a weight planner where
    "dropping" a group means leaving its weights unplanned)."""
    if capacity_factor is None:
        return block_groups * top_k
    import math

    return max(1, math.ceil(
        capacity_factor * block_groups * top_k / n_experts))


class MoETrafficModel(TrainableModel):
    def __init__(self, n_experts: int = N_EXPERTS,
                 feature_dim: int = FEATURE_DIM,
                 hidden_dim: int = HIDDEN_DIM,
                 learning_rate: float = 1e-3,
                 aux_weight: float = 1e-2,
                 top_k: int = 1,
                 capacity_factor: "float | None" = None,
                 capacity_blocks: int = 1,
                 optimizer: str = "adam"):
        """``top_k`` routes each group to its best k experts (gate-
        probability-weighted sum of their outputs); ``capacity_factor``
        bounds per-expert load — assignments past the budget are
        DROPPED (contribute zero, gradient included), the standard
        load-imbalance regime of large-scale MoE.  ``capacity_blocks``
        partitions the G groups into contiguous blocks with the budget
        enforced per block: block = dispatch granularity, so a sharded
        planner over ``capacity_blocks`` batch shards computes the
        bit-identical function (see ShardedMoEPlanner)."""
        if not 1 <= top_k <= n_experts:
            raise ValueError(
                f"top_k ({top_k}) must be in [1, n_experts="
                f"{n_experts}]")
        if capacity_factor is not None and capacity_factor <= 0:
            raise ValueError(
                f"capacity_factor ({capacity_factor}) must be > 0 "
                f"(use None for unbounded)")
        self.n_experts = n_experts
        self.feature_dim = feature_dim
        self.hidden_dim = hidden_dim
        self.aux_weight = aux_weight
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.capacity_blocks = capacity_blocks
        self.optimizer = make_optimizer(optimizer, learning_rate)

    def init_params(self, key: jax.Array) -> Params:
        kg, k1, k2 = jax.random.split(key, 3)
        n, f, h = self.n_experts, self.feature_dim, self.hidden_dim
        scale = lambda fan_in: 1.0 / jnp.sqrt(fan_in)  # noqa: E731
        return {
            # the gate stays float32: it is tiny and its softmax drives
            # discrete routing, where bf16 logit ties would flap routes
            "wg": jax.random.normal(kg, (f, n)) * scale(f),
            "w1": (jax.random.normal(k1, (n, f, h))
                   * scale(f)).astype(jnp.bfloat16),
            "b1": jnp.zeros((n, h), jnp.bfloat16),
            "w2": (jax.random.normal(k2, (n, h, 1))
                   * scale(h)).astype(jnp.bfloat16),
            "b2": jnp.zeros((n, 1), jnp.bfloat16),
        }

    # -- gating ---------------------------------------------------------

    def gate(self, params: Params, features: jax.Array,
             mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """Masked-mean group embedding -> (route [G] int32, probs
        [G, n_experts] f32).  Top-1 routing on the softmax argmax."""
        m = mask[..., None].astype(jnp.float32)
        emb = (jnp.sum(features.astype(jnp.float32) * m, axis=1)
               / jnp.maximum(jnp.sum(m, axis=1), 1.0))      # [G, F]
        logits = emb @ params["wg"]                          # [G, n]
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), probs

    def gate_topk(self, params: Params, features: jax.Array,
                  mask: jax.Array) -> Tuple[jax.Array, jax.Array,
                                            jax.Array]:
        """(routes [G, K] int32 best-first, gate_p [G, K] f32 — the
        softmax probabilities of the selected experts, NOT renormalised
        so K=1 reproduces the switch estimator exactly — and the full
        probs [G, n]).  ``lax.top_k`` breaks ties first-index like the
        argmax in ``gate``, so routes[:, 0] == gate()'s route."""
        _, probs = self.gate(params, features, mask)
        gate_p, routes = jax.lax.top_k(probs, self.top_k)
        return routes.astype(jnp.int32), gate_p, probs

    def keep_mask(self, routes: jax.Array) -> jax.Array:
        """bool [G, K]: which routed assignments fit the capacity
        budget.  Priority is k-major within each capacity block (every
        group's primary choice beats any group's secondary, ties by
        group order) — the Switch top-2 convention where second
        choices drop first.  All-True when capacity_factor is None."""
        g, k = routes.shape
        nb = self.capacity_blocks
        if g % nb:
            raise ValueError(
                f"groups ({g}) must be divisible by capacity_blocks "
                f"({nb})")
        bs = g // nb
        # top_k routes are DISTINCT experts per group, so per-expert
        # load within a block can never exceed bs — cap beyond that is
        # equivalent to unbounded
        cap = min(expert_capacity(bs, k, self.n_experts,
                                  self.capacity_factor), bs)
        if cap >= bs:
            return jnp.ones((g, k), bool)
        # [nb, bs, K] -> k-major flat order per block [nb, K*bs]
        r = (routes.reshape(nb, bs, k).transpose(0, 2, 1)
             .reshape(nb, k * bs))
        onehot = jax.nn.one_hot(r, self.n_experts, dtype=jnp.int32)
        pos = jnp.cumsum(onehot, axis=1) - onehot
        mypos = jnp.take_along_axis(pos, r[..., None], axis=2)[..., 0]
        keep = mypos < cap
        return (keep.reshape(nb, k, bs).transpose(0, 2, 1)
                .reshape(g, k))

    # -- forward --------------------------------------------------------

    def expert_scores(self, params: Params, features: jax.Array,
                      route: jax.Array) -> jax.Array:
        """Apply each group's routed expert: [G, E, F] + route [G] ->
        raw scores [G, E] f32 (one batched MXU einsum per layer)."""
        x = features.astype(jnp.bfloat16)
        w1 = params["w1"][route]                             # [G, F, H]
        b1 = params["b1"][route]                             # [G, H]
        w2 = params["w2"][route]                             # [G, H, 1]
        b2 = params["b2"][route]                             # [G, 1]
        h = jnp.maximum(jnp.einsum("gef,gfh->geh", x, w1)
                        + b1[:, None, :], 0)
        s = jnp.einsum("geh,gho->geo", h, w2)[..., 0] + b2[:, None, 0]
        return s.astype(jnp.float32)

    def scored(self, params: Params, features: jax.Array,
               mask: jax.Array) -> Tuple[jax.Array, jax.Array,
                                         jax.Array]:
        """The one top-k estimator implementation: (scores [G, E] f32,
        route [G] — the primary choice, probs [G, n]).  Scores are the
        gate-probability-weighted sum of the kept routed experts'
        outputs (K=1, unbounded capacity = the switch estimator
        exactly); a dropped assignment contributes zero, so its
        gradient path vanishes too — tokens degrade, they don't
        corrupt.  ``loss`` reuses route/probs for the aux term;
        ``parallel.moe`` swaps ``expert_scores`` for the all_to_all
        dispatch but keeps this same composition."""
        routes, gate_p, probs = self.gate_topk(params, features, mask)
        keep = self.keep_mask(routes)
        s = jnp.zeros(features.shape[:2], jnp.float32)
        for k in range(self.top_k):  # K is tiny and static: unrolled
            sk = self.expert_scores(params, features, routes[:, k])
            s = s + jnp.where(keep[:, k, None],
                              sk * gate_p[:, k, None], 0.0)
        return s, routes[:, 0], probs

    def scores(self, params: Params, features: jax.Array,
               mask: jax.Array) -> jax.Array:
        """[G, E, F] + mask -> [G, E] f32 switch-estimator scores."""
        return self.scored(params, features, mask)[0]

    def forward(self, params: Params, features: jax.Array,
                mask: jax.Array) -> jax.Array:
        """[G, E, F] + mask -> int32 GA weights [G, E]."""
        return plan_weights(self.scores(params, features, mask), mask)

    def dispatch_stats(self, params: Params, features: jax.Array,
                       mask: jax.Array) -> Dict[str, jax.Array]:
        """Dropped-assignment accounting (observability for the
        capacity regime): kept fraction, dropped count, and per-expert
        primary-route load fractions."""
        routes, _, _ = self.gate_topk(params, features, mask)
        keep = self.keep_mask(routes)
        load = jnp.mean(
            jax.nn.one_hot(routes[:, 0], self.n_experts,
                           dtype=jnp.float32), axis=0)
        return {
            "kept_fraction": jnp.mean(keep.astype(jnp.float32)),
            "dropped": jnp.sum(~keep),
            "expert_load": load,
        }

    # -- training -------------------------------------------------------

    def aux_loss(self, route: jax.Array, probs: jax.Array) -> jax.Array:
        """Switch load-balancing loss: n * sum_e f_e * P_e, minimised at
        uniform routing (f_e = fraction routed to e, P_e = mean gate
        probability of e)."""
        f = jnp.mean(
            jax.nn.one_hot(route, self.n_experts, dtype=jnp.float32),
            axis=0)
        p = jnp.mean(probs, axis=0)
        return self.n_experts * jnp.sum(f * p)

    def loss(self, params: Params, batch: Batch) -> jax.Array:
        s, route, probs = self.scored(params, batch.features,
                                      batch.mask)
        ce = masked_ce_loss(s, batch.mask, batch.target)
        return ce + self.aux_weight * self.aux_loss(route, probs)


def synthetic_moe_batch(key: jax.Array, groups: int = 64,
                        endpoints: int = 32,
                        feature_dim: int = FEATURE_DIM,
                        n_regions: int = N_EXPERTS) -> Batch:
    """Region-flavoured fleet telemetry: each group's features carry a
    per-region offset (distinct telemetry regimes), so a well-trained
    gate can separate regions and experts can specialise.  Target is
    weight ~ capacity among healthy endpoints, as in
    ``traffic.synthetic_batch``."""
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    region = jax.random.randint(k4, (groups,), 0, n_regions)
    offset = 2.0 * jax.random.normal(k5, (n_regions, feature_dim))
    features = (jax.random.normal(k1, (groups, endpoints, feature_dim))
                + offset[region][:, None, :])
    healthy = jax.random.bernoulli(k2, 0.9, (groups, endpoints))
    mask = jax.random.bernoulli(k3, 0.8, (groups, endpoints))
    capacity = jnp.exp(features[..., 0])
    raw = jnp.where(mask & healthy, capacity, 0.0)
    denom = jnp.sum(raw, axis=-1, keepdims=True)
    target = jnp.where(denom > 0, raw / jnp.maximum(denom, 1e-9), 0.0)
    return Batch(features=features.astype(jnp.bfloat16), mask=mask,
                 target=target)
