"""Mixture-of-experts traffic model: per-region expert MLPs + learned gate.

Third model family of the compute track.  Global Accelerator endpoint
groups are regional, and regional fleets have regionally distinct
telemetry statistics (different latency floors, capacity mixes) — a
single shared MLP averages those regimes away.  This model routes each
endpoint group to one of ``n_experts`` specialist MLPs with a learned
top-1 (switch-style) gate, trained end-to-end with the standard
load-balancing auxiliary loss so experts don't collapse.

The reference repo has no compute path at all (SURVEY.md §2: expert
parallelism ABSENT upstream); the closest structural analogue is its
per-region AWS client bundle (pkg/cloudprovider/aws/aws.go:18-38 — one
client set per region), which this family mirrors as one scoring expert
per region.

Design notes (TPU-first):
- single-chip forward gathers the routed expert's weights per group
  (``w1[route]``) and runs ONE batched einsum over [G, E, F] — a big
  MXU matmul, no per-expert Python loop, no dynamic shapes;
- routing is argmax (non-differentiable, as in Switch Transformers);
  the gate learns through the selected-probability scaling of the
  expert output and through the auxiliary loss;
- expert-parallel training shards experts one-per-device over an
  ``expert`` mesh axis with all_to_all dispatch: see
  ``parallel.moe.ShardedMoEPlanner``.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from ..ops.weights import plan_weights
from .common import TrainableModel, masked_ce_loss
from .traffic import Batch

Params = Dict[str, jax.Array]

N_EXPERTS = 4
FEATURE_DIM = 8
HIDDEN_DIM = 64


class MoETrafficModel(TrainableModel):
    def __init__(self, n_experts: int = N_EXPERTS,
                 feature_dim: int = FEATURE_DIM,
                 hidden_dim: int = HIDDEN_DIM,
                 learning_rate: float = 1e-3,
                 aux_weight: float = 1e-2):
        self.n_experts = n_experts
        self.feature_dim = feature_dim
        self.hidden_dim = hidden_dim
        self.aux_weight = aux_weight
        self.optimizer = optax.adam(learning_rate)

    def init_params(self, key: jax.Array) -> Params:
        kg, k1, k2 = jax.random.split(key, 3)
        n, f, h = self.n_experts, self.feature_dim, self.hidden_dim
        scale = lambda fan_in: 1.0 / jnp.sqrt(fan_in)  # noqa: E731
        return {
            # the gate stays float32: it is tiny and its softmax drives
            # discrete routing, where bf16 logit ties would flap routes
            "wg": jax.random.normal(kg, (f, n)) * scale(f),
            "w1": (jax.random.normal(k1, (n, f, h))
                   * scale(f)).astype(jnp.bfloat16),
            "b1": jnp.zeros((n, h), jnp.bfloat16),
            "w2": (jax.random.normal(k2, (n, h, 1))
                   * scale(h)).astype(jnp.bfloat16),
            "b2": jnp.zeros((n, 1), jnp.bfloat16),
        }

    # -- gating ---------------------------------------------------------

    def gate(self, params: Params, features: jax.Array,
             mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """Masked-mean group embedding -> (route [G] int32, probs
        [G, n_experts] f32).  Top-1 routing on the softmax argmax."""
        m = mask[..., None].astype(jnp.float32)
        emb = (jnp.sum(features.astype(jnp.float32) * m, axis=1)
               / jnp.maximum(jnp.sum(m, axis=1), 1.0))      # [G, F]
        logits = emb @ params["wg"]                          # [G, n]
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), probs

    # -- forward --------------------------------------------------------

    def expert_scores(self, params: Params, features: jax.Array,
                      route: jax.Array) -> jax.Array:
        """Apply each group's routed expert: [G, E, F] + route [G] ->
        raw scores [G, E] f32 (one batched MXU einsum per layer)."""
        x = features.astype(jnp.bfloat16)
        w1 = params["w1"][route]                             # [G, F, H]
        b1 = params["b1"][route]                             # [G, H]
        w2 = params["w2"][route]                             # [G, H, 1]
        b2 = params["b2"][route]                             # [G, 1]
        h = jnp.maximum(jnp.einsum("gef,gfh->geh", x, w1)
                        + b1[:, None, :], 0)
        s = jnp.einsum("geh,gho->geo", h, w2)[..., 0] + b2[:, None, 0]
        return s.astype(jnp.float32)

    def scored(self, params: Params, features: jax.Array,
               mask: jax.Array) -> Tuple[jax.Array, jax.Array,
                                         jax.Array]:
        """The one switch-estimator implementation: (scores [G, E] f32,
        route [G], probs [G, n]).  Scores are the routed expert's output
        scaled by the selected gate probability — that product is the
        gate's gradient path.  ``loss`` reuses route/probs for the aux
        term; ``parallel.moe`` swaps ``expert_scores`` for the
        all_to_all dispatch but keeps this same composition."""
        route, probs = self.gate(params, features, mask)
        s = self.expert_scores(params, features, route)
        p_sel = jnp.take_along_axis(probs, route[:, None], axis=1)
        return s * p_sel, route, probs

    def scores(self, params: Params, features: jax.Array,
               mask: jax.Array) -> jax.Array:
        """[G, E, F] + mask -> [G, E] f32 switch-estimator scores."""
        return self.scored(params, features, mask)[0]

    def forward(self, params: Params, features: jax.Array,
                mask: jax.Array) -> jax.Array:
        """[G, E, F] + mask -> int32 GA weights [G, E]."""
        return plan_weights(self.scores(params, features, mask), mask)

    # -- training -------------------------------------------------------

    def aux_loss(self, route: jax.Array, probs: jax.Array) -> jax.Array:
        """Switch load-balancing loss: n * sum_e f_e * P_e, minimised at
        uniform routing (f_e = fraction routed to e, P_e = mean gate
        probability of e)."""
        f = jnp.mean(
            jax.nn.one_hot(route, self.n_experts, dtype=jnp.float32),
            axis=0)
        p = jnp.mean(probs, axis=0)
        return self.n_experts * jnp.sum(f * p)

    def loss(self, params: Params, batch: Batch) -> jax.Array:
        s, route, probs = self.scored(params, batch.features,
                                      batch.mask)
        ce = masked_ce_loss(s, batch.mask, batch.target)
        return ce + self.aux_weight * self.aux_loss(route, probs)


def synthetic_moe_batch(key: jax.Array, groups: int = 64,
                        endpoints: int = 32,
                        feature_dim: int = FEATURE_DIM,
                        n_regions: int = N_EXPERTS) -> Batch:
    """Region-flavoured fleet telemetry: each group's features carry a
    per-region offset (distinct telemetry regimes), so a well-trained
    gate can separate regions and experts can specialise.  Target is
    weight ~ capacity among healthy endpoints, as in
    ``traffic.synthetic_batch``."""
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    region = jax.random.randint(k4, (groups,), 0, n_regions)
    offset = 2.0 * jax.random.normal(k5, (n_regions, feature_dim))
    features = (jax.random.normal(k1, (groups, endpoints, feature_dim))
                + offset[region][:, None, :])
    healthy = jax.random.bernoulli(k2, 0.9, (groups, endpoints))
    mask = jax.random.bernoulli(k3, 0.8, (groups, endpoints))
    capacity = jnp.exp(features[..., 0])
    raw = jnp.where(mask & healthy, capacity, 0.0)
    denom = jnp.sum(raw, axis=-1, keepdims=True)
    target = jnp.where(denom > 0, raw / jnp.maximum(denom, 1e-9), 0.0)
    return Batch(features=features.astype(jnp.bfloat16), mask=mask,
                 target=target)
