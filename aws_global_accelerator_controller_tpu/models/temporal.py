"""Temporal traffic model: attention over telemetry history -> weights.

Second model family of the compute track (the first, ``traffic.py``, is
a stateless MLP over the latest telemetry snapshot).  This one consumes
a telemetry *window* ``[T, G, E, F]`` and lets every endpoint attend
causally over its own history before scoring, so slow-moving signals
(capacity trends, flapping health) inform the weight plan.

The attention mapping is TPU-exact: endpoints are independent of each
other along the time axis, so the (G*E) endpoint streams ARE the
attention heads — q = k = v = [T, G*E, D] feeds the same kernels the
long-context stack provides, with zero reshuffling:

- single chip: ``ops.pallas_attention.flash_attention`` (MXU-tiled);
- sequence-sharded: ``parallel.make_ring_attention`` over a mesh axis
  (ring over ICI; pass ``local="flash"`` for flash-in-VMEM inside).

Everything is jittable with static shapes; bfloat16 on the matmuls,
float32 accumulation (the kernels pin preferred_element_type).
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from ..ops.weights import plan_weights
from .common import TrainableModel, make_optimizer, masked_ce_loss
from .traffic import Batch

Params = Dict[str, jax.Array]

# Below this window length the dense reference out-runs the kernel:
# even with auto-sized flash blocks (pallas_attention._auto_block) the
# per-call dispatch and tiling overhead beats XLA's fused dense matmuls
# for tiny T.  At/above it the kernel wins and the CLI defaults reach it.
FLASH_MIN_WINDOW = 64


class TemporalTrafficModel(TrainableModel):
    """Causal self-attention per endpoint stream + MLP head.

    feature_dim F -> embed_dim D per timestep, one causal attention pass
    over the T axis, last-step representation -> score.
    """

    def __init__(self, feature_dim: int = 8, embed_dim: int = 32,
                 hidden_dim: int = 64, learning_rate: float = 1e-3,
                 attention: str = "flash", supervision: str = "last",
                 remat: bool = False, head: str = "reference",
                 attention_chunk: int = 0, optimizer: str = "adam"):
        """``supervision`` picks the training objective:

        - ``"last"`` (default): only the final step's scores are
          supervised — the original objective.  Training then routes
          through the O(T) last-query attention (``scores_last``):
          the full [T, T] attention computes T-1 output rows whose
          gradient is exactly zero under this loss, so the switch is
          a pure algorithmic win (same math, ~T-fold less attention
          compute at the benchmark shape).
        - ``"sequence"``: every step is supervised against the
          per-step target (``synthetic_window(per_step=True)``) — the
          regime where the full causal attention (flash kernel, ring
          sharding) is genuinely load-bearing, and the better
          training signal (T targets per window instead of 1).

        ``remat`` wraps the per-step head in ``jax.checkpoint``:
        under sequence supervision the [T, S, H] hidden activations
        otherwise sit in HBM for the backward — at long windows they
        dwarf the flash VJP's O(T) residuals.  Recompute is one relu
        matmul per step; numerics identical (same f32 ops replayed),
        the same lever ``deep --remat`` applies to pipeline stages.

        ``head`` picks the sequence-supervision scoring-head impl
        (the [T, S, D] -> [T, S] relu-MLP; the 2-D last-row paths are
        always dense — they are too small to dispatch a kernel for):

        - ``"reference"`` (default): dense XLA.  Measured FASTER than
          the kernel at the benchmark shape (0.23 vs 0.52 ms fwd+grad
          on v5e, interleaved A/B) — XLA's epilogue fusion already
          handles this op; the kernel is kept as a tested negative
          result (``ops.pallas_head`` docstring).
        - ``"fused"``: the Pallas fused head
          (``ops.pallas_head.score_head``) on TPU — one HBM pass in
          each direction, no [T, S, H] hidden ever materialised, its
          own recompute VJP (so ``remat`` has nothing left to save
          and is skipped for the head).  Off-TPU: dense.
        - ``"fused_always"``: the kernel on any backend (interpret
          mode off-TPU) — tests prove the fused path end-to-end.
        """
        if attention not in ("flash", "flash_always", "reference"):
            raise ValueError(f"unknown attention impl {attention!r}")
        if supervision not in ("last", "sequence"):
            raise ValueError(f"unknown supervision {supervision!r}")
        if head not in ("fused", "fused_always", "reference"):
            raise ValueError(f"unknown head impl {head!r}")
        if attention_chunk < 0:
            raise ValueError("attention_chunk must be >= 0")
        self.attention_chunk = attention_chunk
        self.remat = remat
        self.head = head
        self.feature_dim = feature_dim
        self.embed_dim = embed_dim
        self.hidden_dim = hidden_dim
        self.attention = attention
        self.supervision = supervision
        # "flat_adam": Adam over one raveled vector — kills the
        # per-leaf tiny-op tax on the unsharded train step
        # (models.common.flat_adam docstring).  Sharded planners run
        # the model's optimizer through train_step, so a flat state
        # rides replicated there (their opt in_sharding is
        # unconstrained) and each ravel gathers the sharded grads —
        # correct but anti-scaling; keep "adam" for sharded training.
        self.optimizer = make_optimizer(optimizer, learning_rate)

    def init_params(self, key: jax.Array) -> Params:
        ks = jax.random.split(key, 6)
        f, d, h = self.feature_dim, self.embed_dim, self.hidden_dim
        s = lambda fan_in: 1.0 / jnp.sqrt(fan_in)
        init = lambda k, shape, fan: (
            jax.random.normal(k, shape) * s(fan)).astype(jnp.bfloat16)
        return {
            "embed": init(ks[0], (f, d), f),
            "wq": init(ks[1], (d, d), d),
            "wk": init(ks[2], (d, d), d),
            "wv": init(ks[3], (d, d), d),
            "w1": init(ks[4], (d, h), d),
            "b1": jnp.zeros((h,), jnp.bfloat16),
            "w2": init(ks[5], (h, 1), h),
            "b2": jnp.zeros((1,), jnp.bfloat16),
        }

    # -- forward --------------------------------------------------------

    def _attend(self, q, k, v):
        """q/k/v: [T, S, D] (S = G*E endpoint streams as heads).

        The Pallas kernel carries a custom flash VJP, so BOTH the
        serving forward and the training gradient run it — long-window
        training gets the O(T) memory benefit the kernel exists for.
        Dispatch:

        - ``flash``: the kernel when T >= FLASH_MIN_WINDOW and running
          on TPU.  Off-TPU the kernel only exists in interpret mode,
          which serialises over the S heads — the dense reference is
          orders of magnitude faster there.
        - ``flash_always``: the kernel whenever T >= FLASH_MIN_WINDOW,
          any backend — for tests proving the kernel path (forward AND
          backward) end-to-end on the CPU mesh.
        - ``reference``: always dense.

        ``attention_chunk`` (constructor knob, 0 = off) splits the S
        streams axis into chunks of at most that many heads, one
        kernel call each — attention is per-head independent, so the
        split is exact.  Purpose: chunks of <= 32 heads fall inside
        the fused one-sweep backward's head gate
        (``pallas_attention._FUSED_BWD_MAX_HEADS``), which the
        benchmark shape's S = 128 otherwise exceeds.  Opt-in until
        its compile + win are confirmed on-chip.
        """
        from ..compat import registry
        use_kernel = (q.shape[0] >= FLASH_MIN_WINDOW
                      and (self.attention == "flash_always"
                           or (self.attention == "flash"
                               and registry.on_tpu_rung())))
        if use_kernel:
            from ..ops import pallas_attention
            s = q.shape[1]
            chunk = self.attention_chunk
            if chunk and s > chunk:
                parts = [
                    pallas_attention.flash_attention(
                        q[:, c:c + chunk], k[:, c:c + chunk],
                        v[:, c:c + chunk], causal=True)
                    for c in range(0, s, chunk)]
                return jnp.concatenate(parts, axis=1)
            return pallas_attention.flash_attention(q, k, v, causal=True)
        from ..parallel.ring_attention import attention_reference
        return attention_reference(q, k, v, causal=True)

    def _embed_kv(self, params: Params, window: jax.Array):
        """[T, G, E, F] -> (k, v [T, S, D]) for the last-query path.

        K/V come STRAIGHT from the raw features: with no nonlinearity
        between the embedding and the K/V projections,
        ``(x @ We) @ Wkv == x @ (We @ Wkv)`` — one composed [F, 2D]
        matrix (F is tiny), so the [T, S, D] embedding is never
        materialised on this path (the caller forms only the last
        row's embedding for q) and the projection contracts F instead
        of D.  Numerics shift by one bf16 rounding association (the
        composed product rounds once where the chained matmuls
        rounded the embedding); the oracle-parity tests carry the
        bf16-scale tolerance."""
        t, g, e, f = window.shape
        x = window.astype(jnp.bfloat16).reshape(t, g * e, f)
        d = params["embed"].shape[-1]
        wkv = params["embed"] @ jnp.concatenate(
            (params["wk"], params["wv"]), axis=1)      # [F, 2D]
        kv = x @ wkv                                   # [T, S, 2D]
        return kv[..., :d], kv[..., d:]

    def _embed_qkv(self, params: Params, window: jax.Array):
        """[T, G, E, F] -> (q, k, v [T, S, D]) for the full-attention
        paths, projected through ONE composed [F, 3D] matrix.

        With no bias or nonlinearity between the embedding and the
        Q/K/V projections, ``(x@We) @ [Wq|Wk|Wv] == x @ (We@[Wq|Wk|
        Wv])`` — exact in real arithmetic.  The composition deletes
        the [T, S, D] embedding from this path entirely (it crossed
        HBM twice) and contracts the tiny feature dim instead of D;
        in the backward, the two [T*S]-row matmuls the chained form
        needs (dW_qkv = embᵀ@dqkv and demb = dqkv@Wᵀ) collapse to one
        xᵀ@dqkv with an [F, 3D] output, the weight chain riding tiny
        [F, D]-class products.  Same bf16-association caveat as
        ``_embed_kv`` (one rounding moved); every consumer — flash,
        ring, reference attention, both supervision modes — shifts
        together, and the last-query path's composed K/V are now the
        SAME matrices this path slices."""
        t, g, e, f = window.shape
        x = window.astype(jnp.bfloat16).reshape(t, g * e, f)
        d = params["embed"].shape[-1]
        wqkv = params["embed"] @ jnp.concatenate(
            (params["wq"], params["wk"], params["wv"]),
            axis=1)                                    # [F, 3D]
        qkv = x @ wqkv                                 # [T, S, 3D]
        return qkv[..., :d], qkv[..., d:2 * d], qkv[..., 2 * d:]

    def _use_fused_head(self, ndim: int = 3) -> bool:
        """One predicate for BOTH the head dispatch and scores_seq's
        remat decision — split copies would silently desync (a remat
        that replays the kernel forward, or a dense head that lost
        its checkpoint)."""
        from ..compat import registry
        return (ndim == 3
                and (self.head == "fused_always"
                     or (self.head == "fused"
                         and registry.on_tpu_rung())))

    def _head(self, params: Params, rep: jax.Array) -> jax.Array:
        """[..., D] attended representation -> [...] float32 score.

        3-D [T, S, D] inputs (the sequence-supervision batch) dispatch
        to the fused Pallas head per the ``head`` mode (a measured
        negative result at the benchmark shape — ``ops.pallas_head``
        docstring — so the default mode is the dense path); 2-D
        last-row inputs stay dense always.
        """
        if self._use_fused_head(rep.ndim):
            from ..ops.pallas_head import score_head
            return score_head(rep, params["w1"], params["b1"],
                              params["w2"], params["b2"])
        h = jnp.maximum(rep.astype(jnp.bfloat16) @ params["w1"]
                        + params["b1"], 0)
        return (h @ params["w2"] + params["b2"])[..., 0].astype(
            jnp.float32)

    def scores(self, params: Params, window: jax.Array,
               attend=None) -> jax.Array:
        """[T, G, E, F] telemetry window -> [G, E] float32 scores via
        the FULL causal attention (last output row through the head).

        ``attend`` overrides the attention impl with a fn(q, k, v:
        [T, S, D]) -> [T, S, D] — the seam `parallel.plan.
        ShardedTemporalPlanner` uses to swap in ring attention over a
        sequence-sharded mesh.  ``scores_last`` computes the same
        quantity in O(T) and is what serving uses; this full form is
        the oracle and the sequence-supervision building block.
        """
        attend = attend or self._attend
        t, g, e, f = window.shape
        q, k, v = self._embed_qkv(params, window)
        attended = attend(q, k, v)                     # [T, S, D]
        return self._head(params, attended[-1]).reshape(g, e)

    def scores_last(self, params: Params, window: jax.Array,
                    attend_last=None, last_index: int = -1
                    ) -> jax.Array:
        """[T, G, E, F] -> [G, E] scores in O(T*S*D) — same math as
        ``scores`` but only the final query row is ever formed: the
        last step attends its whole history (causality is vacuous for
        the last row), softmax over T, one weighted sum.  No [T, T]
        matrix, no flash kernel needed.  ``attend_last`` overrides
        with a fn(q_last [S, D], k, v [T, S, D]) -> [S, D] (the
        sharded planner's seam).  ``last_index`` names which row is
        the temporally-last one — under the zigzag ring layout the
        final timestep lives at the end of shard 0's block, not at
        row -1 (the attended key set is order-free, so only the query
        row needs the index)."""
        t, g, e, f = window.shape
        k, v = self._embed_kv(params, window)
        x_last = window[last_index].astype(
            jnp.bfloat16).reshape(g * e, f)
        # composed like K/V (_embed_kv): q is then a slice of the same
        # projection algebra the full path runs — per-column bitwise
        # agreement, so last-vs-full parity is attention association
        # alone
        q_last = x_last @ (params["embed"] @ params["wq"])  # [S, D]
        attend_last = attend_last or attention_last_reference
        rep = attend_last(q_last, k, v)                # [S, D]
        return self._head(params, rep).reshape(g, e)

    def scores_seq(self, params: Params, window: jax.Array,
                   attend=None) -> jax.Array:
        """[T, G, E, F] -> [T, G, E] per-step scores: every timestep's
        causal-attended representation through the head — the
        sequence-supervision objective where the full attention (flash
        kernel / ring sharding) is genuinely load-bearing."""
        attend = attend or self._attend
        t, g, e, f = window.shape
        q, k, v = self._embed_qkv(params, window)
        attended = attend(q, k, v)                     # [T, S, D]
        # the fused head's VJP recomputes its hidden internally, so
        # wrapping it in jax.checkpoint would only replay the kernel
        # forward for nothing — remat applies to the dense head alone
        head = (jax.checkpoint(self._head)
                if self.remat and not self._use_fused_head()
                else self._head)
        return head(params, attended).reshape(t, g, e)

    def forward(self, params: Params, window: jax.Array,
                mask: jax.Array, attend=None) -> jax.Array:
        """[T, G, E, F] + [G, E] mask -> int32 GA weights [G, E].

        Serving plans from the latest telemetry only, so it takes the
        O(T) last-query path; pass ``attend`` to force the full
        attention (the oracle tests do)."""
        if attend is not None:
            return plan_weights(self.scores(params, window, attend),
                                mask)
        return plan_weights(self.scores_last(params, window), mask)

    # -- training -------------------------------------------------------

    def loss(self, params: Params, window: jax.Array, batch: Batch,
             attend=None) -> jax.Array:
        """``supervision="last"``: CE on the final step's scores via
        the O(T) path (an ``attend`` override forces the full
        attention — sharded planners training through ring attention
        pass it).  ``supervision="sequence"``: masked CE per step
        against ``batch.target`` [T, G, E], averaged over steps."""
        if self.supervision == "sequence":
            seq = self.scores_seq(params, window, attend)  # [T, G, E]
            per_step = jax.vmap(masked_ce_loss,
                                in_axes=(0, None, 0))(
                seq, batch.mask, batch.target)
            return jnp.mean(per_step)
        if attend is not None:
            return masked_ce_loss(
                self.scores(params, window, attend), batch.mask,
                batch.target)
        return masked_ce_loss(
            self.scores_last(params, window), batch.mask, batch.target)


def attention_last_reference(q_last: jax.Array, k: jax.Array,
                             v: jax.Array) -> jax.Array:
    """Last-query attention: q_last [S, D], k/v [T, S, D] -> [S, D].

    The final row of causal softmax attention — equal to
    ``attention_reference(q, k, v, causal=True)[-1]`` whenever
    ``q[-1] == q_last`` — computed without ever forming the other
    T-1 rows (float32 accumulation like the oracle)."""
    qf = q_last.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    scale = qf.shape[-1] ** -0.5
    s = jnp.einsum("sd,tsd->st", qf, kf) * scale       # [S, T]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("st,tsd->sd", p, vf)


def synthetic_window(key: jax.Array, steps: int = 8, groups: int = 16,
                     endpoints: int = 8, feature_dim: int = 8,
                     per_step: bool = False):
    """Random telemetry window + a target favouring endpoints whose
    capacity signal trends up over the window.

    ``per_step=True`` emits the sequence-supervision batch: target
    [T, G, E] where step t's target follows the trend accumulated up
    to t (step 0's trend is zero — a uniform target over the mask)."""
    k1, k2 = jax.random.split(key)
    window = jax.random.normal(
        k1, (steps, groups, endpoints, feature_dim), dtype=jnp.float32)
    mask = jax.random.bernoulli(k2, 0.85, (groups, endpoints))

    def target_for(trend):
        raw = jnp.where(mask, jnp.exp(trend), 0.0)
        denom = jnp.sum(raw, axis=-1, keepdims=True)
        return jnp.where(denom > 0, raw / jnp.maximum(denom, 1e-9),
                         0.0)

    if per_step:
        target = jax.vmap(target_for)(
            window[..., 0] - window[0, ..., 0])        # [T, G, E]
    else:
        target = target_for(window[-1, ..., 0] - window[0, ..., 0])
    return window, Batch(features=window[-1].astype(jnp.bfloat16),
                         mask=mask, target=target)
