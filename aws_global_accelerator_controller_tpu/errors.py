"""Typed errors for the reconcile engine.

Mirrors reference pkg/errors/errors.go:8-39 (NoRetryError + IsNoRetry with
wrap support via errors.As) and the apimachinery NotFound predicate the
reconcile loop dispatches on (pkg/reconcile/reconcile.go:59-66).
"""
from __future__ import annotations


class NoRetryError(Exception):
    """Error that must NOT be requeued by the reconcile loop.

    Reference pkg/errors/errors.go:8-27; consumed at
    pkg/reconcile/reconcile.go:71-73.
    """


def new_no_retry_errorf(fmt: str, *args) -> NoRetryError:
    return NoRetryError(fmt % args if args else fmt)


def is_no_retry(err: BaseException) -> bool:
    """True if ``err`` is, or explicitly wraps (via ``raise ... from``), a
    NoRetryError -- the errors.As-over-wrapped-errors analogue
    (pkg/errors/errors.go:33-39).

    Only the explicit ``__cause__`` chain is followed: Go's errors.As only
    walks Unwrap(), and Python's implicit ``__context__`` would misclassify
    unrelated errors raised while handling a NoRetryError.
    """
    seen = set()
    cur: BaseException | None = err
    while cur is not None and id(cur) not in seen:
        if isinstance(cur, NoRetryError):
            return True
        seen.add(id(cur))
        cur = cur.__cause__
    return False


class NotFoundError(Exception):
    """API-object-not-found, the kerrors.IsNotFound analogue."""

    def __init__(self, kind: str = "", key: str = ""):
        super().__init__(f"{kind} {key!r} not found")
        self.kind = kind
        self.key = key


def is_not_found(err: BaseException) -> bool:
    return isinstance(err, NotFoundError)


class ConflictError(Exception):
    """Optimistic-concurrency conflict on update (resourceVersion mismatch)."""


class AdmissionDeniedError(Exception):
    """A validating admission webhook rejected the request."""

    def __init__(self, code: int, message: str):
        super().__init__(f"admission webhook denied the request "
                         f"({code}): {message}")
        self.code = code
        self.reason = message


# -- AWS error-code taxonomy (resilience/classify.py dispatches on
# these; real.py maps boto ClientError codes into them) ----------------

# The service asked the caller to slow down: retry helps, but only
# after backing off AND shrinking the client-side send rate
# (resilience.AdaptiveTokenBucket).
THROTTLE_CODES = frozenset({
    "Throttling", "ThrottlingException", "ThrottledException",
    "TooManyRequestsException", "RequestLimitExceeded",
    "RequestThrottled", "RequestThrottledException", "SlowDown",
    "PriorRequestNotComplete", "TransactionInProgressException",
    "LimitExceededException",
})

# The service (or the path to it) hiccuped: a plain capped-backoff
# retry is the right response.  5xx HTTP statuses map here too
# (real.py _wrap_client_error).
TRANSIENT_CODES = frozenset({
    "InternalError", "InternalFailure", "InternalServiceError",
    "InternalServiceErrorException", "ServiceUnavailable",
    "ServiceUnavailableException", "ServiceFailure",
    "RequestTimeout", "RequestTimeoutException", "RequestExpired",
    "IDPCommunicationError", "ConnectionError", "HTTPClientError",
})

# Codes that spell "the referenced thing does not exist" without the
# conventional *NotFoundException suffix.
NOT_FOUND_CODES = frozenset({
    "NoSuchHostedZone", "NoSuchEntity", "NotFound", "ResourceNotFound",
})


class AWSAPIError(Exception):
    """Base for simulated/real AWS API errors, carrying an error code the
    way smithy.APIError does (reference
    pkg/controller/endpointgroupbinding/reconcile.go:50-56).

    ``retryable`` is the transport layer's verdict when it has one
    (boto marks 5xx/connection errors retryable); ``None`` means
    "classify by code" (resilience/classify.py).
    """

    def __init__(self, code: str, message: str = "",
                 retryable: "bool | None" = None):
        super().__init__(message or code)
        self.code = code
        self.retryable = retryable

    def is_throttle(self) -> bool:
        return self.code in THROTTLE_CODES


def _walk_causes(err: BaseException):
    """Explicit ``raise ... from`` chain, cycle-safe — the same walk
    discipline as :func:`is_no_retry` (Go errors.As over Unwrap)."""
    seen = set()
    cur: "BaseException | None" = err
    while cur is not None and id(cur) not in seen:
        yield cur
        seen.add(id(cur))
        cur = cur.__cause__


def is_throttle(err: BaseException) -> bool:
    """True if ``err`` is, or explicitly wraps, an AWS throttle
    response — the rate-limit analogue of :func:`is_no_retry`, walking
    the same ``__cause__`` chain so a throttle wrapped by a retry-layer
    error (resilience.RetryBudgetExceededError) still reads as one."""
    return any(isinstance(cur, AWSAPIError) and cur.is_throttle()
               for cur in _walk_causes(err))


def retry_after_hint(err: BaseException) -> float:
    """Largest ``retry_after`` seconds carried by ``err`` or its
    explicit cause chain; 0.0 when none.  The resilience layer's
    budget/deadline/circuit errors carry this hint so the reconcile
    loop can park the key (``Forget`` + ``AddAfter``) instead of
    hammering the rate limiter (reconcile.py error dispatch)."""
    best = 0.0
    for cur in _walk_causes(err):
        try:
            hint = float(getattr(cur, "retry_after", 0.0) or 0.0)
        except (TypeError, ValueError):
            continue
        best = max(best, hint)
    return best


class ListenerNotFoundError(AWSAPIError):
    def __init__(self, message: str = "listener not found"):
        super().__init__("ListenerNotFoundException", message)


class EndpointGroupNotFoundError(AWSAPIError):
    def __init__(self, message: str = "endpoint group not found"):
        super().__init__("EndpointGroupNotFoundException", message)


# Error-code constant used by the EndpointGroupBinding delete path
# (reference pkg/cloudprovider/aws/global_accelerator.go:28).
ERR_ENDPOINT_GROUP_NOT_FOUND = "EndpointGroupNotFoundException"
