"""Freeze-proxy mode for informer-cache views (the runtime half of L103).

The informer read contract (kube/informers.py): objects returned by
``Lister.get`` / ``Lister.list`` / ``by_index`` are SHARED, READ-ONLY
views of the cache — ``deep_copy()`` before mutating.  A violation
corrupts every other reader silently and only surfaces as impossible
reconcile behavior minutes later; this module makes it fail loudly at
the mutation site, like client-go's cache mutation detector
(``KUBE_CACHE_MUTATION_DETECTOR``).

When enabled (test fixture ``enable()`` or ``AGAC_FREEZE_VIEWS=1``),
listers wrap returned objects in :class:`FrozenView`: reads delegate
(including ``isinstance`` via ``__class__``), ``deep_copy()`` thaws to
a private mutable copy, and ANY in-place mutation — attribute store,
``annotations['k'] = v``, ``finalizers.append(...)`` — raises
:class:`SharedViewMutationError` reporting both the mutation site and
the lister call that produced the view.  Each catch also counts into
the ``shared_view_mutations_blocked`` metric.

The origin is captured as raw frame triples at wrap time (micro-seconds,
not a formatted traceback) so the proxies stay cheap enough to keep on
for the whole e2e/stress/soak tier.
"""
from __future__ import annotations

import os
import sys
import traceback
from typing import Any, List, Tuple

from ..metrics import record_shared_view_mutation_blocked

_enabled = bool(os.environ.get("AGAC_FREEZE_VIEWS"))


class SharedViewMutationError(RuntimeError):
    """In-place mutation of a shared informer-cache view."""


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def view(obj: Any):
    """Wrap one lister-returned object (identity when disabled)."""
    if not _enabled or obj is None:
        return obj
    return FrozenView(obj, _origin())


def view_list(objs: List[Any]) -> List[Any]:
    """Wrap a lister-returned list; the list itself stays a plain
    (caller-owned) list — only the shared elements are frozen."""
    if not _enabled:
        return objs
    origin = _origin()
    return [FrozenView(o, origin) if o is not None else o for o in objs]


def _origin() -> Tuple[Tuple[str, int, str], ...]:
    frames = []
    f = sys._getframe(2)
    while f is not None and len(frames) < 10:
        frames.append((f.f_code.co_filename, f.f_lineno,
                       f.f_code.co_name))
        f = f.f_back
    return tuple(frames)


def _format_origin(origin) -> str:
    return "".join(f"  File \"{fn}\", line {ln}, in {name}\n"
                   for fn, ln, name in origin)


def _blocked(origin, what: str):
    record_shared_view_mutation_blocked()
    raise SharedViewMutationError(
        f"in-place mutation ({what}) of a shared informer-cache view — "
        f"deep_copy() before mutating (kube/informers.py read "
        f"contract)\n"
        f"--- mutation site ---\n"
        f"{''.join(traceback.format_stack(limit=12)[:-2])}"
        f"--- view obtained from lister call ---\n"
        f"{_format_origin(origin)}")


def _wrap_value(value: Any, origin):
    if isinstance(value, FrozenDict) or isinstance(value, FrozenList) \
            or type(value) is FrozenView:
        return value
    if isinstance(value, dict):
        return FrozenDict(value, origin)
    if isinstance(value, list):
        return FrozenList(value, origin)
    if isinstance(value, tuple):
        return tuple(_wrap_value(v, origin) for v in value)
    if hasattr(value, "__dict__") and hasattr(value, "deep_copy") \
            or hasattr(value, "__dataclass_fields__"):
        return FrozenView(value, origin)
    return value


class FrozenView:
    """Read-only proxy over one shared object.

    ``isinstance`` sees the wrapped class (``__class__`` property),
    reads return frozen sub-views, ``deep_copy()``/``to_dict()`` thaw
    to private data, writes raise with both stacks."""

    __slots__ = ("_fv_obj", "_fv_origin")

    def __init__(self, obj: Any, origin):
        object.__setattr__(self, "_fv_obj", obj)
        object.__setattr__(self, "_fv_origin", origin)

    @property  # type: ignore[misc]
    def __class__(self):
        return type(object.__getattribute__(self, "_fv_obj"))

    def __getattr__(self, name: str):
        obj = object.__getattribute__(self, "_fv_obj")
        value = getattr(obj, name)
        if callable(value) and not hasattr(value, "__dataclass_fields__"):
            # bound methods of the real object: deep_copy/to_dict/key
            # return fresh data, so handing them out unwrapped is the
            # thaw path.  (A hypothetical self-mutating method would
            # bypass the proxy; the static L103 pass covers that shape.)
            return value
        return _wrap_value(value,
                           object.__getattribute__(self, "_fv_origin"))

    def __setattr__(self, name: str, value: Any) -> None:
        _blocked(object.__getattribute__(self, "_fv_origin"),
                 f"setattr .{name}")

    def __delattr__(self, name: str) -> None:
        _blocked(object.__getattribute__(self, "_fv_origin"),
                 f"delattr .{name}")

    def __repr__(self) -> str:
        return repr(object.__getattribute__(self, "_fv_obj"))

    def __eq__(self, other: Any) -> bool:
        if type(other) is FrozenView:
            other = object.__getattribute__(other, "_fv_obj")
        return object.__getattribute__(self, "_fv_obj") == other

    def __ne__(self, other: Any) -> bool:
        return not self.__eq__(other)

    def __hash__(self) -> int:
        return hash(object.__getattribute__(self, "_fv_obj"))


def _freeze_mutator(what: str):
    def mutator(self, *args, **kwargs):
        _blocked(self._origin, what)
    return mutator


class FrozenDict(dict):
    """Frozen snapshot of a shared dict: still a ``dict`` for
    isinstance/iteration/lookups, raises on every mutator."""

    def __init__(self, data: dict, origin):
        super().__init__({k: _wrap_value(v, origin)
                          for k, v in data.items()})
        self._origin = origin

    __setitem__ = _freeze_mutator("dict setitem")
    __delitem__ = _freeze_mutator("dict delitem")
    update = _freeze_mutator("dict update")
    pop = _freeze_mutator("dict pop")
    popitem = _freeze_mutator("dict popitem")
    clear = _freeze_mutator("dict clear")
    setdefault = _freeze_mutator("dict setdefault")


class FrozenList(list):
    """Frozen snapshot of a shared list (see FrozenDict)."""

    def __init__(self, data: list, origin):
        super().__init__(_wrap_value(v, origin) for v in data)
        self._origin = origin

    __setitem__ = _freeze_mutator("list setitem")
    __delitem__ = _freeze_mutator("list delitem")
    __iadd__ = _freeze_mutator("list +=")
    __imul__ = _freeze_mutator("list *=")
    append = _freeze_mutator("list append")
    extend = _freeze_mutator("list extend")
    insert = _freeze_mutator("list insert")
    pop = _freeze_mutator("list pop")
    remove = _freeze_mutator("list remove")
    clear = _freeze_mutator("list clear")
    sort = _freeze_mutator("list sort")
    reverse = _freeze_mutator("list reverse")
