"""Test-time lockset tracker (the runtime half of L101).

Production code creates its locks through :func:`make_lock` /
:func:`make_rlock`, which return plain ``threading`` primitives unless
detection is enabled (``enable()`` from the test fixture, or the
``AGAC_RACE_DETECT=1`` env flag at import).  When enabled, every
acquisition is recorded against the thread's currently-held lockset and
an edge ``held -> acquiring`` is added to a process-global lock-order
graph; acquiring in the inverse order of a recorded edge raises
:class:`LockOrderViolation` carrying BOTH acquisition stacks — the
Go ``-race``-style "potential deadlock" report, surfaced on the first
inverted acquisition rather than the eventual deadlock.

Every acquisition also counts one lockset check, published through
``metrics.record_lockset_checks`` in batches (the tracker must never
take the metrics registry lock per acquisition — that lock would join
the graph it is measuring).

The tracker is also the runtime half of the L119 guard map
(``analysis/ownership.py``): :func:`install_guard_checks` patches
``__setattr__`` on classes carrying ``# guarded-by: self.<lock>``
declarations so a post-init write without the declared lock held
raises :class:`GuardMapViolation` and bumps
``guard_map_violations_total``; ``AGAC_GUARD_PROFILE=<path>`` (or
:func:`enable_profile`) additionally records every post-init write
with the held lockset for ``hack/guard_infer.py`` to audit against
the declared map.
"""
from __future__ import annotations

import os
import threading
import traceback

from ..metrics import record_lockset_checks

_enabled = bool(os.environ.get("AGAC_RACE_DETECT"))
_tls = threading.local()

# (outer name, inner name) -> (thread id, formatted stack) of the first
# acquisition that recorded the edge.
_edges: dict = {}
_graph_lock = threading.Lock()

_pending = 0
_FLUSH_EVERY = 1024


class LockOrderViolation(RuntimeError):
    """Two locks were acquired in both orders (a deadlock waiting for
    the right interleaving).  Carries the stacks of both sites."""


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    flush_counters()
    _enabled = False


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Drop the recorded ordering graph (test isolation)."""
    with _graph_lock:
        _edges.clear()


def make_lock(name: str):
    """A named lock: plain ``threading.Lock`` in production, tracked
    when race detection is on (decided at creation time)."""
    return TrackedLock(name) if _enabled else threading.Lock()


def make_rlock(name: str):
    return TrackedLock(name, reentrant=True) if _enabled \
        else threading.RLock()


def flush_counters(registry=None) -> None:
    """Publish any batched lockset-check counts to ``registry`` (the
    default metrics registry when None)."""
    global _pending
    n, _pending = _pending, 0
    if n:
        record_lockset_checks(n, registry=registry)


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _stack() -> str:
    return "".join(traceback.format_stack(limit=16)[:-2])


class TrackedLock:
    """Lock wrapper recording per-thread acquisition order.

    Also usable as the lock of a ``threading.Condition``: the
    condition's wait() releases and re-acquires through ``release`` /
    ``acquire``, so the held-set bookkeeping stays correct while a
    worker is parked."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._note_acquired()
            except BaseException:
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._inner.release()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def _note_acquired(self) -> None:
        global _pending
        held = _held()
        _pending += 1
        if _pending >= _FLUSH_EVERY:
            flush_counters()
        if self._reentrant and any(h is self for h in held):
            held.append(self)   # re-entry: no new ordering information
            return
        tid = threading.get_ident()
        for h in held:
            if h is self or h.name == self.name:
                continue
            key = (h.name, self.name)
            with _graph_lock:
                if key not in _edges:
                    inverse = _edges.get((self.name, h.name))
                    if inverse is not None:
                        # acquire() releases the inner lock on raise and
                        # the entry was never appended, so the held set
                        # stays consistent
                        other_tid, other_stack = inverse
                        raise LockOrderViolation(
                            f"lock ordering inversion: thread {tid} "
                            f"acquired '{self.name}' while holding "
                            f"'{h.name}', but thread {other_tid} "
                            f"acquired '{h.name}' while holding "
                            f"'{self.name}'\n"
                            f"--- this acquisition ---\n{_stack()}"
                            f"--- prior inverse acquisition ---\n"
                            f"{other_stack}")
                    _edges[key] = (tid, _stack())
        held.append(self)

    # Condition-lock protocol: threading.Condition prefers these over
    # its acquire/release fallbacks when present.
    def _is_owned(self) -> bool:
        if self._reentrant:
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


# -- field-level guard map (the runtime half of L119) -------------------
#
# install_guard_checks() patches ``__setattr__`` on every imported class
# carrying ``# guarded-by: self.<lock>`` declarations (parsed by
# analysis/ownership.py).  Post-__init__ writes to a declared attribute
# are then cross-checked against the thread's live lockset: a write
# with the owning lock NOT held raises :class:`GuardMapViolation` and
# bumps ``guard_map_violations_total`` — the dynamic witness for the
# interleavings the lexical pass cannot see (getattr chains, exec'd
# code, callbacks).  With ``AGAC_GUARD_PROFILE=<path>`` the same hook
# instead RECORDS (class, attr, locks-held) profiles; hack/guard_infer.py
# renders the dump as reviewable ``# guarded-by:`` proposals for
# not-yet-declared fields.

_profile_path = os.environ.get("AGAC_GUARD_PROFILE")
# (classname, attr) -> {held-names tuple -> count}
_profiles: dict = {}
_profile_lock = threading.Lock()
_patched: set = set()


class GuardMapViolation(RuntimeError):
    """A declared-guarded attribute was written without its owning
    lock held — the runtime cross-check of the static guard map."""


def profile_enabled() -> bool:
    return _profile_path is not None


def enable_profile(path: str) -> None:
    """Arm guard-profile recording (normally via AGAC_GUARD_PROFILE)."""
    global _profile_path
    _profile_path = path


def _describe_held(obj) -> tuple:
    """The thread's held locks as declaration-ready names:
    ``self.<attr>`` when a held lock is an attribute of ``obj``
    (directly or as a Condition's underlying lock), else the lock's
    registered name in angle brackets."""
    names = []
    for h in _held():
        label = None
        try:
            attrs = vars(obj)
        except TypeError:          # __slots__
            attrs = {}
        for k, v in attrs.items():
            if v is h or getattr(v, "_lock", None) is h:
                label = "self." + k
                break
        names.append(label or f"<{h.name}>")
    return tuple(sorted(set(names)))


def _resolve_lock(obj, chain):
    """``['self', '_cond']`` -> the lock object a held-set identity
    check can use (Conditions are unwrapped to their inner lock)."""
    target = obj
    for part in chain[1:]:
        target = getattr(target, part, None)
        if target is None:
            return None
    return getattr(target, "_lock", target)


def _patch_class(cls, lock_decls: dict) -> None:
    orig = cls.__setattr__

    def checked_setattr(self, attr, value):
        # first writes are __init__ construction: the guard itself may
        # not exist yet, and the creating thread owns the instance
        if _enabled:
            try:
                seen = attr in object.__getattribute__(self, "__dict__")
            except AttributeError:
                seen = False
            if seen:
                # profile EVERY post-init write (inference proposes
                # declarations for fields that lack one); cross-check
                # only the declared ones.  Requires detection armed:
                # with plain locks the held set is always empty and
                # the profile would read as all-unguarded
                if _profile_path is not None:
                    key = (cls.__name__, attr)
                    if attr in lock_decls and not isinstance(
                            _resolve_lock(self, lock_decls[attr]),
                            TrackedLock):
                        # the declared lock is a plain primitive
                        # (e.g. the virtual clock's own lock — the
                        # substrate tracked locks park in): its
                        # acquisitions are invisible, so record that
                        # rather than a misleading empty lockset
                        desc = ("<untracked>",)
                    else:
                        desc = _describe_held(self)
                    with _profile_lock:
                        counts = _profiles.setdefault(key, {})
                        counts[desc] = counts.get(desc, 0) + 1
            if seen and attr in lock_decls:
                if _enabled:
                    lock = _resolve_lock(self, lock_decls[attr])
                    # only TrackedLock instances can be cross-checked:
                    # a plain lock means the object predates arming
                    # (make_lock decides at creation time) and its
                    # acquisitions are invisible to the held set
                    if isinstance(lock, TrackedLock) and \
                            not any(h is lock for h in _held()):
                        from ..metrics import record_guard_map_violation
                        record_guard_map_violation(cls.__name__, attr)
                        raise GuardMapViolation(
                            f"write to {cls.__name__}.{attr} without "
                            f"its declared guard "
                            f"'{'.'.join(lock_decls[attr])}' held "
                            f"(held: {_describe_held(self) or '()'})\n"
                            f"{_stack()}")
        orig(self, attr, value)

    cls.__setattr__ = checked_setattr


def install_guard_checks(root=None) -> int:
    """Patch every currently-imported class that carries static
    ``# guarded-by: self.<lock>`` declarations.  Idempotent; returns
    the number of classes newly patched.  Patching is process-global,
    but the hook is a passthrough unless detection or profiling is
    armed, so suites that never opt in pay one dict lookup per
    setattr on the handful of declared classes."""
    import sys
    from pathlib import Path
    from .ownership import declared_runtime_guards

    pkg_root = Path(root) if root is not None \
        else Path(__file__).resolve().parents[1]
    guards = declared_runtime_guards(pkg_root)
    pkg = pkg_root.name
    count = 0
    for modname, mod in list(sys.modules.items()):
        if mod is None or not modname.startswith(pkg):
            continue
        for classname, decls in guards.items():
            cls = getattr(mod, classname, None)
            if not isinstance(cls, type) or cls.__name__ != classname \
                    or cls in _patched:
                continue
            lock_decls = {a: d.chain for a, d in decls.items()
                          if d.kind == "lock" and d.chain}
            if not lock_decls:
                continue
            _patch_class(cls, lock_decls)
            _patched.add(cls)
            count += 1
    return count


def dump_guard_profile(path=None) -> str:
    """Write recorded access profiles as JSON for hack/guard_infer.py.
    Schema: {"ClassName.attr": {"held": {"self._lock|...": n}}}."""
    import json

    out_path = path or _profile_path
    if out_path is None:
        raise RuntimeError("no profile path: set AGAC_GUARD_PROFILE "
                           "or pass path=")
    with _profile_lock:
        doc = {
            f"{cls}.{attr}": {
                "held": {"|".join(held) if held else "<none>": n
                         for held, n in counts.items()},
            }
            for (cls, attr), counts in sorted(_profiles.items())
        }
    with open(out_path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return out_path
