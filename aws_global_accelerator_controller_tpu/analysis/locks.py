"""Test-time lockset tracker (the runtime half of L101).

Production code creates its locks through :func:`make_lock` /
:func:`make_rlock`, which return plain ``threading`` primitives unless
detection is enabled (``enable()`` from the test fixture, or the
``AGAC_RACE_DETECT=1`` env flag at import).  When enabled, every
acquisition is recorded against the thread's currently-held lockset and
an edge ``held -> acquiring`` is added to a process-global lock-order
graph; acquiring in the inverse order of a recorded edge raises
:class:`LockOrderViolation` carrying BOTH acquisition stacks — the
Go ``-race``-style "potential deadlock" report, surfaced on the first
inverted acquisition rather than the eventual deadlock.

Every acquisition also counts one lockset check, published through
``metrics.record_lockset_checks`` in batches (the tracker must never
take the metrics registry lock per acquisition — that lock would join
the graph it is measuring).
"""
from __future__ import annotations

import os
import threading
import traceback

from ..metrics import record_lockset_checks

_enabled = bool(os.environ.get("AGAC_RACE_DETECT"))
_tls = threading.local()

# (outer name, inner name) -> (thread id, formatted stack) of the first
# acquisition that recorded the edge.
_edges: dict = {}
_graph_lock = threading.Lock()

_pending = 0
_FLUSH_EVERY = 1024


class LockOrderViolation(RuntimeError):
    """Two locks were acquired in both orders (a deadlock waiting for
    the right interleaving).  Carries the stacks of both sites."""


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    flush_counters()
    _enabled = False


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Drop the recorded ordering graph (test isolation)."""
    with _graph_lock:
        _edges.clear()


def make_lock(name: str):
    """A named lock: plain ``threading.Lock`` in production, tracked
    when race detection is on (decided at creation time)."""
    return TrackedLock(name) if _enabled else threading.Lock()


def make_rlock(name: str):
    return TrackedLock(name, reentrant=True) if _enabled \
        else threading.RLock()


def flush_counters(registry=None) -> None:
    """Publish any batched lockset-check counts to ``registry`` (the
    default metrics registry when None)."""
    global _pending
    n, _pending = _pending, 0
    if n:
        record_lockset_checks(n, registry=registry)


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _stack() -> str:
    return "".join(traceback.format_stack(limit=16)[:-2])


class TrackedLock:
    """Lock wrapper recording per-thread acquisition order.

    Also usable as the lock of a ``threading.Condition``: the
    condition's wait() releases and re-acquires through ``release`` /
    ``acquire``, so the held-set bookkeeping stays correct while a
    worker is parked."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._note_acquired()
            except BaseException:
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._inner.release()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def _note_acquired(self) -> None:
        global _pending
        held = _held()
        _pending += 1
        if _pending >= _FLUSH_EVERY:
            flush_counters()
        if self._reentrant and any(h is self for h in held):
            held.append(self)   # re-entry: no new ordering information
            return
        tid = threading.get_ident()
        for h in held:
            if h is self or h.name == self.name:
                continue
            key = (h.name, self.name)
            with _graph_lock:
                if key not in _edges:
                    inverse = _edges.get((self.name, h.name))
                    if inverse is not None:
                        # acquire() releases the inner lock on raise and
                        # the entry was never appended, so the held set
                        # stays consistent
                        other_tid, other_stack = inverse
                        raise LockOrderViolation(
                            f"lock ordering inversion: thread {tid} "
                            f"acquired '{self.name}' while holding "
                            f"'{h.name}', but thread {other_tid} "
                            f"acquired '{h.name}' while holding "
                            f"'{self.name}'\n"
                            f"--- this acquisition ---\n{_stack()}"
                            f"--- prior inverse acquisition ---\n"
                            f"{other_stack}")
                    _edges[key] = (tid, _stack())
        held.append(self)

    # Condition-lock protocol: threading.Condition prefers these over
    # its acquire/release fallbacks when present.
    def _is_owned(self) -> bool:
        if self._reentrant:
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True
