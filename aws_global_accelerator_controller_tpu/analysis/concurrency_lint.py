"""AST-based concurrency contract lints (rules L101-L120).

The static half of the concurrency checker: a whole-program pass over
the tree that enforces the synchronization contracts PR 1 introduced as
conventions.  Pure stdlib ``ast`` — run by ``hack/lint.py
--concurrency`` inside the existing lint gate.

Rules (each over-approximates "safe", matching the base linter's
zero-findings gate philosophy):

  L101 lock ordering     Build the lock graph from every ``with <lock>``
                         nesting (plus one level of same-class method
                         calls); flag re-acquisition of a non-reentrant
                         lock and global A->B vs B->A ordering
                         inversions.
  L102 blocking under lock
                         ``time.sleep``, ``subprocess``/``socket``/
                         HTTP calls, provider API calls (``*.apis.*``),
                         ``Thread.join`` and foreign ``.wait()`` made
                         while a ``with <lock>`` block is open (waiting
                         on the held condition itself is the legal
                         cv pattern and exempt).
  L103 shared-view mutation
                         In-place mutation of an object obtained from a
                         lister ``get``/``list``, ``by_index``,
                         ``cache_get``/``cache_list`` call without an
                         intervening ``deep_copy()`` in the same
                         function (the informer read contract,
                         kube/informers.py).
  L104 cache discipline  (a) calls to ``*_locked`` methods outside a
                         ``with <lock>`` block; (b) writes to the
                         fleet-discovery state (``_s.fleet_index``,
                         ``_s.discovery``, ``_s.gen``, ...) outside a
                         lock; (c) gen-keyed singleflight reads
                         (``*.reads.do``) whose key tuple carries no
                         generation component.
  L105 resilient calls   Direct AWS service method calls
                         (``<x>.ga.describe_accelerator(...)``, any
                         method of the three API interfaces) whose
                         receiver chain does not go through ``apis`` —
                         the factory's ResilientAPIs injection point —
                         bypass the retry/breaker/deadline policy
                         (resilience/wrapper.py).  Package files only:
                         tests and tools observe the fake cloud
                         directly by design.
  L106 coalesced writes  Direct calls to the batched mutation surface
                         (``<x>.route53.change_resource_record_sets``
                         / ``..._batch``, ``<x>.ga.
                         update_endpoint_group``) — even through
                         ``apis`` — bypass the write coalescer
                         (cloudprovider/aws/batcher.py): no folding,
                         no bisect-on-rejection, no per-waiter error
                         demux.  Package-scoped like L105;
                         ``batcher.py`` itself (the one legitimate
                         flush issuer) is exempt.
  L107 provider-free fast path
                         Code on the fingerprint fast path — the
                         ``reconcile`` package's dispatch/skip branch
                         and every fingerprint builder (any function
                         whose name contains ``fingerprint``) — must
                         not reach the provider: no call through
                         ``apis`` and no AWS service method at all.
                         The steady-state contract is that a
                         fingerprint answer costs ZERO provider calls
                         (reconcile/fingerprint.py); a builder that
                         consults AWS would silently turn the skip
                         path back into the O(N)-per-resync cost it
                         exists to remove.  Package-scoped like L105.
  L109 class-tagged enqueues
                         Workqueue enqueues from the controller /
                         reconcile packages (``<x>queue.add`` /
                         ``add_rate_limited`` / ``add_after``) must
                         pass an explicit ``klass=`` — a raw enqueue
                         silently defaults the key's traffic class,
                         so an interactive change could ride the
                         background tier (or a resync wave the
                         interactive one) and the overload scheduler's
                         latency/shed contract breaks
                         (kube/workqueue.py tiers).  Package-scoped
                         to controller/ and reconcile/ like L105.
  L111 compat-shimmed accelerator symbols
                         Accelerator code (every shipped package
                         except ``compat/`` itself) must not touch
                         the version-sensitive ``pltpu.*`` /
                         ``orbax.*`` surfaces directly — no import of
                         ``jax.experimental.pallas.tpu`` or
                         ``orbax``, no attribute access rooted at
                         ``pltpu``/``orbax``.  Those symbols drift
                         between releases (``CompilerParams`` vs
                         ``TPUCompilerParams``, handler names) and a
                         direct consumer fails as an opaque
                         AttributeError at trace time; the compat
                         shim (compat/jaxshim.py, compat/orbaxshim.py)
                         resolves each symbol once with recorded
                         provenance and degrades with evidence.
  L112 rollout-gated weight mutations
                         Endpoint-weight mutations
                         (``update_endpoint_weights`` /
                         ``update_endpoint_weight``) outside the
                         ``rollout/`` package must consult the rollout
                         gate lexically in the enclosing function
                         (``self.rollout.decide(...)``, a helper whose
                         name contains ``rollout``): an unconsulted
                         weight write can SNAP a mid-ramp object to
                         its final target, destroying the monotone
                         blue-green ramp the durable state machine
                         guarantees (rollout/machine.py).  The two
                         weight-bearing controllers' shipped consults
                         are verified whenever their files are linted
                         (the seeded probe strips one and asserts the
                         rule fires).  Package-scoped like L105.
  L113 columnar planner purity
                         The whole-fleet planner modules
                         (``parallel/fleet_plan.py``,
                         ``reconcile/columnar.py``) must stay pure
                         over packed arrays: (a) no call through
                         ``apis`` anywhere in either module — packing
                         is host-side preparation over informer/
                         describe state the CALLER collected, the
                         planner itself never reaches the provider;
                         (b) no Python ``for``/``while`` in a device
                         program (any function named ``_device_*`` or
                         decorated with ``jit``/``shard_map``) — a
                         per-object Python loop over fleet keys inside
                         the jit path silently reverts the planner to
                         the object-at-a-time cost the columnar pass
                         exists to delete (it also recompiles per
                         fleet size).  Host-side pack/decode loops are
                         legal; ring-hop unrolls live in undecorated
                         helpers by convention.
  L108 fenced mutations  Mutation-issuing paths must consult the
                         lifecycle fence (resilience/fence.py): no
                         AWS WRITE method may be reachable after
                         stop/lease-loss without a fence check.  A
                         write issued through ``apis`` is gated at
                         runtime by ``ResilientAPIs.invoke`` — so the
                         rule (a) requires any BARE service write to
                         consult the fence lexically in its enclosing
                         function, and (b) requires ``wrapper.py``'s
                         ``invoke`` itself to carry the fence consult
                         whenever that file is in the linted set (the
                         seeded-mutation probe strips it and asserts
                         the gate fires).  Package-scoped like L105.

Waivers: ``# race: <reason>`` on the flagged line (the explicit,
greppable spelling — use for contracts that are upheld non-lexically),
or ``# noqa: L10x``.  Lock-ordering findings check both edge sites.

A lock expression is any ``with`` context manager whose final name
segment looks lock-ish (``lock``/``_lock``/``*_lock``/``cond``/
``mutex``).  Identity is class-qualified for ``self.X`` (two classes'
``self._lock`` never alias) and suffix-chained for shared-state locks
(``self._s.lock`` is the same ``_s.lock`` node from any class).

  L116 topology-routed cross-region mutations (ISSUE 14)
                         The cross-region wire surface
                         (``apply_region_batch`` — the regional
                         aggregation point, api.RegionGatewayAPI) is
                         issued ONLY by the per-region aggregators in
                         ``topology/``: a direct call anywhere else
                         re-creates flat fan-in with none of the
                         aggregator's contracts (per-contribution
                         fence checks, per-entry error demux, region
                         batch accounting).  The
                         ShardedCoalescer→aggregator handoff itself
                         (batcher.py ``_wire_record_sets`` /
                         ``_wire_endpoint_group`` consulting the
                         aggregator) is re-verified whenever
                         batcher.py is in the linted set — the
                         seeded probe strips the shipped consult and
                         asserts the rule fires.  Package-scoped like
                         L105; ``topology/`` is the one exempt home.
  L115 wall-clock leaks (ISSUE 13)
                         The clock-owned packages (kube/, resilience/,
                         cloudprovider/, leaderelection/, reconcile/,
                         rollout/, controller/, manager/, sharding/,
                         tracing.py, flight.py, metrics.py) read time
                         ONLY through simulation/clock.py: a direct
                         ``time.monotonic()`` / ``time.time()`` /
                         ``time.sleep()``, a raw ``threading.Event()``
                         / ``threading.Condition()`` construction, or
                         a ``.wait(<numeric literal>)`` silently
                         breaks virtual-time determinism — under a
                         VirtualClock the leaked wait parks in the OS
                         where the scheduler cannot see it (a stalled
                         sim) or burns real seconds the simulation
                         thought were free.  The real-I/O shims
                         (http_store/rest_server/kubeconfig/tlsutil/
                         real.py) are the waiver-listed boundary;
                         ``# race: <reason>`` waives a deliberate
                         wall-clock read.
  L117 registry-owned knobs (ISSUE 15)
                         Scheduling constants the TunableRegistry
                         owns (autotune/knobs.py catalog: coalescer
                         linger/warm_gap, sweep_every, the queue
                         watermarks and aging horizon,
                         breaker_window, digest exchange_every) must
                         not be re-hardcoded as numeric literals in
                         the clock-owned packages — a fresh literal
                         forks "the default" away from the one the
                         feedback controllers' snap-to-default freeze
                         restores.  Flags keyword arguments,
                         signature defaults and assignments whose
                         target name is (or suffixes as) a catalog
                         parameter name with a numeric literal value;
                         the ``autotune/`` package (the owner) is
                         exempt; ``# race: <reason>`` waives a
                         deliberate divergence (test profiles).
  L118 steady-state full-repack ban (ISSUE 16)
                         The full-repack entry points (``pack_fleet``,
                         ``WholeFleetPlanner.plan_groups``) are the
                         ORACLE: on the steady-state wave path — the
                         sweep controller (controller/fleetsweep.py)
                         and the plan/flush pipeline
                         (parallel/overlap.py) — every wave replans
                         only dirty shards through the resident
                         planner (``ResidentFleetPlanner.plan_wave``),
                         so a full repack creeping back in silently
                         reverts milliseconds-per-wave to O(fleet)
                         per wave at million-EG scale.  Flags any
                         ``pack_fleet`` / ``plan_groups`` call in
                         those modules whose enclosing function is
                         not an oracle/verification entry point (name
                         contains ``oracle``/``verify``/
                         ``full_repack``); ``# race: <reason>``
                         waives a deliberate repack.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_LOCKISH = re.compile(r"(?:^|_)(lock|cond|mutex|rlock)$")
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

# Fields of cloudprovider.aws.provider.FleetDiscoveryState whose every
# read-modify-write must happen under the discovery lock (rule L104b).
FLEET_FIELDS = {"fleet_index", "fleet_at", "fleet_epoch", "discovery",
                "tags", "prime_log", "gen", "scans_inflight"}

_MUTATING_METHODS = {"append", "extend", "insert", "remove", "pop",
                     "popitem", "clear", "update", "setdefault", "sort",
                     "reverse", "add", "discard"}

# Calls that park the thread (or hit the network) — forbidden while any
# lock is held (rule L102).
_BLOCKING_ROOTS = {"subprocess", "socket", "requests"}

# Informer read API: objects returned by these are shared views (L103).
_VIEW_METHODS = {"by_index", "cache_get", "cache_list"}
_LISTER_METHODS = {"get", "list"}

# The AWS API call surface (the abstract methods of
# cloudprovider.aws.api's three interfaces) and the attribute names the
# bundle exposes them under — rule L105 flags reaching one without
# going through ``apis`` (the ResilientAPIs injection point).
_AWS_SERVICES = {"ga", "elb", "route53"}
_AWS_API_METHODS = {
    # GlobalAcceleratorAPI
    "list_accelerators", "describe_accelerator",
    "list_tags_for_resource", "create_accelerator",
    "update_accelerator", "tag_resource", "delete_accelerator",
    "list_listeners", "create_listener", "update_listener",
    "delete_listener", "list_endpoint_groups",
    "describe_endpoint_group", "create_endpoint_group",
    "update_endpoint_group", "add_endpoints", "remove_endpoints",
    "delete_endpoint_group",
    # ELBv2API
    "describe_load_balancers",
    # Route53API
    "list_hosted_zones", "list_hosted_zones_by_name",
    "list_resource_record_sets", "change_resource_record_sets",
    "change_resource_record_sets_batch",
}

# The write-coalescing surface: the MutationCoalescer
# (cloudprovider/aws/batcher.py) is the ONLY legitimate issuer of
# these mutations — a direct call, even through ``apis``, bypasses
# folding, flush-level bisect and per-waiter error demultiplexing
# (rule L106).
_COALESCED_WRITES = {
    ("route53", "change_resource_record_sets"),
    ("route53", "change_resource_record_sets_batch"),
    ("ga", "update_endpoint_group"),
}

# Every AWS WRITE method (mutates cloud state) — the surface rule L108
# requires a lifecycle-fence consult for.  Imported from the runtime
# gate's own set so the lint can never silently drift from the surface
# it polices (a write method fenced at the wrapper is exactly a write
# method L108 checks).
from ..resilience.wrapper import MUTATION_METHODS as _AWS_WRITE_METHODS


def _consults_fence(fn: ast.AST) -> bool:
    """Does this function lexically consult the lifecycle fence?  A
    call whose receiver chain names a ``*fence*`` attribute and ends
    in ``check``/``flush_pass`` (``self._fence.check(...)``,
    ``fence.check(op)``, ``with self._fence.flush_pass():``), or a
    helper whose own name contains ``fence`` (``check_fence()``)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain is None:
            continue
        if chain[-1] in ("check", "flush_pass") \
                and any("fence" in seg for seg in chain[:-1]):
            return True
        if "fence" in chain[-1]:
            return True
    return False


def _consults_rollout(fn: ast.AST) -> bool:
    """Does this function lexically consult the rollout gate?  A call
    whose receiver chain names a ``*rollout*`` attribute and ends in
    ``decide``/``active`` (``self.rollout.decide(...)``), or a helper
    whose own name contains ``rollout`` (``_record_rollout()``,
    ``rollout_active(...)``)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain is None:
            continue
        if chain[-1] in ("decide", "active") \
                and any("rollout" in seg for seg in chain[:-1]):
            return True
        if "rollout" in chain[-1]:
            return True
    return False


def _consults_shard(fn: ast.AST) -> bool:
    """Does this function lexically consult the shard-ownership
    assertion?  A call whose receiver chain names a ``*shard*``
    attribute and ends in ``check``/``owns_key``/``guard``
    (``self._shards.check(key)``, ``shards.owns_key(k)``,
    ``with self.shards.guard(route):``), or a helper whose own name
    contains ``shard`` (``check_shard()``)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain is None:
            continue
        if chain[-1] in ("check", "owns_key", "guard") \
                and any("shard" in seg for seg in chain[:-1]):
            return True
        if "shard" in chain[-1]:
            return True
    return False


def _consults_trace(fn: ast.AST) -> bool:
    """Does this function capture the ambient trace context (the
    coalescer submit's L114 runtime gate)?"""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain and chain[-1] == "ambient_context":
                return True
    return False


def _l105_in_scope(path: Path) -> bool:
    """L105 covers the shipped package (where every AWS call must ride
    the resilient wrapper) and the lint fixtures (the rule's own test
    corpus); tests/tools observing the fake cloud directly are the
    supported escape hatch, not a violation."""
    parts = path.parts
    return ("aws_global_accelerator_controller_tpu" in parts
            or "lint_fixtures" in parts)


def _l109_in_scope(path: Path) -> bool:
    """L109 polices the packages that enqueue reconcile keys — the
    controller and reconcile packages — plus the fixture corpus.
    Everything else (the queue implementation itself, tests driving
    queues directly, tools) enqueues on its own terms."""
    parts = path.parts
    if "lint_fixtures" in parts:
        return True
    return ("aws_global_accelerator_controller_tpu" in parts
            and ("controller" in parts or "reconcile" in parts))


# The cross-region wire surface rule L116 confines to topology/ (the
# per-region aggregators, the one legitimate issuer).
_CROSS_REGION_METHODS = {"apply_region_batch"}


def _l116_in_scope(path: Path) -> bool:
    """L116 covers every shipped package file EXCEPT the topology
    package itself, plus the fixture corpus.  Tests and tools may
    drive the gateway directly — observing the fake region model is
    their job."""
    parts = path.parts
    if "lint_fixtures" in parts:
        return True
    if "aws_global_accelerator_controller_tpu" not in parts:
        return False
    pkg_idx = parts.index("aws_global_accelerator_controller_tpu")
    return not (len(parts) > pkg_idx + 1
                and parts[pkg_idx + 1] == "topology")


def _consults_aggregator(fn: ast.AST) -> bool:
    """Does this function lexically consult the region aggregator (the
    ShardedCoalescer→aggregator handoff, L116)?  A call whose
    receiver chain names an ``*aggregator*`` attribute
    (``self._aggregator.submit_record_sets(...)``), or a helper whose
    own name contains ``aggregator``."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain is None:
            continue
        if any("aggregator" in seg for seg in chain[:-1]):
            return True
        if "aggregator" in chain[-1]:
            return True
    return False


# Rule L115's scope: the packages whose every timing surface the
# virtual clock owns (simulation/clock.py).  The real-I/O shims inside
# them are the simulation boundary and stay on the wall clock.
_L115_DIRS = {"kube", "resilience", "cloudprovider", "leaderelection",
              "reconcile", "rollout", "controller", "manager",
              "sharding", "topology", "autotune"}
_L115_FILES = {"tracing.py", "flight.py", "metrics.py"}
_L115_EXEMPT_FILES = {"http_store.py", "rest_server.py",
                      "kubeconfig.py", "tlsutil.py", "real.py"}


def _l117_in_scope(path: Path) -> bool:
    """L117 covers the same clock-owned packages as L115 — the knob
    CONSUMERS — while the autotune package itself (the catalog that
    OWNS the numeric spellings, and the registry that moves them) is
    exempt: re-hardcoding is only meaningful outside the owner."""
    parts = path.parts
    if "lint_fixtures" in parts:
        return path.name.startswith("l117_")
    if "aws_global_accelerator_controller_tpu" in parts:
        i = parts.index("aws_global_accelerator_controller_tpu")
        rel = parts[i + 1:]
        if rel and rel[0] == "autotune":
            return False
    return _l115_in_scope(path)


def _l115_in_scope(path: Path) -> bool:
    """L115 covers the clock-owned packages (plus the fixture corpus);
    the waiver-listed real-I/O shims and everything outside the listed
    packages (cmd/, webhook/, compat/, accelerator code, tools, tests)
    keep their own relationship with real time."""
    parts = path.parts
    if "lint_fixtures" in parts:
        # only the rule's own corpus: the other rules' fixtures use
        # time.sleep/raw events deliberately (the L102 shapes)
        return path.name.startswith("l115_")
    if "aws_global_accelerator_controller_tpu" not in parts:
        return False
    if path.name in _L115_EXEMPT_FILES:
        return False
    i = parts.index("aws_global_accelerator_controller_tpu")
    rel = parts[i + 1:]
    if len(rel) == 1:
        return rel[0] in _L115_FILES
    return rel[0] in _L115_DIRS


# The enqueue surface rule L109 requires a ``klass=`` keyword on, when
# the receiver chain names a queue.  Rule L114 requires a ``ctx=`` on
# the same surface: a workqueue item constructed without its
# TraceContext severs the event→converged trace at the hand-off
# (tracing.py; an explicit ``ctx=None`` is the supported spelling for
# a genuinely untraced path — the explicitness is the contract).
_ENQUEUE_METHODS = {"add", "add_rate_limited", "add_after"}


# The endpoint-weight mutation surface rule L112 requires a rollout
# gate consult around: a direct call to either snaps weights, which is
# exactly what a mid-ramp object must never experience.
_WEIGHT_MUTATIONS = {"update_endpoint_weights", "update_endpoint_weight"}


def _l112_in_scope(path: Path) -> bool:
    """L112 covers every shipped package file EXCEPT the rollout
    package itself (the gate's one legitimate home — its machine
    plans the very weights everyone else must gate on), plus the
    fixture corpus."""
    parts = path.parts
    if "lint_fixtures" in parts:
        return True
    if "aws_global_accelerator_controller_tpu" not in parts:
        return False
    pkg_idx = parts.index("aws_global_accelerator_controller_tpu")
    return not (len(parts) > pkg_idx + 1
                and parts[pkg_idx + 1] == "rollout")


def _l111_in_scope(path: Path) -> bool:
    """L111 covers every shipped package file EXCEPT the compat shim
    itself (the one legitimate home of raw ``pltpu.*``/``orbax.*``
    access), plus the fixture corpus.  Tests and tools may poke the
    raw modules — probing drift is their job."""
    parts = path.parts
    if "lint_fixtures" in parts:
        return True
    if "aws_global_accelerator_controller_tpu" not in parts:
        return False
    # only the TOP-LEVEL compat/ package is exempt — a nested dir that
    # happens to be named "compat" (vendored code, a future
    # kube/compat/) gets no free pass at raw accelerator symbols
    pkg_idx = parts.index("aws_global_accelerator_controller_tpu")
    return not (len(parts) > pkg_idx + 1 and parts[pkg_idx + 1] == "compat")


# module prefixes whose direct import rule L111 flags outside compat/
_L111_MODULES = ("jax.experimental.pallas.tpu", "orbax")
# attribute-chain roots that reach the version-sensitive surface even
# without a visible import (the seeded-graft shape)
_L111_ROOTS = {"pltpu", "orbax"}
# ...and the submodule-through-the-alias shape: `pl.tpu.X` /
# `pallas.tpu.X` reaches the same drifting surface through the pallas
# alias every kernel file already imports (the tpu submodule binds
# onto the package as soon as ANYTHING — e.g. the shim — imports it)
_L111_ALIAS_ROOTS = {"pl", "pallas"}


def _l111_chain(chain: List[str]) -> bool:
    if len(chain) > 1 and chain[0] in _L111_ROOTS:
        return True
    return (len(chain) > 2 and chain[0] in _L111_ALIAS_ROOTS
            and chain[1] == "tpu")


def _l111_module(name: str) -> bool:
    return any(name == m or name.startswith(m + ".")
               for m in _L111_MODULES)


def _l113_in_scope(path: Path) -> bool:
    """L113 covers the two columnar planner modules (the fleet pass
    and its packing layer) plus the fixture corpus (``l113_*.py``)."""
    if path.name.startswith("l113_"):
        return True
    parts = path.parts
    if "aws_global_accelerator_controller_tpu" not in parts:
        return False
    return (path.name == "fleet_plan.py" and "parallel" in parts) \
        or (path.name == "columnar.py" and "reconcile" in parts)


def _l113_device_fn(fn: ast.AST) -> bool:
    """Is this function a device program?  By the planner's naming
    convention (``_device_*``) or by carrying a ``jit``/``shard_map``
    decoration (bare, attribute-qualified, or through
    ``partial(...)``)."""
    if fn.name.startswith("_device_"):
        return True
    for dec in fn.decorator_list:
        for node in ast.walk(dec):
            if isinstance(node, ast.Name) \
                    and node.id in ("jit", "shard_map"):
                return True
            if isinstance(node, ast.Attribute) \
                    and node.attr in ("jit", "shard_map"):
                return True
    return False


# The full-repack entry points (rule L118): legal on the steady-state
# wave path only inside oracle / verification functions.
_L118_REPACK_CALLS = {"pack_fleet", "plan_groups"}
_L118_ORACLE_TAGS = ("oracle", "verify", "full_repack")


def _l118_in_scope(path: Path) -> bool:
    """L118 covers the steady-state wave path — the sweep controller
    and the plan/flush pipeline — plus the fixture corpus
    (``l118_*.py``)."""
    if path.name.startswith("l118_"):
        return True
    parts = path.parts
    if "aws_global_accelerator_controller_tpu" not in parts:
        return False
    return (path.name == "fleetsweep.py" and "controller" in parts) \
        or (path.name == "overlap.py" and "parallel" in parts)


def _l107_fastpath(path: Path, fn_name: str) -> bool:
    """Is this function on the fingerprint fast path (rule L107)?
    The reconcile package's own modules (the dispatch + the
    fingerprint cache) and every fingerprint builder — by the naming
    convention the controllers follow: the builder's name contains
    ``fingerprint``."""
    if "fingerprint" in fn_name:
        return True
    parts = path.parts
    return ("reconcile" in parts
            and ("aws_global_accelerator_controller_tpu" in parts
                 or "lint_fixtures" in parts))


class Finding:
    def __init__(self, path, line: int, code: str, msg: str):
        self.path, self.line, self.code, self.msg = path, line, code, msg

    def __str__(self):
        return f"{self.path}:{self.line}: {self.code} {self.msg}"


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``self._s.reads.do`` -> ['self', '_s', 'reads', 'do']."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    """Base Name of an attribute/subscript chain (``svc.meta.x`` -> svc)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _FileInfo:
    def __init__(self, path: Path, tree: ast.Module, source: str):
        self.path = path
        self.tree = tree
        self.module = path.stem
        # raw source lines: the ownership pass (L119/L120) reads the
        # guard-declaration comments the AST drops
        self.lines = source.splitlines()
        self.waived = _waived_lines(source)
        # (class or None, method name) -> set of lock ids the body
        # acquires via ``with`` — the one-level call expansion for L101.
        self.fn_acquires: Dict[Tuple[Optional[str], str], Set[str]] = {}


def _waived_lines(source: str) -> Dict[int, Set[str]]:
    """line -> waived codes; '' means every concurrency rule (the
    ``# race: reason`` spelling), specific codes via ``# noqa: L10x``."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), 1):
        if re.search(r"#\s*race:\s*\S", line):
            out.setdefault(i, set()).add("")
        m = re.search(r"#\s*noqa(?::\s*([A-Z0-9, ]+))?", line)
        if m:
            codes = m.group(1)
            out.setdefault(i, set()).update(
                {c.strip() for c in codes.split(",")} if codes else {""})
    return out


def _is_waived(info: _FileInfo, line: int, code: str) -> bool:
    codes = info.waived.get(line)
    return codes is not None and ("" in codes or code in codes)


class _LockId:
    """Stable cross-file identity for a lock expression."""

    @staticmethod
    def of(chain: List[str], classname: Optional[str],
           module: str) -> str:
        if chain[0] in ("self", "cls"):
            if len(chain) == 2 and classname:
                # self._cache_lock inside Informer -> Informer._cache_lock
                return f"{classname}.{chain[1]}"
            # self._s.lock -> _s.lock: the shared-state object's type is
            # the identity, whatever class reaches through it
            return ".".join(chain[1:])
        # bare / module-level locks are file-scoped: two modules' `lock`
        # must not alias into one graph node
        return f"{module}:{'.'.join(chain)}"


def _lock_exprs(item: ast.withitem, classname: Optional[str],
                module: str) -> Optional[Tuple[str, List[str]]]:
    chain = _attr_chain(item.context_expr)
    if chain is None or not _LOCKISH.search(chain[-1]):
        return None
    return _LockId.of(chain, classname, module), chain


class Engine:
    """Two-phase whole-program pass: collect lock definitions and
    per-method acquisition sets, then walk every function tracking the
    lexically-held lockset, then check the global ordering graph."""

    def __init__(self):
        self.files: List[_FileInfo] = []
        self.rlocks: Set[str] = set()
        # (outer id, inner id) -> (info, line) of first occurrence
        self.edges: Dict[Tuple[str, str], Tuple[_FileInfo, int]] = {}
        self.findings: List[Finding] = []

    # -- phase 1: definitions ------------------------------------------

    def add_file(self, path: Path, source: str,
                 tree: Optional[ast.Module] = None) -> None:
        if tree is None:
            try:
                tree = ast.parse(source, filename=str(path))
            except SyntaxError as e:
                self.findings.append(Finding(path, e.lineno or 0, "L100",
                                             f"syntax error: {e.msg}"))
                return
        info = _FileInfo(path, tree, source)
        self.files.append(info)
        self._collect_defs(info)

    def _collect_defs(self, info: _FileInfo) -> None:
        for classname, fn in self._functions(info.tree):
            acquires: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        got = _lock_exprs(item, classname, info.module)
                        if got:
                            acquires.add(got[0])
            info.fn_acquires[(classname, fn.name)] = acquires
        # RLock definitions: `<target> = threading.RLock()` (or the
        # tracked factory `make_rlock(...)`) — re-acquiring these nested
        # is legal, so L101's same-lock check skips them.
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            call = node.value
            if not isinstance(call, ast.Call):
                continue
            fchain = _attr_chain(call.func)
            if fchain and fchain[-1] in ("RLock", "make_rlock"):
                tchain = _attr_chain(node.targets[0])
                if tchain:
                    classname = self._enclosing_class(info.tree, node)
                    self.rlocks.add(
                        _LockId.of(tchain, classname, info.module))

    @staticmethod
    def _enclosing_class(tree: ast.Module, target: ast.AST
                         ) -> Optional[str]:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if sub is target:
                        return node.name
        return None

    @staticmethod
    def _functions(tree: ast.Module
                   ) -> Iterable[Tuple[Optional[str], ast.AST]]:
        """(enclosing class name, function) for every def in the file;
        nested defs report the class of their outermost method."""
        def visit(node, classname):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    yield from visit(child, child.name)
                elif isinstance(child, _FUNCS):
                    yield classname, child
                    yield from visit(child, classname)
                else:
                    yield from visit(child, classname)
        yield from visit(tree, None)

    # -- phase 2: per-function walks -----------------------------------

    def run(self) -> List[Finding]:
        for info in self.files:
            for classname, fn in self._functions(info.tree):
                self._walk_held(info, classname, fn, fn.body, [])
                self._check_shared_views(info, fn)
            self._check_compat_shim(info)
            self._check_columnar_purity(info)
            self._check_wave_repack(info)
            self._check_knob_literals(info)
        # field-level lock ownership (L119/L120) — its own module, the
        # local import keeps the dependency one-directional
        from . import ownership
        self.findings.extend(ownership.run_pass(self.files))
        self._check_ordering_graph()
        self._check_wrapper_fence_gate()
        self._check_sharded_submit_gate()
        self._check_coalescer_trace_gate()
        self._check_rollout_gate()
        self._check_region_handoff_gate()
        suppressed = [f for f in self.findings
                      if not self._finding_waived(f)]
        return suppressed

    def raw_findings(self) -> List[Finding]:
        """Findings before waiver filtering (the useless-noqa probe)."""
        return list(self.findings)

    def _finding_waived(self, f: Finding) -> bool:
        for info in self.files:
            if info.path == f.path:
                return _is_waived(info, f.line, f.code)
        return False

    # .. held-lockset walk (L101, L102, L104) ..........................

    def _walk_held(self, info, classname, fn, nodes, held) -> None:
        """Recursive node-list walk carrying the lexically-held lockset
        as (lock id, chain, line) triples.  Nested function bodies run
        with a FRESH (empty) set — a closure defined under a lock does
        not execute under it."""
        for child in nodes:
            if isinstance(child, _FUNCS + (ast.Lambda, ast.ClassDef)):
                continue  # separate execution context, walked on its own
            if isinstance(child, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in child.items:
                    got = _lock_exprs(item, classname, info.module)
                    if got is None:
                        continue
                    lock_id, chain = got
                    self._note_acquire(info, fn, lock_id, held,
                                       child.lineno)
                    acquired.append((lock_id, chain, child.lineno))
                self._walk_held(info, classname, fn, child.body,
                                held + acquired)
                continue
            self._check_node(info, classname, fn, child, held)
            self._walk_held(info, classname, fn,
                            ast.iter_child_nodes(child), held)

    def _note_acquire(self, info, fn, lock_id, held, line) -> None:
        for held_id, _, held_line in held:
            if held_id == lock_id:
                if lock_id not in self.rlocks:
                    self.findings.append(Finding(
                        info.path, line, "L101",
                        f"nested acquisition of non-reentrant lock "
                        f"'{lock_id}' (already held since line "
                        f"{held_line}) deadlocks"))
                continue
            key = (held_id, lock_id)
            if key not in self.edges:
                self.edges[key] = (info, line)

    def _check_wrapper_fence_gate(self) -> None:
        """L108's other half: every ``apis.*`` write in the tree relies
        on ``ResilientAPIs.invoke`` consulting the fence at runtime —
        so whenever the resilience wrapper module is part of the linted
        set, its ``invoke`` must lexically carry the consult (the
        seeded-mutation probe strips it and asserts this fires).  A
        fixture subset without wrapper.py trusts the shipped one."""
        for info in self.files:
            if info.path.name != "wrapper.py" \
                    or not _l105_in_scope(info.path):
                continue
            invokes = [fn for _, fn in self._functions(info.tree)
                       if fn.name == "invoke"]
            if not invokes:
                continue
            for fn in invokes:
                if not _consults_fence(fn):
                    self.findings.append(Finding(
                        info.path, fn.lineno, "L108",
                        "ResilientAPIs.invoke no longer consults the "
                        "lifecycle fence: every apis.* write in the "
                        "tree relies on this gate to reject mutations "
                        "after stop/lease-loss "
                        "(resilience/fence.py)"))

    def _check_sharded_submit_gate(self) -> None:
        """L110's other half: every coalesced mutation in the tree is
        shard-gated at runtime by the ShardedCoalescer's routing
        method carrying ``self._shards.check(container_key)`` — so
        whenever batcher.py is part of the linted set, that consult
        must be lexically present on the submit path (the
        seeded-mutation probe strips it and asserts this fires)."""
        for info in self.files:
            if info.path.name != "batcher.py" \
                    or not _l105_in_scope(info.path):
                continue
            submits = [fn for cls, fn in self._functions(info.tree)
                       if cls == "ShardedCoalescer"
                       and fn.name in ("_cohort", "change_record_sets",
                                       "update_endpoints")]
            if not submits:
                continue
            if not any(_consults_shard(fn) for fn in submits):
                self.findings.append(Finding(
                    info.path, submits[0].lineno, "L110",
                    "ShardedCoalescer's submit path no longer asserts "
                    "shard ownership: every coalesced mutation in the "
                    "tree relies on this gate to keep one writer per "
                    "endpoint group / hosted zone "
                    "(sharding/shardset.py ShardSet.check)"))

    def _check_coalescer_trace_gate(self) -> None:
        """L114's other half: coalescer intents get their trace from
        the AMBIENT attach (tracing.ambient_context) captured on the
        submit path, not from per-call plumbing — so whenever
        batcher.py is part of the linted set, ``MutationCoalescer's``
        submit must lexically carry that capture (the seeded-mutation
        probe strips it and asserts this fires).  A fixture subset
        without batcher.py trusts the shipped one."""
        for info in self.files:
            if info.path.name != "batcher.py" \
                    or not _l105_in_scope(info.path):
                continue
            submits = [fn for cls, fn in self._functions(info.tree)
                       if cls == "MutationCoalescer"
                       and fn.name == "_submit"]
            if not submits:
                continue
            if not any(_consults_trace(fn) for fn in submits):
                self.findings.append(Finding(
                    info.path, submits[0].lineno, "L114",
                    "MutationCoalescer._submit no longer captures the "
                    "ambient trace context: every coalesced mutation "
                    "in the tree relies on this capture to carry its "
                    "submitter's trace across the flush boundary "
                    "(tracing.ambient_context)"))

    def _check_rollout_gate(self) -> None:
        """L112's other half: the two weight-bearing controllers'
        shipped rollout consults are load-bearing for every ramp in
        the fleet — whenever their files are part of the linted set,
        the consult must be lexically present (the seeded-mutation
        probe strips one and asserts this fires).  A fixture subset
        without the controllers trusts the shipped ones."""
        surfaces = {
            "endpointgroupbinding.py": ("_reconcile_update",),
            "route53.py": ("process_service_create_or_update",
                           "process_ingress_create_or_update"),
        }
        for info in self.files:
            names = surfaces.get(info.path.name)
            if names is None or not _l105_in_scope(info.path) \
                    or "controller" not in info.path.parts:
                continue
            for classname, fn in self._functions(info.tree):
                if fn.name in names and not _consults_rollout(fn):
                    self.findings.append(Finding(
                        info.path, fn.lineno, "L112",
                        f"'{fn.name}' no longer consults the rollout "
                        f"gate: every weight this controller writes "
                        f"relies on rollout/engine.py deciding the "
                        f"in-force mid-ramp values — an unconsulted "
                        f"path snaps ramping objects to their final "
                        f"target"))

    def _check_region_handoff_gate(self) -> None:
        """L116's other half: with a topology configured, every
        coalesced mutation reaches the wire through the
        ShardedCoalescer→aggregator handoff — the ``_wire_*``
        functions on ``MutationCoalescer`` consulting the region
        aggregator.  Whenever batcher.py is part of the linted set,
        that consult must be lexically present (the seeded-mutation
        probe strips it and asserts this fires); a batcher.py with no
        ``_wire_*`` functions at all has lost the handoff entirely
        and fires too."""
        for info in self.files:
            if info.path.name != "batcher.py" \
                    or not _l105_in_scope(info.path):
                continue
            wires = [fn for cls, fn in self._functions(info.tree)
                     if cls == "MutationCoalescer"
                     and fn.name.startswith("_wire_")]
            coalescers = [fn for cls, fn in self._functions(info.tree)
                          if cls == "MutationCoalescer"]
            if not coalescers:
                continue
            if not wires or not all(_consults_aggregator(fn)
                                    for fn in wires):
                line = (wires[0].lineno if wires
                        else coalescers[0].lineno)
                self.findings.append(Finding(
                    info.path, line, "L116",
                    "MutationCoalescer's wire path no longer hands "
                    "off to the region aggregator: with a topology "
                    "configured every coalesced mutation relies on "
                    "this consult to ride the per-region fan-in "
                    "(topology/aggregator.py) instead of flat "
                    "cross-region calls"))

    def _check_compat_shim(self, info: _FileInfo) -> None:
        """Rule L111: version-sensitive ``pltpu.*``/``orbax.*`` access
        outside ``compat/``.  Whole-file pass (imports are module
        statements the per-function walk never visits): flags (a) any
        import of the drifting modules, and (b) any attribute chain
        rooted at ``pltpu``/``orbax`` — the grafted-call shape that
        reaches the raw surface without a visible import."""
        if not _l111_in_scope(info.path):
            return

        def flag(line: int, what: str) -> None:
            self.findings.append(Finding(
                info.path, line, "L111",
                f"{what} reaches a version-sensitive accelerator "
                f"surface directly — these symbols drift between "
                f"jax/orbax releases and fail as opaque "
                f"AttributeErrors at trace time; import the resolved "
                f"name from compat/jaxshim.py / compat/orbaxshim.py "
                f"(or waive with '# race: <reason>')"))

        flagged_lines: Set[int] = set()
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if _l111_module(alias.name):
                        flag(node.lineno,
                             f"import of '{alias.name}'")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level:  # relative import: package-internal
                    continue
                if _l111_module(mod):
                    flag(node.lineno, f"import from '{mod}'")
                elif mod == "jax.experimental.pallas" and any(
                        alias.name == "tpu" for alias in node.names):
                    flag(node.lineno,
                         "import of 'jax.experimental.pallas.tpu'")
            elif isinstance(node, ast.Attribute):
                chain = _attr_chain(node)
                if (chain and _l111_chain(chain)
                        and node.lineno not in flagged_lines):
                    flagged_lines.add(node.lineno)
                    flag(node.lineno,
                         f"attribute access '{'.'.join(chain)}'")

    def _check_columnar_purity(self, info: _FileInfo) -> None:
        """Rule L113: the columnar planner modules stay pure over
        packed arrays — no reach through ``apis`` anywhere in the
        module, no Python loops over fleet keys inside a device
        program (module docstring).  Whole-file pass like L111: the
        ``apis`` half must also catch module-level statements the
        per-function walk never visits."""
        if not _l113_in_scope(info.path):
            return
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain and "apis" in chain[:-1]:
                self.findings.append(Finding(
                    info.path, node.lineno, "L113",
                    f"provider call '{'.'.join(chain)}()' inside the "
                    f"columnar planner: the whole-fleet pass is pure "
                    f"over packed arrays — collect provider state in "
                    f"the caller (controller/fleetsweep.py) and pack "
                    f"it, or waive with '# race: <reason>'"))
        for classname, fn in self._functions(info.tree):
            if not _l113_device_fn(fn):
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.For, ast.AsyncFor,
                                     ast.While)):
                    self.findings.append(Finding(
                        info.path, node.lineno, "L113",
                        f"Python loop in device program "
                        f"'{fn.name}': a per-object loop over fleet "
                        f"keys in the jit path reverts the planner "
                        f"to object-at-a-time cost (and recompiles "
                        f"per fleet size) — express it as array ops "
                        f"over the packed [G, E] grids, or move the "
                        f"loop to host-side pack/decode"))

    def _check_wave_repack(self, info: _FileInfo) -> None:
        """Rule L118: the steady-state wave path never full-repacks.
        The sweep controller and the plan/flush pipeline plan through
        the resident planner's dirty-mask API; ``pack_fleet`` /
        ``plan_groups`` stay behind oracle/verification entry points
        (``verify_full_repack`` and friends).  Whole-file pass like
        L113 so module-level calls are caught too; calls lexically
        inside an oracle-tagged function (name contains ``oracle``/
        ``verify``/``full_repack``, nested helpers included) are the
        allowed shape."""
        if not _l118_in_scope(info.path):
            return
        exempt: Set[int] = set()
        for _classname, fn in self._functions(info.tree):
            if any(tag in fn.name for tag in _L118_ORACLE_TAGS):
                exempt.update(id(n) for n in ast.walk(fn)
                              if isinstance(n, ast.Call))
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call) or id(node) in exempt:
                continue
            chain = _attr_chain(node.func)
            if chain and chain[-1] in _L118_REPACK_CALLS:
                self.findings.append(Finding(
                    info.path, node.lineno, "L118",
                    f"full-repack call '{'.'.join(chain)}()' on the "
                    f"steady-state wave path: waves replan only dirty "
                    f"shards through the resident planner "
                    f"(ResidentFleetPlanner.plan_wave) — a full "
                    f"repack here reverts steady state to O(fleet) "
                    f"per wave; keep pack_fleet/plan_groups behind "
                    f"an oracle/verify entry point or waive with "
                    f"'# race: <reason>'"))

    def _check_knob_literals(self, info: _FileInfo) -> None:
        """Rule L117: knobs owned by the TunableRegistry
        (autotune/knobs.py catalog) must not be re-hardcoded as
        numeric literals in the clock-owned packages.  The feedback
        controllers steer the LIVE values and the snap-to-default
        freeze restores the catalog's; a fresh literal spelling of a
        registered parameter name forks "the default" away from the
        registry's and silently escapes both.  Flagged shapes (for
        any catalog parameter name — ``linger``, ``sweep_every``,
        ``aging_horizon``, ``depth_watermark``, ``age_watermark``,
        ``warm_gap``, ``breaker_window``, ``exchange_every``):

        - keyword arguments: ``CoalesceConfig(linger=0.005)``;
        - signature defaults: ``def __init__(self, linger=0.005)``
          (dataclass field defaults parse as the next shape);
        - assignments whose target NAME is, or suffixes as, a
          parameter name: ``linger = 0.005``, ``self.linger = 0.005``,
          ``DEFAULT_AGING_HORIZON = 2.0`` (annotated or not).

        Import the catalog constant instead
        (``knobs.COALESCER_LINGER``); a deliberate divergent literal
        is waived with '# race: <reason>'."""
        if not _l117_in_scope(info.path):
            return
        from ..autotune.knobs import PARAM_NAMES

        def numeric(node) -> bool:
            return (isinstance(node, ast.Constant)
                    and isinstance(node.value, (int, float))
                    and not isinstance(node.value, bool))

        def matched_param(name: str):
            low = name.lower()
            for p in PARAM_NAMES:
                if low == p or low.endswith("_" + p):
                    return p
            return None

        def flag(line: int, what: str, param: str) -> None:
            self.findings.append(Finding(
                info.path, line, "L117",
                f"re-hardcoded knob {what}: '{param}' is owned by "
                f"the TunableRegistry (autotune/knobs.py) — import "
                f"its catalog constant so the feedback controllers' "
                f"snap-to-default provably restores it, or waive a "
                f"deliberate divergence with '# race: <reason>'"))

        for node in ast.walk(info.tree):
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg and numeric(kw.value):
                        p = matched_param(kw.arg)
                        if p is not None:
                            flag(kw.value.lineno,
                                 f"keyword '{kw.arg}="
                                 f"{kw.value.value}'", p)
            elif isinstance(node, _FUNCS):
                a = node.args
                pos = a.posonlyargs + a.args
                for arg, default in zip(pos[len(pos)
                                            - len(a.defaults):],
                                        a.defaults):
                    if numeric(default):
                        p = matched_param(arg.arg)
                        if p is not None:
                            flag(default.lineno,
                                 f"signature default '{arg.arg}="
                                 f"{default.value}'", p)
                for arg, default in zip(a.kwonlyargs, a.kw_defaults):
                    if default is not None and numeric(default):
                        p = matched_param(arg.arg)
                        if p is not None:
                            flag(default.lineno,
                                 f"signature default '{arg.arg}="
                                 f"{default.value}'", p)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                value = node.value
                if value is None or not numeric(value):
                    continue
                for tgt in targets:
                    name = (tgt.id if isinstance(tgt, ast.Name)
                            else tgt.attr
                            if isinstance(tgt, ast.Attribute)
                            else None)
                    if name is None:
                        continue
                    p = matched_param(name)
                    if p is not None:
                        flag(node.lineno,
                             f"assignment '{name} = {value.value}'",
                             p)

    def _check_ordering_graph(self) -> None:
        seen: Set[Tuple[str, str]] = set()
        for (a, b), (info, line) in sorted(
                self.edges.items(),
                key=lambda kv: (str(kv[1][0].path), kv[1][1])):
            if (b, a) not in self.edges or (b, a) in seen:
                continue
            seen.add((a, b))
            rinfo, rline = self.edges[(b, a)]
            if _is_waived(info, line, "L101") \
                    or _is_waived(rinfo, rline, "L101"):
                continue
            self.findings.append(Finding(
                info.path, line, "L101",
                f"lock ordering inversion: '{a}' -> '{b}' here but "
                f"'{b}' -> '{a}' at {rinfo.path}:{rline} — concurrent "
                f"paths deadlock"))

    def _check_node(self, info, classname, fn, node, held) -> None:
        if isinstance(node, ast.Call):
            self._check_call(info, classname, fn, node, held)
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                self._check_fleet_write(info, fn, tgt, held)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                self._check_fleet_write(info, fn, tgt, held)

    def _check_call(self, info, classname, fn, call, held) -> None:
        chain = _attr_chain(call.func)
        if chain is None:
            return
        line = call.lineno
        # L104a: *_locked callees document "caller holds the lock";
        # calling one with no lock lexically open is exactly the
        # _update_accelerator stale-index bug shape from PR 1.
        if chain[-1].endswith("_locked") and len(chain) > 1:
            if not held and not fn.name.endswith("_locked"):
                self.findings.append(Finding(
                    info.path, line, "L104",
                    f"'{chain[-1]}()' requires the caller to hold the "
                    f"cache lock but no 'with <lock>:' block is open "
                    f"here"))
        # L104b: mutating-method writes through the fleet state
        # (self._s.discovery.pop(...), self._s.prime_log.append(...)).
        if (chain[-1] in _MUTATING_METHODS and len(chain) >= 3
                and chain[-2] in FLEET_FIELDS and chain[-3] == "_s"):
            self._require_lock(info, fn, line, held,
                               f"_s.{chain[-2]}.{chain[-1]}()")
        # L104c: gen-keyed singleflight reads.
        if chain[-1] == "do" and len(chain) >= 2 and chain[-2] == "reads":
            self._check_singleflight_key(info, call)
        # L105: an AWS service method reached without going through
        # ``apis`` (the wrapper injection point) runs bare — no retry,
        # no breaker, no deadline.
        if (len(chain) >= 2 and chain[-1] in _AWS_API_METHODS
                and chain[-2] in _AWS_SERVICES
                and "apis" not in chain[:-2]
                and _l105_in_scope(info.path)):
            self.findings.append(Finding(
                info.path, line, "L105",
                f"direct AWS API call "
                f"'{'.'.join(chain)}()' bypasses the ResilientAPIs "
                f"wrapper (no retry/breaker/deadline policy) — reach "
                f"it via '...apis.{chain[-2]}.{chain[-1]}' or waive "
                f"with '# race: <reason>' if this is a deliberate "
                f"bare call"))
        # L106: a mutation on the write-coalescing surface issued
        # directly — even through ``apis`` — bypasses the
        # MutationCoalescer.  batcher.py (the flush issuer) is the one
        # exempt module.
        if (len(chain) >= 2 and (chain[-2], chain[-1]) in _COALESCED_WRITES
                and _l105_in_scope(info.path)
                and info.path.name != "batcher.py"
                and "topology" not in info.path.parts):
            # topology/aggregator.py's flat fallback is the one other
            # legitimate flush issuer: it sits BELOW the coalescer
            self.findings.append(Finding(
                info.path, line, "L106",
                f"direct write-path mutation '{'.'.join(chain)}()' "
                f"bypasses the MutationCoalescer (no folding, no "
                f"bisect-on-rejection, no per-waiter error demux — "
                f"cloudprovider/aws/batcher.py): submit an intent via "
                f"the provider's coalescer, or waive with "
                f"'# race: <reason>' for a deliberate direct call"))
        # L107: the fingerprint fast path must stay provider-free —
        # no reach through ``apis`` and no AWS service method at all
        # (the skip's whole contract is zero provider calls).
        if (_l105_in_scope(info.path)
                and _l107_fastpath(info.path, fn.name)
                and ("apis" in chain[:-1]
                     or (len(chain) >= 2
                         and chain[-1] in _AWS_API_METHODS
                         and chain[-2] in _AWS_SERVICES))):
            self.findings.append(Finding(
                info.path, line, "L107",
                f"provider call '{'.'.join(chain)}()' on the "
                f"fingerprint fast path (reconcile/fingerprint.py "
                f"contract: a skip costs ZERO provider calls) — move "
                f"the read into the sync/sweep path, or waive with "
                f"'# race: <reason>' if this is deliberate"))
        # L108: an AWS WRITE must be fence-gated.  Through ``apis`` the
        # ResilientAPIs.invoke runtime gate covers it (verified by
        # _check_wrapper_fence_gate when wrapper.py is in the set); a
        # BARE service write needs a lexical fence consult right here.
        if (len(chain) >= 2 and chain[-1] in _AWS_WRITE_METHODS
                and chain[-2] in _AWS_SERVICES
                and "apis" not in chain[:-2]
                and _l105_in_scope(info.path)
                and not _consults_fence(fn)):
            self.findings.append(Finding(
                info.path, line, "L108",
                f"unfenced mutation '{'.'.join(chain)}()': a bare "
                f"AWS write reachable after stop/lease-loss must "
                f"consult the lifecycle fence (resilience/fence.py — "
                f"call '...fence.check(...)' in this function, route "
                f"the write through 'apis' so ResilientAPIs gates it, "
                f"or waive with '# race: <reason>')"))
        # L110a: a BARE AWS write must also assert shard ownership
        # (sharding/shardset.py ShardSet.check) — through ``apis`` the
        # routed dispatch's guard + the ShardedCoalescer submit gate
        # cover it at runtime (verified by _check_sharded_submit_gate
        # when batcher.py is in the set).
        if (len(chain) >= 2 and chain[-1] in _AWS_WRITE_METHODS
                and chain[-2] in _AWS_SERVICES
                and "apis" not in chain[:-2]
                and _l105_in_scope(info.path)
                and not _consults_shard(fn)):
            self.findings.append(Finding(
                info.path, line, "L110",
                f"shard-unchecked mutation '{'.'.join(chain)}()': a "
                f"bare AWS write must pass through the shard-ownership "
                f"assertion (sharding/shardset.py — call "
                f"'...shards.check(container_key)' in this function, "
                f"route the write through the sharded coalescer, or "
                f"waive with '# race: <reason>')"))
        # L112: an endpoint-weight mutation outside rollout/ must be
        # gated on the rollout engine — an unconsulted write snaps a
        # mid-ramp object straight to its final target.
        if (len(chain) >= 2 and chain[-1] in _WEIGHT_MUTATIONS
                and _l112_in_scope(info.path)
                and not _consults_rollout(fn)):
            self.findings.append(Finding(
                info.path, line, "L112",
                f"ungated weight mutation '{'.'.join(chain)}()': an "
                f"endpoint-weight write outside rollout/ must consult "
                f"the rollout gate in this function "
                f"(rollout/engine.py — 'self.rollout.decide(...)' "
                f"decides the weights IN FORCE mid-ramp; an ungated "
                f"write snaps a ramping object to its target), or "
                f"waive with '# race: <reason>'"))
        # L109: an enqueue that names no traffic class silently
        # defaults the key's tier — the controller/reconcile packages
        # must say whether a key is interactive, background, or a
        # requeue keeping its class (CLASS_KEEP).
        if (len(chain) >= 2 and chain[-1] in _ENQUEUE_METHODS
                and any("queue" in seg for seg in chain[:-1])
                and _l109_in_scope(info.path)
                and not any(kw.arg == "klass" for kw in call.keywords)):
            self.findings.append(Finding(
                info.path, line, "L109",
                f"class-less enqueue '{'.'.join(chain)}()': pass "
                f"klass= (CLASS_INTERACTIVE for watch events / "
                f"user-visible changes, CLASS_BACKGROUND for "
                f"resync/sweep re-deliveries, CLASS_KEEP for "
                f"requeues) so the key rides the right workqueue "
                f"tier (kube/workqueue.py), or waive with "
                f"'# race: <reason>'"))
        # L114: an enqueue that names no trace context silently severs
        # the event's trace at the queue boundary — the same
        # controller/reconcile surface L109 polices must say whose
        # trace the item carries (or explicitly ctx=None).
        if (len(chain) >= 2 and chain[-1] in _ENQUEUE_METHODS
                and any("queue" in seg for seg in chain[:-1])
                and _l109_in_scope(info.path)
                and not any(kw.arg == "ctx" for kw in call.keywords)):
            self.findings.append(Finding(
                info.path, line, "L114",
                f"trace-dropping enqueue '{'.'.join(chain)}()': pass "
                f"ctx= (the event's TraceContext from "
                f"tracing.new_context / the dispatch's claimed_trace, "
                f"or an explicit ctx=None for a genuinely untraced "
                f"path) so the item carries its trace across the "
                f"queue/thread boundary (tracing.py), or waive with "
                f"'# race: <reason>'"))
        # L116: a cross-region wire call (the regional aggregation
        # point) outside topology/ re-creates flat fan-in without the
        # aggregator's fence/demux/accounting contracts.
        if (chain[-1] in _CROSS_REGION_METHODS
                and _l116_in_scope(info.path)):
            self.findings.append(Finding(
                info.path, line, "L116",
                f"cross-region mutation '{'.'.join(chain)}()' outside "
                f"topology/: the regional aggregation point is issued "
                f"only by the per-region aggregators "
                f"(topology/aggregator.py — per-contribution fence "
                f"checks, per-entry error demux, region batch "
                f"accounting); submit through the coalescer so the "
                f"handoff routes it, or waive with "
                f"'# race: <reason>'"))
        # L115: wall-clock leaks in the clock-owned packages — a
        # direct time.* read/sleep or a raw threading primitive is
        # invisible to the virtual clock (simulation/clock.py): under
        # simulation the wait parks in the OS (a stalled sim) or reads
        # real seconds the scenario thought were virtual.
        if _l115_in_scope(info.path):
            leak = None
            if (len(chain) == 2 and chain[0] == "time"
                    and chain[1] in ("monotonic", "time", "sleep")):
                leak = (f"'{'.'.join(chain)}()' — use simclock."
                        f"{'wall' if chain[1] == 'time' else chain[1]}"
                        f"() (simulation/clock.py)")
            elif (len(chain) == 2 and chain[0] == "threading"
                    and chain[1] in ("Event", "Condition")):
                leak = (f"'threading.{chain[1]}()' — use simclock."
                        f"make_{chain[1].lower()}() so waits park in "
                        f"the active clock")
            elif (chain[-1] == "wait" and len(chain) > 1
                    and any(isinstance(a, ast.Constant)
                            and isinstance(a.value, (int, float))
                            and not isinstance(a.value, bool)
                            for a in list(call.args)
                            + [kw.value for kw in call.keywords])):
                leak = (f"'{'.'.join(chain)}(<literal timeout>)' — a "
                        f"hard-coded real-seconds wait; name the "
                        f"bound (module constant) or derive it from "
                        f"the clock")
            if leak is not None:
                self.findings.append(Finding(
                    info.path, line, "L115",
                    f"wall-clock leak: {leak}.  Wall-clock reads "
                    f"outside simulation/clock.py break virtual-time "
                    f"determinism (ISSUE 13); waive a deliberate one "
                    f"with '# race: <reason>'"))
        # L102: blocking while any lock is held.
        if held and self._is_blocking(chain, held):
            self.findings.append(Finding(
                info.path, line, "L102",
                f"blocking call '{'.'.join(chain)}' while holding "
                f"'{held[-1][0]}' (held since line {held[-1][2]}) "
                f"stalls every other thread needing the lock"))
        # L101 one-level call expansion: self.method() whose body
        # acquires locks counts as acquiring them here.
        if (held and len(chain) == 2 and chain[0] in ("self", "cls")):
            for lock_id in info.fn_acquires.get(
                    (classname, chain[1]), ()):
                self._note_acquire(info, fn, lock_id, held, line)

    def _is_blocking(self, chain: List[str],
                     held: List[Tuple[str, List[str], int]]) -> bool:
        if chain[-1] == "sleep" and len(chain) > 1:
            return True   # time.sleep AND simclock.sleep both park
        if chain[0] in _BLOCKING_ROOTS:
            return True
        if chain[-1] == "urlopen":
            return True
        if "apis" in chain[:-1]:   # self.apis.ga.describe_accelerator(...)
            return True
        if chain[-1] in ("wait", "join") and len(chain) > 1:
            # cv.wait() on the HELD condition releases it while parked —
            # the one legal wait under a lock; anything else
            # (Event.wait, Thread.join, a different lock) parks the
            # thread with the lock still held.
            target = chain[:-1]
            return not any(target == hc for _, hc, _ in held)
        return False

    def _require_lock(self, info, fn, line, held, what) -> None:
        if held or fn.name.endswith("_locked") or fn.name == "__init__":
            return
        self.findings.append(Finding(
            info.path, line, "L104",
            f"fleet-state write '{what}' outside a 'with <lock>:' "
            f"block (the discovery cache's single-writer contract, "
            f"provider.FleetDiscoveryState)"))

    def _check_fleet_write(self, info, fn, tgt, held) -> None:
        # self._s.<field> = ... / self._s.<field>[k] = ... / del ...
        node = tgt
        sub = ""
        if isinstance(node, ast.Subscript):
            sub = "[...]"
            node = node.value
        chain = _attr_chain(node)
        if (chain and len(chain) >= 3 and chain[-2] == "_s"
                and chain[-1] in FLEET_FIELDS):
            self._require_lock(info, fn, tgt.lineno,
                               held, f"_s.{chain[-1]}{sub}")

    def _check_singleflight_key(self, info, call: ast.Call) -> None:
        line = call.lineno
        if not call.args:
            return
        key = call.args[0]
        if not isinstance(key, ast.Tuple):
            self.findings.append(Finding(
                info.path, line, "L104",
                "gen-keyed singleflight read: the key of a "
                "'reads.do(...)' call must be a tuple carrying the "
                "cache generation"))
            return
        for elt in key.elts:
            chain = _attr_chain(elt)
            if chain and "gen" in chain[-1]:
                return
        self.findings.append(Finding(
            info.path, line, "L104",
            "singleflight key lacks a generation component: a read "
            "begun before an invalidation could be joined by a caller "
            "arriving after it (key by the cache gen, see "
            "provider._verified_read)"))

    # .. shared-view taint (L103) ......................................

    def _check_shared_views(self, info, fn) -> None:
        if not isinstance(fn, _FUNCS):
            return
        # var -> (taint line, kind).  'view' = one shared object;
        # 'viewlist' = a lister-returned LIST: the list container is
        # caller-owned (informers hand out a fresh shallow list per
        # call — sorting/filtering/appending it is legal), only the
        # ELEMENTS are shared views.
        tainted: Dict[str, Tuple[int, str]] = {}

        def view_call_kind(node) -> Optional[str]:
            if not isinstance(node, ast.Call):
                return None
            chain = _attr_chain(node.func)
            if chain is None:
                return None
            if chain[-1] in ("by_index", "cache_list"):
                return "viewlist"
            if chain[-1] == "cache_get":
                return "view"
            if chain[-1] in _LISTER_METHODS \
                    and any("lister" in seg for seg in chain[:-1]):
                return "viewlist" if chain[-1] == "list" else "view"
            return None

        flagged: Set[Tuple[int, str]] = set()

        def flag(node, var):
            # compound statements are scanned once per nesting level;
            # report each (line, var) once
            if (node.lineno, var) in flagged:
                return
            flagged.add((node.lineno, var))
            self.findings.append(Finding(
                info.path, node.lineno, "L103",
                f"in-place mutation of '{var}' (a shared informer-cache "
                f"view from line {tainted[var][0]}): call .deep_copy() "
                f"before mutating (kube/informers.py read contract)"))

        def check_mutations(node):
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Assign, ast.AugAssign)):
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for tgt in targets:
                        check_store_target(sub, tgt)
                elif isinstance(sub, ast.Delete):
                    for tgt in sub.targets:
                        check_store_target(sub, tgt)
                elif isinstance(sub, ast.Call):
                    chain = _attr_chain(sub.func)
                    if (chain and len(chain) > 1
                            and chain[-1] in _MUTATING_METHODS
                            and chain[0] in tainted):
                        if (tainted[chain[0]][1] == "viewlist"
                                and len(chain) == 2):
                            continue   # xs.sort(): caller-owned list
                        flag(sub, chain[0])

        def check_store_target(stmt, tgt):
            if not isinstance(tgt, (ast.Attribute, ast.Subscript)):
                return
            root = _root_name(tgt)
            if root not in tainted:
                return
            if (tainted[root][1] == "viewlist"
                    and isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)):
                return   # xs[0] = y: replacing an own-list slot
            flag(stmt, root)

        def process(stmts):
            for stmt in stmts:
                if isinstance(stmt, _FUNCS + (ast.ClassDef, ast.Lambda)):
                    continue   # separate scope, walked on its own
                check_mutations(stmt)
                # taint / untaint AFTER checking: `svc.x = 1` then
                # `svc = svc.deep_copy()` still flags line 1
                if isinstance(stmt, ast.Assign) \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    name = stmt.targets[0].id
                    value = stmt.value
                    kind = view_call_kind(value)
                    if kind:
                        tainted[name] = (stmt.lineno, kind)
                    elif isinstance(value, ast.Call) and (
                            chain := _attr_chain(value.func)) \
                            and chain[-1] in ("deep_copy", "deepcopy"):
                        tainted.pop(name, None)
                    elif isinstance(value, (ast.Attribute, ast.Subscript)):
                        root = _root_name(value)
                        if root in tainted:
                            # aliasing an element/field of a shared
                            # view shares the view
                            tainted[name] = (tainted[root][0], "view")
                        else:
                            tainted.pop(name, None)
                    else:
                        tainted.pop(name, None)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    it = stmt.iter
                    iter_is_view = (
                        view_call_kind(it) is not None
                        or (isinstance(it, ast.Name) and it.id in tainted
                            and tainted[it.id][1] == "viewlist"))
                    if iter_is_view and isinstance(stmt.target, ast.Name):
                        tainted[stmt.target.id] = (stmt.lineno, "view")
                # recurse into compound statements in source order
                for field in ("body", "orelse", "finalbody"):
                    process(getattr(stmt, field, []) or [])
                for handler in getattr(stmt, "handlers", []) or []:
                    process(handler.body)

        # comprehension variables over view calls (`for o in
        # informer.by_index(...)`) are shared elements: seed them
        # before the ordered pass so the mutation check sees them
        for node in ast.walk(fn):
            if isinstance(node, (ast.ListComp, ast.SetComp,
                                 ast.GeneratorExp, ast.DictComp)):
                for gen in node.generators:
                    if view_call_kind(gen.iter) \
                            and isinstance(gen.target, ast.Name):
                        tainted[gen.target.id] = (node.lineno, "view")
        process(fn.body)


def lint_files(files: Sequence[Path]) -> List[Finding]:
    """Run the L1xx suite over a file set; returns waiver-filtered
    findings sorted by (path, line)."""
    engine = Engine()
    for path in files:
        engine.add_file(path, path.read_text())
    findings = engine.run()
    return sorted(findings, key=lambda f: (str(f.path), f.line, f.code))
