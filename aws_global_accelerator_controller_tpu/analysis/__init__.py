"""Concurrency contract checking (static lints + runtime detectors).

PR 1 replaced defensive deepcopies on the reconcile hot path with
convention-only contracts (shared read-only lister views, fleet-index
writes under the discovery lock, generation-keyed singleflight reads).
This package makes those conventions machine-checked — the Python
analogue of running the Go reference under ``-race`` plus client-go's
cache object-mutation detector:

- ``concurrency_lint``: AST-based static pass (rules L101-L120) run by
  ``hack/lint.py --concurrency`` over the whole tree.  Pure stdlib, no
  runtime dependencies — importable by the lint gate without pulling in
  the controller stack.
- ``locks``: test-time lockset tracker.  ``make_lock``/``make_rlock``
  return plain threading primitives in production and instrumented ones
  when detection is enabled; the tracker records acquisition order per
  thread and raises :class:`locks.LockOrderViolation` on an ordering
  inversion, with the stacks of both acquisition sites.
- ``freezeproxy``: freeze-proxy mode for informer-cache views.  When
  enabled, listers hand out proxies that raise
  :class:`freezeproxy.SharedViewMutationError` on any in-place
  mutation, reporting both the mutation site and the lister call that
  produced the view.

Submodules are imported directly (``from ..analysis import locks``); the
package root stays import-light so the lint gate can load
``concurrency_lint`` without the metrics/threading machinery.
"""
