"""Field-level lock-ownership pass (rules L119-L120).

The ordering tracker (locks.py) and the L101-L118 contracts prove the
tree acquires locks consistently — but a no-GIL hot path needs the
stronger RacerD-style invariant: each shared FIELD is consistently
guarded by ONE lock.  This pass makes that invariant declarable and
checkable:

  declaration   a ``# guarded-by: <spec>`` comment on (or directly
                above) an attribute's assignment inside a class binds
                the attribute to its owner:

                    self._cache = {}        # guarded-by: self._cache_lock
                    self.gen = 0            # guarded-by: self.lock
                    self.arns = InternTable()  # guarded-by: external: sweep owner
                    self._clock = clock     # guarded-by: immutable
                    self._stop = Event()    # guarded-by: internal

                ``self.<lock>`` names an instance lock (checked
                lexically, rule L119); ``immutable`` promises the
                attribute is never written after ``__init__`` (L119
                flags post-init rebinds AND container mutation);
                ``internal`` marks an internally-synchronized object
                (Event, Queue, Singleflight — method calls are safe
                anywhere, only post-init REBINDS flag); ``external:
                <why>`` documents ownership the checker cannot see
                lexically (a caller's wave lock, pipeline
                serialization) — it satisfies L120 and is exempt from
                L119.

  L119          reads/writes of a declared-guarded attribute without
                the owning lock lexically held.  Class-qualified lock
                identities and one-level same-class call expansion,
                like L101: a method whose every same-class call site
                holds the owning lock is exempt (callers carry the
                lock), as are ``__init__``/``__post_init__`` and
                ``*_locked`` methods (their call sites are policed by
                L104).  One level of holder indirection is resolved
                through constructor assignments: after
                ``self._s = FleetDiscoveryState()``, accesses to
                ``self._s.<attr>`` are checked against the held
                class's declarations with the lock re-rooted at the
                holder (``self._s.lock`` — the same ``_s.lock``
                identity the ordering graph uses).  ``# race:``
                waivers are honored.

  L120          classes whose instances provably cross threads — any
                method spawns a thread (``threading.Thread`` /
                ``simclock.start_thread``), so state constructed on
                one thread is touched from worker/flusher/elector
                paths — with mutable attributes (written outside
                ``__init__``, or container-mutated via
                append/update/...) carrying neither a guard
                declaration nor an immutability waiver.

Unlike L101's closure rule (a nested def gets a FRESH lockset), L119
walks nested functions with the lockset held at their DEFINITION site:
a closure built under the lock and invoked later would over-report
otherwise, and the zero-findings gate favors precision over recall.

Pure stdlib ``ast``; invoked from concurrency_lint.Engine.run() so
waiver filtering, fixture scoping and ``hack/lint.py --concurrency``
wiring are shared with L101-L118.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .concurrency_lint import (Finding, _attr_chain, _FileInfo, _LockId,
                               _lock_exprs, _LOCKISH, _MUTATING_METHODS)

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*(.+?)\s*$")

# attribute names that ARE synchronization/lifecycle plumbing: the lock
# itself, its condition, thread handles and stop events need no guard
# declaration of their own (a lock does not guard itself)
_SYNCISH = re.compile(
    r"(?:^|_)(lock|cond|mutex|rlock|event|sem|thread|threads|waker)$")

# thread-spawn call surface: the stdlib constructor and the virtual
# clock's tracked spawner (simulation/clock.py)
_SPAWN_CALLS = {"Thread", "start_thread"}


class GuardDecl:
    """One parsed ``# guarded-by:`` declaration."""

    __slots__ = ("kind", "chain", "line", "spec")

    def __init__(self, kind: str, chain: Optional[List[str]],
                 line: int, spec: str):
        self.kind = kind          # 'lock' | 'immutable' | 'external'
        self.chain = chain        # ['self', '_cache_lock'] for 'lock'
        self.line = line
        self.spec = spec


def _l119_in_scope(path: Path) -> bool:
    """L119/L120 cover every shipped package file plus their own
    fixture corpus (other rules' fixtures spawn threads and strip
    locks deliberately — that is their test shape, not a finding)."""
    parts = path.parts
    if "lint_fixtures" in parts:
        return path.name.startswith(("l119_", "l120_"))
    return "aws_global_accelerator_controller_tpu" in parts


def _decl_comment(info: _FileInfo, node: ast.AST) -> Optional[Tuple[str, int]]:
    """The guarded-by spec attached to an assignment: on any source
    line of the statement, or in the contiguous pure-comment block
    directly above (an ``external:`` reason often wraps lines)."""
    lines = info.lines
    start = node.lineno
    end = getattr(node, "end_lineno", None) or start
    for ln in range(start, min(end, len(lines)) + 1):
        m = _GUARD_RE.search(lines[ln - 1])
        if m:
            return m.group(1), ln
    ln = start - 1
    while ln >= 1 and lines[ln - 1].strip().startswith("#"):
        m = _GUARD_RE.search(lines[ln - 1])
        if m:
            return m.group(1), ln
        ln -= 1
    return None


def _parse_spec(spec: str) -> Optional[GuardDecl]:
    if spec in ("immutable", "internal"):
        return GuardDecl(spec, None, 0, spec)
    if spec.split(":", 1)[0].strip() == "external":
        return GuardDecl("external", None, 0, spec)
    if spec.startswith("self."):
        return GuardDecl("lock", spec.split("."), 0, spec)
    return None


class _ClassGuards:
    """Declarations + derived facts for one class in one file."""

    def __init__(self, info: _FileInfo, node: ast.ClassDef):
        self.info = info
        self.node = node
        self.decls: Dict[str, GuardDecl] = {}
        # attr -> classname of the guarded class it holds (one-level
        # holder indirection, resolved after global collection)
        self.holds: Dict[str, str] = {}
        self.spawns_threads = False


def _assign_targets(node: ast.AST) -> Iterable[ast.AST]:
    if isinstance(node, ast.Assign):
        yield from node.targets
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        yield node.target
    elif isinstance(node, ast.Delete):
        yield from node.targets


def _self_attr(tgt: ast.AST) -> Optional[str]:
    """``self.X`` (through one optional subscript) -> 'X'."""
    node = tgt
    if isinstance(node, ast.Subscript):
        node = node.value
    chain = _attr_chain(node)
    if chain and len(chain) == 2 and chain[0] == "self":
        return chain[1]
    return None


class OwnershipPass:
    def __init__(self, files: Sequence[_FileInfo]):
        self.files = [f for f in files if _l119_in_scope(f.path)]
        self.findings: List[Finding] = []
        # classname -> _ClassGuards (first definition wins; the tree
        # has no duplicate shared-structure class names)
        self.classes: Dict[str, _ClassGuards] = {}

    # -- phase 1: declarations + thread-crossing facts -----------------

    def collect(self) -> None:
        for info in self.files:
            for node in ast.walk(info.tree):
                if isinstance(node, ast.ClassDef):
                    cg = _ClassGuards(info, node)
                    self.classes.setdefault(node.name, cg)
                    self._collect_class(cg)

    def _collect_class(self, cg: _ClassGuards) -> None:
        info = cg.info
        for sub in ast.walk(cg.node):
            if isinstance(sub, ast.Call):
                fchain = _attr_chain(sub.func)
                if fchain and fchain[-1] in _SPAWN_CALLS:
                    cg.spawns_threads = True
            for tgt in _assign_targets(sub):
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                got = _decl_comment(info, sub)
                if got is None:
                    continue
                spec, line = got
                decl = _parse_spec(spec)
                if decl is None:
                    self.findings.append(Finding(
                        info.path, line, "L119",
                        f"unparseable guard declaration "
                        f"'# guarded-by: {spec}' — use 'self.<lock>', "
                        f"'immutable', or 'external: <why>'"))
                    continue
                if decl.kind == "lock" \
                        and not _LOCKISH.search(decl.chain[-1]):
                    self.findings.append(Finding(
                        info.path, line, "L119",
                        f"guard declaration for '{attr}' names "
                        f"'{'.'.join(decl.chain)}', which the lock "
                        f"tracker will never see held (attribute "
                        f"names a lock only when it ends in "
                        f"lock/cond/mutex/rlock)"))
                    continue
                decl.line = line
                prev = cg.decls.get(attr)
                if prev is not None and prev.spec != decl.spec:
                    self.findings.append(Finding(
                        info.path, line, "L119",
                        f"conflicting guard declarations for "
                        f"'{attr}': '{prev.spec}' (line {prev.line}) "
                        f"vs '{decl.spec}'"))
                    continue
                cg.decls[attr] = decl

    def _collect_holders(self) -> None:
        """``self.X = GuardedClass(...)`` in __init__ makes X a holder:
        ``self.X.<attr>`` accesses check against GuardedClass's map.
        ``self.X = injected or GuardedClass()`` counts too — the
        dependency-injection default names the class either way."""
        for cg in self.classes.values():
            for sub in ast.walk(cg.node):
                if not isinstance(sub, ast.Assign):
                    continue
                calls: List[ast.Call] = []
                if isinstance(sub.value, ast.Call):
                    calls.append(sub.value)
                elif isinstance(sub.value, ast.BoolOp):
                    calls.extend(v for v in sub.value.values
                                 if isinstance(v, ast.Call))
                for call in calls:
                    fchain = _attr_chain(call.func)
                    if fchain is None:
                        continue
                    held_cls = self.classes.get(fchain[-1])
                    if held_cls is None or not held_cls.decls:
                        continue
                    for tgt in sub.targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            cg.holds[attr] = fchain[-1]

    # -- phase 2: held-lockset access walk -----------------------------

    def run(self) -> List[Finding]:
        self.collect()
        self._collect_holders()
        for classname, cg in self.classes.items():
            if cg.decls or cg.holds:
                self._check_class_l119(classname, cg)
            self._check_class_l120(classname, cg)
        return self.findings

    def _check_class_l119(self, classname: str, cg: _ClassGuards) -> None:
        info = cg.info
        # (method, attr, needed lock id) -> [(line, lock expr)]
        unheld: Dict[Tuple[str, str, str], List[Tuple[int, str]]] = {}
        # callee method -> [set of held lock ids at each same-class
        # call site] — the one-level call expansion
        callsites: Dict[str, List[Set[str]]] = {}

        def resolve(chain: List[str]
                    ) -> Optional[Tuple[str, GuardDecl, str, str]]:
            """An access chain -> (attr label, decl, owning lock
            id, lock expression to render in the finding)."""
            if len(chain) == 2 and chain[0] == "self":
                decl = cg.decls.get(chain[1])
                if decl is None:
                    return None
                lock_id = ""
                if decl.kind == "lock":
                    lock_id = _LockId.of(decl.chain, classname,
                                         info.module)
                return (chain[1], decl, lock_id,
                        ".".join(decl.chain or ()))
            if len(chain) == 3 and chain[0] == "self" \
                    and chain[1] in cg.holds:
                held_cls = self.classes[cg.holds[chain[1]]]
                decl = held_cls.decls.get(chain[2])
                if decl is None:
                    return None
                lock_id = expr = ""
                if decl.kind == "lock":
                    # re-root at the holder: self._s + lock -> _s.lock,
                    # the identity the ordering graph already uses
                    rooted = ["self", chain[1]] + decl.chain[1:]
                    lock_id = _LockId.of(rooted, classname, info.module)
                    expr = ".".join(rooted)
                return f"{chain[1]}.{chain[2]}", decl, lock_id, expr
            return None

        def note(method: str, node: ast.Attribute, held_ids: Set[str],
                 rebinds: Set[int], mutations: Set[int]) -> None:
            chain = _attr_chain(node)
            if chain is None:
                return
            got = resolve(chain)
            if got is None:
                return
            label, decl, lock_id, lock_expr = got
            if decl.kind == "external":
                return
            if decl.kind in ("immutable", "internal"):
                written = node.lineno in rebinds or (
                    decl.kind == "immutable"
                    and node.lineno in mutations)
                if written and method not in (
                        "__init__", "__post_init__"):
                    self.findings.append(Finding(
                        info.path, node.lineno, "L119",
                        f"write to '{label}' declared "
                        f"'# guarded-by: {decl.kind}' (line "
                        f"{decl.line}) outside __init__ — drop the "
                        f"waiver and declare its lock, or waive "
                        f"with '# race: <reason>'"))
                return
            if lock_id in held_ids:
                return
            unheld.setdefault((method, label, lock_id), []).append(
                (node.lineno, lock_expr))

        def walk(method: str, nodes, held: Set[str],
                 rebinds: Set[int], mutations: Set[int]) -> None:
            for child in nodes:
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    got_ids = set(held)
                    for item in child.items:
                        got = _lock_exprs(item, classname, info.module)
                        if got:
                            got_ids.add(got[0])
                    walk(method, child.body, got_ids, rebinds,
                         mutations)
                    continue
                if isinstance(child, _FUNCS + (ast.Lambda,)):
                    # closure: inherits the definition-site lockset
                    # (precision over recall — see module docstring)
                    body = child.body if isinstance(child.body, list) \
                        else [child.body]
                    walk(method, body, held, rebinds, mutations)
                    continue
                if isinstance(child, ast.ClassDef):
                    continue
                for tgt in _assign_targets(child):
                    node = tgt
                    if isinstance(node, ast.Subscript):
                        # container write through a subscript: not a
                        # rebind of the attribute itself
                        mutations.add(node.lineno)
                        node = node.value
                    elif isinstance(node, ast.Attribute):
                        rebinds.add(node.lineno)
                if isinstance(child, ast.Call):
                    fchain = _attr_chain(child.func)
                    if fchain and fchain[-1] in _MUTATING_METHODS \
                            and len(fchain) >= 3:
                        mutations.add(child.lineno)
                    if fchain and len(fchain) == 2 \
                            and fchain[0] == "self":
                        callsites.setdefault(
                            fchain[-1], []).append(set(held))
                if isinstance(child, ast.Attribute):
                    note(method, child, held, rebinds, mutations)
                walk(method, ast.iter_child_nodes(child), held,
                     rebinds, mutations)

        for stmt in cg.node.body:
            if not isinstance(stmt, _FUNCS):
                continue
            if stmt.name in ("__init__", "__post_init__") \
                    or stmt.name.endswith("_locked"):
                continue
            walk(stmt.name, stmt.body, set(), set(), set())

        for (method, label, lock_id), sites in sorted(unheld.items()):
            calls = callsites.get(method, [])
            if calls and all(lock_id in held for held in calls):
                continue   # every same-class caller carries the lock
            for line, lock_expr in sites:
                self.findings.append(Finding(
                    info.path, line, "L119",
                    f"access to '{label}' (guarded by '{lock_id}') "
                    f"without the owning lock held — wrap in "
                    f"'with {lock_expr}:', rename the method "
                    f"'*_locked' so L104 polices its callers, or "
                    f"waive with '# race: <reason>'"))

    # -- L120: thread-crossing classes need declarations ---------------

    def _check_class_l120(self, classname: str, cg: _ClassGuards) -> None:
        if not cg.spawns_threads:
            return
        info = cg.info
        # attr -> first mutation line outside __init__
        mutated: Dict[str, int] = {}
        for stmt in cg.node.body:
            if not isinstance(stmt, _FUNCS) \
                    or stmt.name in ("__init__", "__post_init__"):
                continue
            for sub in ast.walk(stmt):
                for tgt in _assign_targets(sub):
                    attr = _self_attr(tgt)
                    if attr is not None and attr not in mutated:
                        mutated[attr] = tgt.lineno
                if isinstance(sub, ast.Call):
                    fchain = _attr_chain(sub.func)
                    if fchain and len(fchain) == 3 \
                            and fchain[0] == "self" \
                            and fchain[-1] in _MUTATING_METHODS \
                            and fchain[1] not in mutated:
                        mutated[fchain[1]] = sub.lineno
        for attr, line in sorted(mutated.items(), key=lambda kv: kv[1]):
            if attr in cg.decls or _SYNCISH.search(attr):
                continue
            self.findings.append(Finding(
                info.path, line, "L120",
                f"'{classname}' spawns threads, so instances cross "
                f"thread contexts — mutable attribute '{attr}' needs "
                f"a guard declaration on its assignment "
                f"('# guarded-by: self.<lock>', '# guarded-by: "
                f"immutable', or '# guarded-by: external: <why>'), "
                f"or a '# race: <reason>' waiver here"))


def run_pass(files: Sequence[_FileInfo]) -> List[Finding]:
    """Engine hook: L119/L120 findings for the linted file set (waiver
    filtering happens in the caller, like every other rule)."""
    return OwnershipPass(files).run()


# ----------------------------------------------------------------------
# runtime consumers: the declared guard map as data
# ----------------------------------------------------------------------

def declared_runtime_guards(
        root: Path) -> Dict[str, Dict[str, GuardDecl]]:
    """classname -> {attr -> GuardDecl} parsed from the tree under
    ``root`` — the static guard map locks.py cross-checks at runtime
    (AGAC_RACE_DETECT) and hack/guard_infer.py diffs proposals
    against.  Parse errors are skipped: the lint gate owns syntax."""
    out: Dict[str, Dict[str, GuardDecl]] = {}
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        try:
            source = path.read_text()
            tree = ast.parse(source, filename=str(path))
        except (OSError, SyntaxError):
            continue
        info = _FileInfo(path, tree, source)
        op = OwnershipPass([info])
        op.collect()
        for classname, cg in op.classes.items():
            if cg.decls:
                out.setdefault(classname, {}).update(cg.decls)
    return out
