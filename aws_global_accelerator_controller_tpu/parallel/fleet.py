"""Fleet-scale batch reconciliation planning on the device mesh.

Scales the EndpointGroupBinding controller's per-object work to fleets:
for F bindings at once, compute (a) endpoint membership diffs
(desired vs current, ops.diff) and (b) weight allocations from endpoint
telemetry (ops.weights), in ONE sharded XLA program.

Sharding: bindings shard over the mesh's 'data' axis inside a
``shard_map``; fleet-wide statistics (endpoints to add/remove, mean
weight entropy) reduce with explicit ``psum`` collectives over ICI --
the only cross-shard traffic; the per-binding planning itself is
embarrassingly parallel.

Host integration: ``FleetPlan.for_bindings`` hashes ARN strings to int32
ids (ops.diff.hash_ids) and pads to the static [F, E] shape so the
compiled program is reused across reconcile rounds (no data-dependent
shapes, XLA-friendly).

Resident-state plumbing (ISSUE 16): :class:`DeviceGridRing`
double-buffers the device-resident fleet grids so the incremental
planner (parallel/fleet_plan.py ``ResidentFleetPlanner``) can build
wave N+1's refreshed state while wave N's intent flush is still
reading the buffer it planned from, and :func:`make_row_splice` picks
the row-splice mechanism per rung (jnp scatter everywhere; on the
pallas-tpu rung a double-buffered async-copy DMA kernel streams the
dirty rows into the resident grid — the SNIPPETS.md pattern).
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import RUNG_TPU, registry
from ..compat.jaxshim import shard_map

from ..ops.diff import EMPTY, membership_diff
from ..ops.weights import plan_weights

# ---------------------------------------------------------------------------
# device-side program
# ---------------------------------------------------------------------------


def _plan_block(desired, current, scores, mask):
    """Per-shard planning: diffs + weights + local stats."""
    to_add, to_remove = membership_diff(desired, current)
    weights = plan_weights(scores, mask)
    stats = jnp.array([
        jnp.sum(to_add), jnp.sum(to_remove),
        jnp.sum(mask),
    ], dtype=jnp.float32)
    return to_add, to_remove, weights, stats


def make_fleet_planner(mesh: Mesh):
    """Compile the sharded fleet planner for a mesh.

    Returns fn(desired [F,E] int32, current [F,E] int32,
               scores [F,E] f32, mask [F,E] bool) ->
      (to_add [F,E] bool, to_remove [F,E] bool, weights [F,E] int32,
       fleet_stats [3] f32 replicated)
    where fleet_stats = (total adds, total removes, total live endpoints)
    psum-reduced across the 'data' axis.
    """
    axes = P("data", None)

    @partial(shard_map, mesh=mesh,
             in_specs=(axes, axes, axes, axes),
             out_specs=(axes, axes, axes, P()))
    def planner(desired, current, scores, mask):
        to_add, to_remove, weights, stats = _plan_block(
            desired, current, scores, mask)
        # the single collective: fleet-wide totals ride ICI
        stats = jax.lax.psum(stats, axis_name="data")
        # 'model' axis (if >1) holds replicas of the same shard; results
        # are identical so no reduction is needed there for correctness,
        # but stats were psum'd only over 'data' by construction.
        return to_add, to_remove, weights, stats

    return jax.jit(planner)


# ---------------------------------------------------------------------------
# host-side integration
# ---------------------------------------------------------------------------


@dataclass
class BindingPlan:
    to_add: List[str]
    to_remove: List[str]
    weights: Dict[str, int]


class FleetPlanner:
    """Host wrapper: strings in, per-binding plans out.

    ``endpoints_cap`` fixes E (pad width); fleets larger than the device
    count's granularity pad F up to a multiple of the data axis.
    """

    def __init__(self, mesh: Mesh, endpoints_cap: int = 32):
        self.mesh = mesh
        self.endpoints_cap = endpoints_cap
        self.data_axis = mesh.shape["data"]
        self._fn = make_fleet_planner(mesh)

    def _encode(self, per_binding_ids: Sequence[Sequence[str]],
                fill=int(EMPTY)) -> Tuple[jnp.ndarray, List[List[str]]]:
        import zlib

        F = len(per_binding_ids)
        Fp = -(-max(F, 1) // self.data_axis) * self.data_axis
        host = [[fill] * self.endpoints_cap for _ in range(Fp)]
        rows: List[List[str]] = []
        for i, ids in enumerate(per_binding_ids):
            ids = list(ids)
            if len(ids) > self.endpoints_cap:
                raise ValueError(
                    f"binding {i} has {len(ids)} endpoints, exceeding "
                    f"endpoints_cap={self.endpoints_cap}; raise the cap "
                    "(silent truncation would strand endpoints)")
            rows.append(ids)
            for j, s in enumerate(ids):
                # inline 31-bit CRC (ops.diff.hash_ids semantics) without
                # per-row device round trips
                host[i][j] = zlib.crc32(s.encode()) & 0x7FFFFFFF
        return jnp.asarray(host, dtype=jnp.int32), rows

    def plan(self, desired: Sequence[Sequence[str]],
             current: Sequence[Sequence[str]],
             scores: Sequence[Sequence[float]]) -> Tuple[List[BindingPlan],
                                                         Dict[str, float]]:
        """desired/current: per-binding ARN lists; scores: per-desired-slot
        endpoint scores (same ragged shape as desired)."""
        F = len(desired)
        d_arr, d_rows = self._encode(desired)
        c_arr, c_rows = self._encode(current)
        Fp, E = d_arr.shape
        s_host = [[0.0] * E for _ in range(Fp)]
        m_host = [[False] * E for _ in range(Fp)]
        for i, row in enumerate(scores):
            for j, s in enumerate(list(row)[:E]):
                s_host[i][j] = float(s)
                m_host[i][j] = True
        s_arr = jnp.asarray(s_host, dtype=jnp.float32)
        m_arr = jnp.asarray(m_host)

        for i, row in enumerate(desired):
            if len(list(row)) != len(list(scores[i])):
                raise ValueError(
                    f"binding {i}: scores must align with desired ids")
        shard = NamedSharding(self.mesh, P("data", None))
        d_arr = jax.device_put(d_arr, shard)
        c_arr = jax.device_put(c_arr, shard)
        s_arr = jax.device_put(s_arr, shard)
        m_arr = jax.device_put(m_arr, shard)

        to_add, to_remove, weights, stats = self._fn(d_arr, c_arr, s_arr,
                                                     m_arr)
        to_add = jax.device_get(to_add)
        to_remove = jax.device_get(to_remove)
        weights = jax.device_get(weights)
        stats = jax.device_get(stats)

        plans = []
        for i in range(F):
            adds = [arn for j, arn in enumerate(d_rows[i]) if to_add[i][j]]
            removes = [arn for j, arn in enumerate(c_rows[i])
                       if to_remove[i][j]]
            w = {arn: int(weights[i][j]) for j, arn in enumerate(d_rows[i])}
            plans.append(BindingPlan(adds, removes, w))
        fleet_stats = {"adds": float(stats[0]), "removes": float(stats[1]),
                       "live_endpoints": float(stats[2])}
        return plans, fleet_stats


# ---------------------------------------------------------------------------
# resident device state: double-buffer ring + rung-aware row splice
# ---------------------------------------------------------------------------


class DeviceGridRing:
    """Double-buffered device residency for the fleet grids.

    The incremental planner's overlap hinges on a hand-off rule: the
    buffer wave N planned from must stay LIVE until wave N's intent
    flush has drained through the coalescer — the flush decodes from
    host copies, but the next wave's device pass reads/writes the
    *other* buffer, so an in-flight ``device_get`` or a donated-buffer
    reuse can never race the flush.  Concretely:

    - :meth:`advance` installs the new front (wave N+1's arrays) and
      parks the previous front as *retired* — still referenced, so XLA
      cannot recycle its memory;
    - :meth:`release_retired` is the flush-completion edge (the
      pipeline calls it when wave N's flush closes), dropping the
      retired buffer reference.

    Steady-state memory is therefore two generations of the resident
    grids (front + retired), the classic double buffer.
    """

    def __init__(self):
        # guarded-by: external: pipeline-serialized — install/
        # retire run on the submit edge, release on the flush-
        # completion edge, never concurrently (see class docstring)
        self._front: Optional[Tuple] = None
        # guarded-by: external: pipeline-serialized, as _front
        self._retired: Optional[Tuple] = None

    @property
    def front(self) -> Optional[Tuple]:
        return self._front

    def reset(self, arrays: Tuple) -> Tuple:
        """Full (re-)upload: capacity growth or first wave.  Any
        retired buffer keeps its reference — the previous flush may
        still be open."""
        self._front = tuple(jax.device_put(a) for a in arrays)
        return self._front

    def advance(self, arrays: Tuple) -> Tuple:
        """Install wave N+1's refreshed grids; wave N's buffer retires
        but stays referenced until :meth:`release_retired`."""
        self._retired = self._front
        self._front = tuple(arrays)
        return self._front

    def release_retired(self) -> None:
        self._retired = None

    def drop(self) -> None:
        """Invalidate residency outright (shape change): both buffers
        go; the next wave must :meth:`reset`."""
        self._front = None
        self._retired = None


def _dma_row_splice(K: int, E: int, rows_total: int):
    """Pallas double-buffered async-copy splice: stream ``K`` dirty
    rows ``[K, E]`` into a resident ``[rows_total, E]`` grid at
    per-row destinations ``lin [K]`` (SMEM scalars).

    The guide's two-semaphore pipeline: start row k+1's DMA before
    waiting on row k's, so every copy after the first overlaps the
    previous wait.  Only traced on the pallas-tpu rung with
    ``make_async_copy`` resolved (same documented limit as the stats
    ring — everywhere else the jnp scatter path below is the splice).
    """
    from ..compat import jaxshim

    def kernel(lin_ref, rows_ref, out_ref, sem):
        def copy_op(k, slot):
            return jaxshim.make_async_copy(
                rows_ref.at[k], out_ref.at[lin_ref[k]], sem.at[slot])

        copy_op(0, 0).start()

        def body(k, carry):
            jaxshim.when(k + 1 < K)(
                lambda: copy_op(k + 1, (k + 1) % 2).start())
            copy_op(k, k % 2).wait()
            return carry

        jax.lax.fori_loop(0, K, body, 0)

    def splice(dst, lin, rows):
        return jaxshim.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((rows_total, E), dst.dtype),
            in_specs=[
                jaxshim.block_spec(memory_space=jaxshim.SMEM),
                jaxshim.block_spec(memory_space=jaxshim.ANY),
            ],
            out_specs=jaxshim.block_spec(memory_space=jaxshim.ANY),
            scratch_shapes=[jaxshim.SemaphoreType.DMA((2,))],
            input_output_aliases={2: 0},
        )(lin, rows, dst)

    return splice


def make_row_splice(rung: str):
    """Rung-dispatched splice ``(dst, ks, kg, rows) -> dst'`` writing
    ``rows`` at positions ``(ks[i], kg[i])`` of a ``[S, cap, ...]``
    resident grid.

    jnp scatter is the universal path (and the oracle semantics).  On
    the pallas-tpu rung with async-copy support, full endpoint rows go
    through the DMA pipeline above — per-group scalar planes (2-D
    dst) always scatter; a width-E DMA per scalar would be all
    descriptor overhead.
    """
    from ..compat import jaxshim

    # _Missing shims are falsy — an unresolved make_async_copy simply
    # keeps the scatter path, same degrade rule as the stats ring
    use_dma = (rung == RUNG_TPU and registry.supports("pallas_tpu")
               and bool(jaxshim.make_async_copy))

    def scatter(dst, ks, kg, rows):
        return dst.at[ks, kg].set(rows)

    if not use_dma:
        return scatter

    def splice(dst, ks, kg, rows):
        if dst.ndim == 2:                      # per-group scalar plane
            return scatter(dst, ks, kg, rows)
        S, cap, E = dst.shape
        K = rows.shape[0]
        lin = (ks * cap + kg).astype(jnp.int32)
        flat = _dma_row_splice(K, E, S * cap)(
            dst.reshape(S * cap, E), lin, rows)
        return flat.reshape(S, cap, E)

    return splice
