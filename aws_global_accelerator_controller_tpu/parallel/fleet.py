"""Fleet-scale batch reconciliation planning on the device mesh.

Scales the EndpointGroupBinding controller's per-object work to fleets:
for F bindings at once, compute (a) endpoint membership diffs
(desired vs current, ops.diff) and (b) weight allocations from endpoint
telemetry (ops.weights), in ONE sharded XLA program.

Sharding: bindings shard over the mesh's 'data' axis inside a
``shard_map``; fleet-wide statistics (endpoints to add/remove, mean
weight entropy) reduce with explicit ``psum`` collectives over ICI --
the only cross-shard traffic; the per-binding planning itself is
embarrassingly parallel.

Host integration: ``FleetPlan.for_bindings`` hashes ARN strings to int32
ids (ops.diff.hash_ids) and pads to the static [F, E] shape so the
compiled program is reused across reconcile rounds (no data-dependent
shapes, XLA-friendly).
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat.jaxshim import shard_map

from ..ops.diff import EMPTY, membership_diff
from ..ops.weights import plan_weights

# ---------------------------------------------------------------------------
# device-side program
# ---------------------------------------------------------------------------


def _plan_block(desired, current, scores, mask):
    """Per-shard planning: diffs + weights + local stats."""
    to_add, to_remove = membership_diff(desired, current)
    weights = plan_weights(scores, mask)
    stats = jnp.array([
        jnp.sum(to_add), jnp.sum(to_remove),
        jnp.sum(mask),
    ], dtype=jnp.float32)
    return to_add, to_remove, weights, stats


def make_fleet_planner(mesh: Mesh):
    """Compile the sharded fleet planner for a mesh.

    Returns fn(desired [F,E] int32, current [F,E] int32,
               scores [F,E] f32, mask [F,E] bool) ->
      (to_add [F,E] bool, to_remove [F,E] bool, weights [F,E] int32,
       fleet_stats [3] f32 replicated)
    where fleet_stats = (total adds, total removes, total live endpoints)
    psum-reduced across the 'data' axis.
    """
    axes = P("data", None)

    @partial(shard_map, mesh=mesh,
             in_specs=(axes, axes, axes, axes),
             out_specs=(axes, axes, axes, P()))
    def planner(desired, current, scores, mask):
        to_add, to_remove, weights, stats = _plan_block(
            desired, current, scores, mask)
        # the single collective: fleet-wide totals ride ICI
        stats = jax.lax.psum(stats, axis_name="data")
        # 'model' axis (if >1) holds replicas of the same shard; results
        # are identical so no reduction is needed there for correctness,
        # but stats were psum'd only over 'data' by construction.
        return to_add, to_remove, weights, stats

    return jax.jit(planner)


# ---------------------------------------------------------------------------
# host-side integration
# ---------------------------------------------------------------------------


@dataclass
class BindingPlan:
    to_add: List[str]
    to_remove: List[str]
    weights: Dict[str, int]


class FleetPlanner:
    """Host wrapper: strings in, per-binding plans out.

    ``endpoints_cap`` fixes E (pad width); fleets larger than the device
    count's granularity pad F up to a multiple of the data axis.
    """

    def __init__(self, mesh: Mesh, endpoints_cap: int = 32):
        self.mesh = mesh
        self.endpoints_cap = endpoints_cap
        self.data_axis = mesh.shape["data"]
        self._fn = make_fleet_planner(mesh)

    def _encode(self, per_binding_ids: Sequence[Sequence[str]],
                fill=int(EMPTY)) -> Tuple[jnp.ndarray, List[List[str]]]:
        import zlib

        F = len(per_binding_ids)
        Fp = -(-max(F, 1) // self.data_axis) * self.data_axis
        host = [[fill] * self.endpoints_cap for _ in range(Fp)]
        rows: List[List[str]] = []
        for i, ids in enumerate(per_binding_ids):
            ids = list(ids)
            if len(ids) > self.endpoints_cap:
                raise ValueError(
                    f"binding {i} has {len(ids)} endpoints, exceeding "
                    f"endpoints_cap={self.endpoints_cap}; raise the cap "
                    "(silent truncation would strand endpoints)")
            rows.append(ids)
            for j, s in enumerate(ids):
                # inline 31-bit CRC (ops.diff.hash_ids semantics) without
                # per-row device round trips
                host[i][j] = zlib.crc32(s.encode()) & 0x7FFFFFFF
        return jnp.asarray(host, dtype=jnp.int32), rows

    def plan(self, desired: Sequence[Sequence[str]],
             current: Sequence[Sequence[str]],
             scores: Sequence[Sequence[float]]) -> Tuple[List[BindingPlan],
                                                         Dict[str, float]]:
        """desired/current: per-binding ARN lists; scores: per-desired-slot
        endpoint scores (same ragged shape as desired)."""
        F = len(desired)
        d_arr, d_rows = self._encode(desired)
        c_arr, c_rows = self._encode(current)
        Fp, E = d_arr.shape
        s_host = [[0.0] * E for _ in range(Fp)]
        m_host = [[False] * E for _ in range(Fp)]
        for i, row in enumerate(scores):
            for j, s in enumerate(list(row)[:E]):
                s_host[i][j] = float(s)
                m_host[i][j] = True
        s_arr = jnp.asarray(s_host, dtype=jnp.float32)
        m_arr = jnp.asarray(m_host)

        for i, row in enumerate(desired):
            if len(list(row)) != len(list(scores[i])):
                raise ValueError(
                    f"binding {i}: scores must align with desired ids")
        shard = NamedSharding(self.mesh, P("data", None))
        d_arr = jax.device_put(d_arr, shard)
        c_arr = jax.device_put(c_arr, shard)
        s_arr = jax.device_put(s_arr, shard)
        m_arr = jax.device_put(m_arr, shard)

        to_add, to_remove, weights, stats = self._fn(d_arr, c_arr, s_arr,
                                                     m_arr)
        to_add = jax.device_get(to_add)
        to_remove = jax.device_get(to_remove)
        weights = jax.device_get(weights)
        stats = jax.device_get(stats)

        plans = []
        for i in range(F):
            adds = [arn for j, arn in enumerate(d_rows[i]) if to_add[i][j]]
            removes = [arn for j, arn in enumerate(c_rows[i])
                       if to_remove[i][j]]
            w = {arn: int(weights[i][j]) for j, arn in enumerate(d_rows[i])}
            plans.append(BindingPlan(adds, removes, w))
        fleet_stats = {"adds": float(stats[0]), "removes": float(stats[1]),
                       "live_endpoints": float(stats[2])}
        return plans, fleet_stats
