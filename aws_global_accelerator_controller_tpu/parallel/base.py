"""Shared surface for the snapshot-batch sharded planners.

``SnapshotPlannerMixin`` carries the shard_params/shard_batch/forward/
train_step plumbing that ``ShardedTrafficPlanner``, ``ShardedMoEPlanner``
and ``ShardedPipelinePlanner`` would otherwise copy-paste; a subclass
sets ``param_shardings`` (dict), ``batch_shardings`` (Batch of
shardings), ``_forward`` and ``_step`` in its ``__init__``.  The
temporal planner keeps its own methods (its data is a (window, batch)
pair and its params share one replicated sharding).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..models.traffic import Batch


class SnapshotPlannerMixin:
    param_shardings: dict
    batch_shardings: Batch

    def shard_params(self, params) -> dict:
        # jnp.array(copy=True) forces distinct storage: device_put can
        # alias the source buffer, and train_step DONATES params —
        # without the copy, donating the sharded handle would delete
        # the caller's original too.  device_put(..., may_alias=False)
        # is NOT sufficient: on the host-platform mesh the donated
        # output still deletes the source (verified empirically), so
        # the copy must happen before placement.
        return {k: jax.device_put(jnp.array(v, copy=True),
                                  self.param_shardings[k])
                for k, v in params.items()}

    def shard_batch(self, batch: Batch) -> Batch:
        return Batch(*[jax.device_put(v, s)
                       for v, s in zip(batch, self.batch_shardings)])

    def forward(self, params, features, mask):
        return self._forward(params, features, mask)

    def train_step(self, params, opt_state,
                   batch: Batch) -> Tuple[dict, object, jax.Array]:
        return self._step(params, opt_state, batch)
