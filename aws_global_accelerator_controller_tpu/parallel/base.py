"""Shared surface for the snapshot-batch sharded planners.

``SnapshotPlannerMixin`` carries the shard_params/shard_batch/forward/
train_step plumbing that ``ShardedTrafficPlanner``, ``ShardedMoEPlanner``
and ``ShardedPipelinePlanner`` would otherwise copy-paste; a subclass
sets ``param_shardings`` (dict), ``batch_shardings`` (Batch of
shardings), ``_forward`` and ``_step`` in its ``__init__``.  The
temporal planner keeps its own methods (its data is a (window, batch)
pair and its params share one replicated sharding).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.traffic import Batch


def opt_state_shardings(model, param_shardings: dict, mesh):
    """Per-leaf NamedShardings for the model's optimizer state.

    The donating train-step jits used to leave the opt_state's in/out
    shardings unconstrained; the installed jax crashes inside XLA
    (aliased input/output size mismatch) when GSPMD then picks an
    output layout different from the donated input's.  Deriving the
    shardings structurally pins both sides: adam's mu/nu mirror the
    param dict, so a state leaf whose tree path ends at a param key
    (and matches its shape) rides that param's sharding; everything
    else — step counts, flat_adam's raveled vectors — replicates.
    """
    rep = NamedSharding(mesh, P())
    p_abs = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
    opt_abs = jax.eval_shape(model.init_opt_state, p_abs)

    def place(path, leaf):
        for entry in reversed(path):
            key = getattr(entry, "key", None)
            if key in param_shardings:
                if tuple(leaf.shape) == tuple(p_abs[key].shape):
                    return param_shardings[key]
                break
        return rep

    return jax.tree_util.tree_map_with_path(place, opt_abs)


class SnapshotPlannerMixin:
    param_shardings: dict
    batch_shardings: Batch

    def shard_params(self, params) -> dict:
        # jnp.array(copy=True) forces distinct storage: device_put can
        # alias the source buffer, and train_step DONATES params —
        # without the copy, donating the sharded handle would delete
        # the caller's original too.  device_put(..., may_alias=False)
        # is NOT sufficient: on the host-platform mesh the donated
        # output still deletes the source (verified empirically), so
        # the copy must happen before placement.
        return {k: jax.device_put(jnp.array(v, copy=True),
                                  self.param_shardings[k])
                for k, v in params.items()}

    def shard_batch(self, batch: Batch) -> Batch:
        return Batch(*[jax.device_put(v, s)
                       for v, s in zip(batch, self.batch_shardings)])

    def forward(self, params, features, mask):
        return self._forward(params, features, mask)

    def train_step(self, params, opt_state,
                   batch: Batch) -> Tuple[dict, object, jax.Array]:
        return self._step(params, opt_state, batch)
