"""Expert-parallel endpoint-group scoring via all_to_all dispatch.

Global Accelerator endpoint groups are regional; give each device one
region "expert" (its own scoring parameters — a per-region affine on the
telemetry features) and route every group to its region's expert with the
MoE dispatch pattern: bucket locally by destination, exchange buckets with
one ``jax.lax.all_to_all``, apply the local expert, exchange back, and
scatter into original order.  All shapes static (capacity = local group
count, so no overflow is possible); the only cross-device traffic is the
two all_to_alls over ICI.

No reference analogue (SURVEY.md §2: expert parallelism ABSENT upstream).
"""
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..compat.jaxshim import shard_map

ExpertParams = Dict[str, jax.Array]


def init_expert_params(key: jax.Array, n_experts: int,
                       feature_dim: int) -> ExpertParams:
    """Per-region affine scoring params: score = (x*scale + bias).sum(-1)."""
    k1, k2 = jax.random.split(key)
    return {
        "scale": 1.0 + 0.1 * jax.random.normal(
            k1, (n_experts, feature_dim), dtype=jnp.float32),
        "bias": 0.1 * jax.random.normal(
            k2, (n_experts, feature_dim), dtype=jnp.float32),
    }


def expert_scores_reference(params: ExpertParams, features: jax.Array,
                            region: jax.Array) -> jax.Array:
    """Unsharded oracle: apply each group's regional expert densely.

    features [G, E, F] f32, region [G] int32 -> scores [G, E] f32.
    """
    scale = params["scale"][region]  # [G, F]
    bias = params["bias"][region]
    x = features * scale[:, None, :] + bias[:, None, :]
    return jnp.sum(x, axis=-1)


def make_expert_planner(mesh: Mesh, axis: str = "expert"):
    """Compile fn(features [G, E, F], region [G] int32) -> scores [G, E].

    ``G`` is sharded over ``axis``; expert params are sharded one region
    per device along the same axis.  Equal to
    :func:`expert_scores_reference` for region ids < mesh.shape[axis].
    """
    n = mesh.shape[axis]

    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis, None), P(axis, None, None), P(axis)),
             out_specs=P(axis, None),
             check_vma=False)
    def planner(expert_param_block, x_local, region_local):
        # expert_param_block [1, 2F]: this device's (scale|bias)
        G_l, E, F = x_local.shape
        cap = G_l  # worst case: every local group routes to one expert

        # --- local bucketing by destination expert -------------------
        onehot = jax.nn.one_hot(region_local, n, dtype=jnp.int32)  # [G_l,n]
        slot = jnp.cumsum(onehot, axis=0)[jnp.arange(G_l),
                                          region_local] - 1  # [G_l]
        send = jnp.zeros((n, cap, E, F), x_local.dtype)
        send = send.at[region_local, slot].set(x_local)

        # --- exchange: send[d] -> device d ---------------------------
        recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                                  tiled=False)
        recv = recv.reshape(n, cap, E, F)  # [src, cap, E, F]

        # --- local expert ------------------------------------------------
        scale = expert_param_block[0, :F]
        bias = expert_param_block[0, F:]
        y = jnp.sum(recv * scale + bias, axis=-1)  # [src, cap, E]

        # --- exchange back + scatter to original order ---------------
        back = jax.lax.all_to_all(y[:, :, None], axis, split_axis=0,
                                  concat_axis=0, tiled=False)
        back = back.reshape(n, cap, E)  # [dst, cap, E]
        # no validity mask needed: capacity == G_l means every (dst, slot)
        # pair read here was written by this device's own scatter above
        return back[region_local, slot]  # [G_l, E]

    def fn(params: ExpertParams, features, region):
        packed = jnp.concatenate([params["scale"], params["bias"]], axis=-1)
        return planner(packed, features, region)

    return jax.jit(fn)
