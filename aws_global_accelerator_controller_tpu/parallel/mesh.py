"""Device mesh construction helpers."""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def _factor(n: int) -> Tuple[int, int]:
    """Split n into the most square (a, b) with a*b == n, a <= b."""
    best = (1, n)
    for a in range(1, int(np.sqrt(n)) + 1):
        if n % a == 0:
            best = (a, n // a)
    return best


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Sequence[str] = ("data", "model"),
              devices=None,
              axis_shapes: Optional[dict] = None) -> Mesh:
    """Build a 2-D ('data', 'model') mesh over the first n devices.

    The model axis gets the smaller factor (weights shard less than the
    batch); a prime or single device degenerates to (n, 1) cleanly.

    ``axis_shapes`` ({name: size, ...}, ordered) overrides both the
    axis names and the factorisation — for layouts where an axis size
    is semantic rather than a free split (e.g. one expert per device
    along an 'expert' axis).
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devices = devices[:n_devices]
    if axis_shapes:
        want = int(np.prod(list(axis_shapes.values())))
        if len(devices) < want:
            raise ValueError(
                f"axis_shapes {axis_shapes} needs {want} devices, have "
                f"{len(devices)}")
        grid = np.asarray(devices[:want]).reshape(
            tuple(axis_shapes.values()))
        return Mesh(grid, axis_names=tuple(axis_shapes))
    n = len(devices)
    model, data = _factor(n)
    grid = np.asarray(devices).reshape(data, model)
    return Mesh(grid, axis_names=tuple(axis_names))
