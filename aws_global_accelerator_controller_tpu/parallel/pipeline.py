"""Pipeline-parallel scoring: model stages across devices, microbatches
in flight.

Splits a deep residual scoring MLP layer-wise over the mesh's 'stage'
axis (one [H, H] block per device) and streams M microbatches through with
the GPipe schedule: at step t, stage s processes microbatch t-s and hands
its activations to stage s+1 via ``jax.lax.ppermute`` (neighbour hop over
ICI).  M + S - 1 steps fill and drain the pipe; everything is a
``lax.fori_loop`` with static shapes — no data-dependent Python control
flow under jit.

No reference analogue (SURVEY.md §2: pipeline parallelism ABSENT
upstream); this is how the compute track would scale a model too deep for
one chip.
"""
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..compat.jaxshim import shard_map

PipeParams = Dict[str, jax.Array]


def init_pipeline_params(key: jax.Array, n_stages: int, feature_dim: int,
                         hidden_dim: int) -> PipeParams:
    """w_in/w_out replicated; one residual [H, H] block per stage."""
    k1, k2, k3 = jax.random.split(key, 3)
    s = lambda fan_in: 1.0 / jnp.sqrt(fan_in)
    return {
        "w_in": jax.random.normal(k1, (feature_dim, hidden_dim),
                                  dtype=jnp.float32) * s(feature_dim),
        "stage_w": jax.random.normal(k2, (n_stages, hidden_dim, hidden_dim),
                                     dtype=jnp.float32) * s(hidden_dim),
        "stage_b": jnp.zeros((n_stages, hidden_dim), jnp.float32),
        "w_out": jax.random.normal(k3, (hidden_dim, 1),
                                   dtype=jnp.float32) * s(hidden_dim),
    }


def _stage_fn(h, w, b):
    """Residual block: h + relu(h @ w + b) — keeps activations well-scaled
    through arbitrarily many stages."""
    return h + jnp.maximum(h @ w + b, 0.0)


def pipeline_reference(params: PipeParams, x: jax.Array) -> jax.Array:
    """Unsharded oracle: [M, B, F] -> [M, B] scores."""
    h = x @ params["w_in"]
    for i in range(params["stage_w"].shape[0]):
        h = _stage_fn(h, params["stage_w"][i], params["stage_b"][i])
    return (h @ params["w_out"])[..., 0]


def make_pipeline(mesh: Mesh, n_microbatches: int, axis: str = "stage"):
    """Compile fn(params, x [M, B, F]) -> [M, B], equal to
    :func:`pipeline_reference` with n_stages == mesh.shape[axis]."""
    S = mesh.shape[axis]
    M = n_microbatches

    @partial(shard_map, mesh=mesh,
             in_specs=(P(), P(axis, None, None), P(axis, None), P(), P()),
             out_specs=P(),
             check_vma=False)
    def pipe(w_in, stage_w, stage_b, w_out, x):
        # stage_w [1, H, H]: this device's block
        idx = jax.lax.axis_index(axis)
        h_in = x @ w_in  # [M, B, H] (cheap; input layer replicated)
        B, H = h_in.shape[1], h_in.shape[2]
        perm = [(i, (i + 1) % S) for i in range(S)]
        last = S - 1

        def compute(t, recv, out):
            """One schedule step: apply this stage to microbatch t-idx,
            recording the result if this is the last stage."""
            m = t - idx  # microbatch this stage works on now
            valid = jnp.logical_and(m >= 0, m < M)
            mc = jnp.clip(m, 0, M - 1)
            inp = jnp.where(idx == 0,
                            jax.lax.dynamic_index_in_dim(
                                h_in, mc, axis=0, keepdims=False),
                            recv)
            h = _stage_fn(inp, stage_w[0], stage_b[0])
            keep = jnp.logical_and(valid, idx == last)
            prev = jax.lax.dynamic_index_in_dim(out, mc, axis=0,
                                                keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(keep, h, prev), mc, axis=0)
            return h, out

        def body(t, carry):
            recv, out = carry
            h, out = compute(t, recv, out)
            return jax.lax.ppermute(h, axis, perm), out

        out0 = jnp.zeros((M, B, H), h_in.dtype)
        recv0 = jnp.zeros((B, H), h_in.dtype)
        total = M + S - 1
        recv, out = jax.lax.fori_loop(0, total - 1, body, (recv0, out0))
        # drain step: the last stage records its final microbatch; no
        # further activation hop is needed
        _, out = compute(total - 1, recv, out)
        # only the last stage holds real outputs; psum replicates them
        out = jax.lax.psum(
            jnp.where(idx == last, out, jnp.zeros_like(out)), axis)
        return (out @ w_out)[..., 0]

    def fn(params: PipeParams, x):
        return pipe(params["w_in"], params["stage_w"], params["stage_b"],
                    params["w_out"], x)

    return jax.jit(fn)
