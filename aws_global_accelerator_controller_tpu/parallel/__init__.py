"""Multi-chip parallelism: mesh construction + sharded planner/training.

The reference has no distributed compute (SURVEY.md §2: DP/TP/PP/SP/EP all
ABSENT; its only multi-replica story is leader election).  This package is
the TPU-native scale-out path for the compute track: jax.sharding Meshes
with data x model axes, NamedSharding-annotated pjit programs, and XLA
collectives over ICI inserted by the compiler.
"""
from .distributed import (
    initialize_multihost,
    make_hybrid_mesh,
)
from .experts import (
    expert_scores_reference,
    init_expert_params,
    make_expert_planner,
)
from .fleet import FleetPlanner
from .fleet_plan import (
    FleetPlanResult,
    WholeFleetPlanner,
    make_fleet_pass,
)
from .mesh import make_mesh
from .moe import ShardedMoEPlanner, moe_param_specs
from .pipeline import (
    init_pipeline_params,
    make_pipeline,
    pipeline_reference,
)
from .pipeline_train import (
    ShardedPipelinePlanner,
    deep_param_specs,
)
from .plan import (
    ShardedTemporalPlanner,
    ShardedTrafficPlanner,
)
from .ring import ewma_reference, make_mesh_1d, make_ring_ewma
from .ring_attention import (
    attention_reference,
    inverse_zigzag_indices,
    make_last_attention,
    make_ring_attention,
    zigzag_indices,
)
