"""Multi-chip parallelism: mesh construction + sharded planner/training.

The reference has no distributed compute (SURVEY.md §2: DP/TP/PP/SP/EP all
ABSENT; its only multi-replica story is leader election).  This package is
the TPU-native scale-out path for the compute track: jax.sharding Meshes
with data x model axes, NamedSharding-annotated pjit programs, and XLA
collectives over ICI inserted by the compiler.
"""
from .distributed import (  # noqa: F401
    initialize_multihost,
    make_hybrid_mesh,
)
from .experts import (  # noqa: F401
    expert_scores_reference,
    init_expert_params,
    make_expert_planner,
)
from .fleet import FleetPlanner  # noqa: F401
from .mesh import make_mesh  # noqa: F401
from .moe import ShardedMoEPlanner, moe_param_specs  # noqa: F401
from .pipeline import (  # noqa: F401
    init_pipeline_params,
    make_pipeline,
    pipeline_reference,
)
from .pipeline_train import (  # noqa: F401
    ShardedPipelinePlanner,
    deep_param_specs,
)
from .plan import (  # noqa: F401
    ShardedTemporalPlanner,
    ShardedTrafficPlanner,
)
from .ring import ewma_reference, make_mesh_1d, make_ring_ewma  # noqa: F401
from .ring_attention import (  # noqa: F401
    attention_reference,
    inverse_zigzag_indices,
    make_last_attention,
    make_ring_attention,
    zigzag_indices,
)
