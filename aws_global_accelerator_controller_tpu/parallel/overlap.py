"""Plan/flush overlap pipeline for the incremental resident planner.

FlexLink's premise (PAPERS.md): use every resource concurrently.  On
the steady-state wave path the resources are planner compute (device),
host↔device row splices, and the provider wire (the coalescer flush) —
PR 11's loop serialized them: plan wave N, flush wave N, plan wave
N+1.  This module pipelines them: a dedicated flusher thread drains
wave N's mutation intents through the coalescer while the main thread
packs and plans wave N+1 against the OTHER device buffer of the
:class:`~.fleet.DeviceGridRing` double buffer (the
``ResidentFleetPlanner`` advanced the ring when wave N's pass
returned; the retired buffer is released only at flush completion —
the hand-off rule).

Stage-ledger accounting makes the overlap observable rather than
asserted: every mutated key carries a :class:`~..tracing.TraceContext`
through the canonical hop sequence (``queued → claimed → planned →
inflight → flushed → converged``), so wave N's coalesced/inflight
window and wave N+1's queued/planned window come from the SAME
monotonic hop stamps the PR-12 convergence ledger aggregates — the
bench leg reports both the per-stage percentiles and the measured
window intersection (:meth:`PlanFlushPipeline.overlap_seconds`).

Thread model: ONE submitting thread (the wave driver) and ONE flusher;
the depth-1 queue bounds pipelining at the double buffer's depth.  The
queue/thread come from simulation/clock.py shims, so the pipeline runs
identically under a VirtualClock (where flush latency is charged in
virtual time) and the real clock (where the overlap windows are
physically concurrent) — note that under a VirtualClock pure compute
does not advance time, so overlap WINDOWS are only meaningful on the
real clock.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..simulation import clock as simclock
from ..tracing import default_ledger, new_context

from .fleet_plan import ResidentFleetPlanner, WaveResult


@dataclass
class WaveWindows:
    """One wave's measured stage windows (monotonic seconds)."""

    wave: int
    plan: Tuple[float, float]
    flush: Optional[Tuple[float, float]] = None


class PlanFlushPipeline:
    """Overlap a wave's intent flush with the next wave's plan.

    ``flush(wave)`` is the drain edge — whatever pushes the wave's
    :class:`~.fleet_plan.WaveResult` intents through the coalescer to
    the provider (or charges simulated wire latency in a bench).  It
    runs on the flusher thread; exceptions are captured and re-raised
    at the next submit/close (fail the driver, not the daemon).
    """

    def __init__(self, planner: ResidentFleetPlanner,
                 flush: Callable[[WaveResult], None],
                 controller: str = "fleet_sweep", ledger=None):
        self.planner = planner
        self._flush = flush
        self._controller = controller
        self._ledger = ledger if ledger is not None else default_ledger
        # guarded-by: external: the driver thread owns the list;
        # the flusher only fills each window's flush tuple
        self.windows: List[WaveWindows] = []
        self._q = simclock.make_queue(maxsize=1)
        # guarded-by: external: single-slot handoff — the flusher
        # stores, the driver consumes at the next submit/close
        self._err: Optional[BaseException] = None
        self._closed = False  # guarded-by: external: driver thread only
        self._thread = simclock.start_thread(
            self._drain, name="plan-flush-drain")

    # -- driver edge ---------------------------------------------------

    def submit_wave(self, mutated_keys: Sequence[str] = ()
                    ) -> WaveResult:
        """Plan the next wave and hand its intents to the flusher.

        ``mutated_keys`` are the keys this wave's mutations touched
        (already applied to the resident fleet by the caller); each
        gets a ledger trace carried through the full hop sequence.
        Blocks only when the flusher is a full wave behind — the
        double buffer's depth.
        """
        self._reraise()
        ctxs = []
        for k in mutated_keys:
            c = new_context("queued", record_span=False)
            if c is not None:
                ctxs.append((k, c))
        for _, c in ctxs:
            c.hop("claimed")
        p0 = simclock.monotonic()
        wave = self.planner.plan_wave()
        p1 = simclock.monotonic()
        for _, c in ctxs:
            c.hop("planned", now=p1)
            c.hop("inflight")
        win = WaveWindows(wave=len(self.windows), plan=(p0, p1))
        self.windows.append(win)
        self._q.put((wave, ctxs, win))
        return wave

    def close(self) -> None:
        """Drain outstanding flushes and stop the flusher."""
        if not self._closed:
            self._closed = True
            self._q.put(None)
            simclock.join_thread(self._thread, timeout=60.0)
        self._reraise()

    def __enter__(self) -> "PlanFlushPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _reraise(self) -> None:
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    # -- flusher edge --------------------------------------------------

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            wave, ctxs, win = item
            f0 = simclock.monotonic()
            try:
                self._flush(wave)
            except BaseException as e:  # surfaced at the driver's
                self._err = e           # next submit/close
            f1 = simclock.monotonic()
            win.flush = (f0, f1)
            for key, c in ctxs:
                c.hop("flushed", now=f1)
                c.hop("converged")
                self._ledger.record(self._controller, key, c)
            # the hand-off rule: wave N's retired device buffer is
            # only released once its flush has drained
            self.planner.flush_complete()

    # -- the observable ------------------------------------------------

    def overlap_seconds(self) -> float:
        """Total measured intersection of wave N's flush window with
        wave N+1's plan window — >0 means planning demonstrably ran
        while the previous flush was on the wire."""
        total = 0.0
        for prev, cur in zip(self.windows, self.windows[1:]):
            if prev.flush is None:
                continue
            lo = max(prev.flush[0], cur.plan[0])
            hi = min(prev.flush[1], cur.plan[1])
            total += max(0.0, hi - lo)
        return total

    def window_report(self) -> List[Dict[str, float]]:
        """Per-wave window edges for the bench record (monotonic,
        relative to the first wave's plan start)."""
        if not self.windows:
            return []
        t0 = self.windows[0].plan[0]
        out = []
        for w in self.windows:
            rec = {"wave": w.wave, "plan_start": w.plan[0] - t0,
                   "plan_end": w.plan[1] - t0}
            if w.flush is not None:
                rec["flush_start"] = w.flush[0] - t0
                rec["flush_end"] = w.flush[1] - t0
            out.append(rec)
        return out
