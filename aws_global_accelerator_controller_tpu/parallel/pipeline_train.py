"""GPipe training for the deep traffic model (stage-sharded pipeline).

``parallel.pipeline`` proves the GPipe schedule on a toy scorer; this
module trains the real ``models.deep.DeepTrafficModel`` end-to-end with
its residual stages sharded one-per-device along a 'stage' mesh axis.

The forward streams M microbatches through the stage ring: at schedule
step t, stage s applies its block to microbatch t-s and hands the
activations to stage s+1 with one ``jax.lax.ppermute`` neighbour hop
(ICI traffic only).  M + S - 1 steps fill and drain the pipe.  The loop
is a ``lax.scan`` with static trip count — which is what makes the
BACKWARD pipeline free: reverse-mode AD through the scan replays the
schedule in reverse, and each ppermute transposes to the opposite-
direction ppermute, so gradients stream stage S-1 -> 0 exactly like
activations streamed 0 -> S-1.  Nobody hand-writes a backward schedule;
XLA compiles the one autodiff derives.

Stage parameters live sharded (P('stage')) so each device's HBM holds
only its own block — the property that lets total depth scale with the
number of stages.  w_in/w_out are replicated (they are O(F*H), small);
their gradients psum over the stage axis via the shard_map transpose.

No reference analogue (SURVEY.md §2: PP ABSENT upstream).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat.jaxshim import shard_map

from ..models.common import masked_ce_loss
from ..models.deep import DeepTrafficModel, Params, stage_fn
from ..models.traffic import Batch
from ..ops.weights import plan_weights
from .base import SnapshotPlannerMixin, opt_state_shardings


def deep_param_specs(stage_axis: str = "stage") -> dict:
    return {
        "w_in": P(),
        "stage_w": P(stage_axis, None, None),
        "stage_b": P(stage_axis, None),
        "w_out": P(),
    }


class ShardedPipelinePlanner(SnapshotPlannerMixin):
    """pjit-compiled GPipe forward + train step.

    Requires ``model.n_stages == mesh.shape[stage_axis]`` (one residual
    block per device) and G divisible by ``n_microbatches``.

    ``data_axis`` composes data parallelism with the pipeline (dp x pp
    over a 2-D mesh, e.g. ``make_hybrid_mesh(dcn_axes=("data",),
    ici_axes=("stage",))`` — replicas across hosts, the stage ring on
    ICI): each data shard streams ITS slice of every microbatch through
    its own stage ring; stage params are replicated across ``data`` and
    their gradients all-reduce over it via the shard_map transpose —
    no hand-written cross-replica sync.
    """

    def __init__(self, model: DeepTrafficModel, mesh: Mesh,
                 n_microbatches: int = 4, stage_axis: str = "stage",
                 remat: bool = False, data_axis: "str | None" = None):
        if model.n_stages != mesh.shape[stage_axis]:
            raise ValueError(
                f"model has {model.n_stages} stages but the "
                f"'{stage_axis}' mesh axis has {mesh.shape[stage_axis]} "
                f"devices — pipeline layout is one stage per device")
        if data_axis is not None and data_axis not in mesh.shape:
            raise ValueError(
                f"mesh has no '{data_axis}' axis (axes: "
                f"{tuple(mesh.shape)})")
        self.model = model
        self.mesh = mesh
        self.n_microbatches = n_microbatches
        self.remat = remat
        self.data_axis = data_axis
        n_data = mesh.shape[data_axis] if data_axis else 1
        s = mesh.shape[stage_axis]
        m = n_microbatches
        # remat trades FLOPs for activation memory: the scan's backward
        # otherwise saves every schedule step's stage activations; with
        # jax.checkpoint around the stage block only its INPUT survives
        # to the backward, and the relu/matmul recompute on the fly —
        # the standard long-pipe memory lever (numerically identical,
        # same f32 ops replayed)
        stage = jax.checkpoint(stage_fn) if remat else stage_fn

        ps = {k: NamedSharding(mesh, spec)
              for k, spec in deep_param_specs(stage_axis).items()}
        rep = NamedSharding(mesh, P())
        # with a data axis, endpoint groups shard over it end-to-end:
        # batch in HBM, microbatch rows inside the pipe, and the [M, B]
        # result all carry the same 'data' placement (no resharding)
        feat_spec = (NamedSharding(mesh, P(data_axis, None, None))
                     if data_axis else rep)
        gm_spec = (NamedSharding(mesh, P(data_axis, None))
                   if data_axis else rep)
        bs = Batch(features=feat_spec, mask=gm_spec, target=gm_spec)
        x_spec = P(None, data_axis, None) if data_axis else P()
        out_spec = P(None, data_axis) if data_axis else P()

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P(stage_axis, None, None),
                           P(stage_axis, None), P(),
                           x_spec),
                 out_specs=out_spec,
                 check_vma=False)
        def pipe(w_in, stage_w, stage_b, w_out, x):
            # x [M, B, F] microbatched input (replicated); stage_w
            # [1, H, H] this device's block
            idx = jax.lax.axis_index(stage_axis)
            h_in = x @ w_in                      # [M, B, H]
            b_dim, h_dim = h_in.shape[1], h_in.shape[2]
            perm = [(i, (i + 1) % s) for i in range(s)]
            last = s - 1

            def compute(t, recv, out):
                mb = t - idx                     # this stage's microbatch
                valid = jnp.logical_and(mb >= 0, mb < m)
                mc = jnp.clip(mb, 0, m - 1)
                inp = jnp.where(
                    idx == 0,
                    jax.lax.dynamic_index_in_dim(h_in, mc, axis=0,
                                                 keepdims=False),
                    recv)
                h = stage(inp, stage_w[0], stage_b[0])
                keep = jnp.logical_and(valid, idx == last)
                prev = jax.lax.dynamic_index_in_dim(out, mc, axis=0,
                                                    keepdims=False)
                out = jax.lax.dynamic_update_index_in_dim(
                    out, jnp.where(keep, h, prev), mc, axis=0)
                return h, out

            def body(carry, t):
                recv, out = carry
                h, out = compute(t, recv, out)
                return (jax.lax.ppermute(h, stage_axis, perm), out), None

            out0 = jnp.zeros((m, b_dim, h_dim), h_in.dtype)
            recv0 = jnp.zeros((b_dim, h_dim), h_in.dtype)
            total = m + s - 1
            (recv, out), _ = jax.lax.scan(body, (recv0, out0),
                                          jnp.arange(total - 1))
            # drain: the last stage records its final microbatch
            _, out = compute(total - 1, recv, out)
            out = jax.lax.psum(
                jnp.where(idx == last, out, jnp.zeros_like(out)),
                stage_axis)
            return (out @ w_out)[..., 0]         # [M, B]

        def scores(params: Params, features):
            g, e, f = features.shape
            if g % m:
                raise ValueError(
                    f"groups ({g}) must be divisible by "
                    f"n_microbatches ({m})")
            if ((g // m) * e) % n_data:
                raise ValueError(
                    f"microbatch rows ({(g // m) * e}) must be "
                    f"divisible by the '{data_axis}' axis ({n_data})")
            # interleaved microbatching: group g -> (microbatch g % m,
            # row g // m).  A data shard's contiguous groups then form
            # ITS OWN B-slice of EVERY microbatch, so the G-sharded
            # batch maps onto pipe's P(None, data, None) spec with no
            # cross-replica movement (contiguous g -> whole-microbatch
            # assignment would force an all-to-all per step).  Which
            # groups share a microbatch is schedule-only — results are
            # bit-identical either way (the M-invariance test).
            x = (features.astype(jnp.float32)
                 .reshape(g // m, m, e, f).swapaxes(0, 1)
                 .reshape(m, (g // m) * e, f))
            out = pipe(params["w_in"], params["stage_w"],
                       params["stage_b"], params["w_out"], x)
            return out.reshape(m, g // m, e).swapaxes(0, 1).reshape(g, e)

        def loss_fn(params: Params, batch: Batch):
            return masked_ce_loss(scores(params, batch.features),
                                  batch.mask, batch.target)

        def step(params, opt_state, batch):
            # models/common.py owns the optimizer update; only the loss
            # (with its GPipe scores) is planner-specific
            return model.train_step_with(loss_fn, params, opt_state,
                                         batch)

        self._forward = jax.jit(
            lambda params, features, mask: plan_weights(
                scores(params, features), mask),
            in_shardings=(ps, bs.features, bs.mask),
            out_shardings=rep)
        opt_s = opt_state_shardings(model, ps, mesh)
        self._step = jax.jit(step, in_shardings=(ps, opt_s, bs),
                             out_shardings=(ps, opt_s, None),
                             donate_argnums=(0, 1))
        self.param_shardings = ps
        self.batch_shardings = bs

    def _check_groups(self, g: int) -> None:
        """Pre-jit divisibility checks: pjit's own in_shardings
        validation fires before the traced checks and reports an opaque
        sharding error — say what the constraint is directly."""
        if g % self.n_microbatches:
            raise ValueError(
                f"groups ({g}) must be divisible by n_microbatches "
                f"({self.n_microbatches})")
        n_data = self.mesh.shape[self.data_axis] if self.data_axis else 1
        if g % n_data:
            raise ValueError(
                f"groups ({g}) must be divisible by the "
                f"'{self.data_axis}' axis ({n_data})")

    def shard_batch(self, batch: Batch) -> Batch:
        self._check_groups(batch.features.shape[0])
        return SnapshotPlannerMixin.shard_batch(self, batch)

    def forward(self, params, features, mask):
        self._check_groups(features.shape[0])
        return SnapshotPlannerMixin.forward(self, params, features,
                                            mask)

    def train_step(self, params, opt_state, batch: Batch):
        self._check_groups(batch.features.shape[0])
        return SnapshotPlannerMixin.train_step(self, params, opt_state,
                                               batch)
