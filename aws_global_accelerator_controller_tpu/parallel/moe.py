"""Expert-parallel training for the MoE traffic model (data x expert mesh).

``parallel.experts`` proves the all_to_all dispatch pattern on a toy
per-region affine; this module is the real thing: the full
``models.moe.MoETrafficModel`` trained end-to-end with its experts
sharded one-per-device along an ``expert`` mesh axis and the batch
sharded over BOTH axes (every device holds groups AND one expert — the
standard 2D MoE layout).

Per training step, inside ``jax.shard_map``:

1. gate (replicated f32 matmul, computed outside the shard_map);
2. each device buckets its local groups by destination expert
   (static capacity = local group count, so overflow is impossible);
3. ONE ``jax.lax.all_to_all`` over the ``expert`` axis ships buckets to
   their experts (ICI traffic only within each data-axis row);
4. the local expert MLP runs as one [n*cap*E, F] MXU matmul stack;
5. a second all_to_all ships scores back; scatter restores group order.

Everything is differentiable: the all_to_alls transpose to all_to_alls,
the scatters to gathers, and the expert-parameter gradients psum over
the ``data`` axis automatically (shard_map inserts the reduction for
inputs replicated along an axis).  The gate's gradient flows through
the selected-probability scaling exactly as in the dense model, so
sharded and unsharded training follow the same trajectory.

No reference analogue (SURVEY.md §2: EP ABSENT upstream).
"""
from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.common import masked_ce_loss
from ..models.moe import MoETrafficModel, Params
from ..models.traffic import Batch
from ..ops.weights import plan_weights
from .base import SnapshotPlannerMixin


def moe_param_specs(expert_axis: str = "expert") -> dict:
    """Experts shard dim 0 over the expert axis; the gate replicates."""
    e = expert_axis
    return {
        "wg": P(),
        "w1": P(e, None, None),
        "b1": P(e, None),
        "w2": P(e, None, None),
        "b2": P(e, None),
    }


class ShardedMoEPlanner(SnapshotPlannerMixin):
    """pjit-compiled MoE forward + train step bound to a mesh.

    Requires ``model.n_experts == mesh.shape[expert_axis]`` (one expert
    per device along that axis) and G divisible by the full device
    count (the batch shards over every data axis plus the expert axis).
    ``data_axis`` accepts a single axis name or a sequence of them —
    e.g. ``("dcn_data", "data")`` to put a cross-host replica axis from
    ``make_hybrid_mesh`` outside the local data tile.
    """

    def __init__(self, model: MoETrafficModel, mesh: Mesh,
                 data_axis: "str | Sequence[str]" = "data",
                 expert_axis: str = "expert"):
        if model.n_experts != mesh.shape[expert_axis]:
            raise ValueError(
                f"model has {model.n_experts} experts but the "
                f"'{expert_axis}' mesh axis has "
                f"{mesh.shape[expert_axis]} devices — expert-parallel "
                f"layout is one expert per device")
        self.model = model
        self.mesh = mesh
        n = model.n_experts

        # data_axis may name several mesh axes (e.g. a DCN-outer
        # replica axis plus the local data tile from make_hybrid_mesh);
        # the batch dim shards over all of them plus the expert axis,
        # and the dispatch all_to_all stays on the expert axis only —
        # so expert traffic rides ICI while DCN carries just the
        # gradient all-reduce
        data_axes = ((data_axis,) if isinstance(data_axis, str)
                     else tuple(data_axis))
        both = data_axes + (expert_axis,)
        ps = {k: NamedSharding(mesh, s)
              for k, s in moe_param_specs(expert_axis).items()}
        bs = Batch(features=NamedSharding(mesh, P(both, None, None)),
                   mask=NamedSharding(mesh, P(both, None)),
                   target=NamedSharding(mesh, P(both, None)))
        out_s = NamedSharding(mesh, P(both, None))

        @partial(jax.shard_map, mesh=mesh,
                 in_specs=(P(expert_axis, None, None),
                           P(expert_axis, None),
                           P(expert_axis, None, None),
                           P(expert_axis, None),
                           P(both, None, None),
                           P(both)),
                 out_specs=P(both, None),
                 check_vma=False)
        def dispatch(w1, b1, w2, b2, x_local, route_local):
            # w1 [1, F, H], b1 [1, H], w2 [1, H, 1], b2 [1, 1]: this
            # device's expert.  x_local [G_l, E, F], route_local [G_l].
            g_l, e_dim, f_dim = x_local.shape
            cap = g_l  # worst case: every local group -> one expert

            onehot = jax.nn.one_hot(route_local, n, dtype=jnp.int32)
            slot = jnp.cumsum(onehot, axis=0)[
                jnp.arange(g_l), route_local] - 1          # [G_l]
            send = jnp.zeros((n, cap, e_dim, f_dim), x_local.dtype)
            send = send.at[route_local, slot].set(x_local)

            recv = jax.lax.all_to_all(
                send, expert_axis, split_axis=0, concat_axis=0,
                tiled=False).reshape(n, cap, e_dim, f_dim)

            flat = recv.reshape(n * cap * e_dim, f_dim)
            h = jnp.maximum(flat @ w1[0] + b1[0], 0)
            s = (h @ w2[0] + b2[0]).reshape(n, cap, e_dim)

            back = jax.lax.all_to_all(
                s, expert_axis, split_axis=0, concat_axis=0,
                tiled=False).reshape(n, cap, e_dim)
            # every (dst, slot) read below was written by this device's
            # own scatter above, so no validity mask is needed
            return back[route_local, slot]                 # [G_l, E]

        def scores(params: Params, features, mask):
            route, probs = model.gate(params, features, mask)
            s = dispatch(params["w1"], params["b1"], params["w2"],
                         params["b2"], features.astype(jnp.bfloat16),
                         route)
            p_sel = jnp.take_along_axis(probs, route[:, None], axis=1)
            return s.astype(jnp.float32) * p_sel, route, probs

        def loss_fn(params: Params, batch: Batch):
            s, route, probs = scores(params, batch.features, batch.mask)
            ce = masked_ce_loss(s, batch.mask, batch.target)
            return ce + model.aux_weight * model.aux_loss(route, probs)

        def step(params, opt_state, batch):
            # models/common.py owns the optimizer update; only the loss
            # (with its all_to_all dispatch) is planner-specific
            return model.train_step_with(loss_fn, params, opt_state,
                                         batch)

        self._forward = jax.jit(
            lambda params, features, mask: plan_weights(
                scores(params, features, mask)[0], mask),
            in_shardings=(ps, bs.features, bs.mask),
            out_shardings=out_s)
        self._step = jax.jit(step, in_shardings=(ps, None, bs),
                             out_shardings=(ps, None, None))
        self.param_shardings = ps
        self.batch_shardings = bs
        self._n_total = 1
        for axis in both:
            self._n_total *= mesh.shape[axis]

    def shard_batch(self, batch: Batch) -> Batch:
        g = batch.features.shape[0]
        if g % self._n_total:
            raise ValueError(
                f"groups ({g}) must be divisible by the mesh device "
                f"count ({self._n_total}) — the batch shards over both "
                f"axes")
        return SnapshotPlannerMixin.shard_batch(self, batch)
