"""Expert-parallel training for the MoE traffic model (data x expert mesh).

``parallel.experts`` proves the all_to_all dispatch pattern on a toy
per-region affine; this module is the real thing: the full
``models.moe.MoETrafficModel`` trained end-to-end with its experts
sharded one-per-device along an ``expert`` mesh axis and the batch
sharded over BOTH axes (every device holds groups AND one expert — the
standard 2D MoE layout).

Per training step, inside ``jax.shard_map``:

1. gate (replicated f32 matmul, computed outside the shard_map);
2. each device buckets its local groups by destination expert
   (static capacity = local group count, so overflow is impossible);
3. ONE ``jax.lax.all_to_all`` over the ``expert`` axis ships buckets to
   their experts (ICI traffic only within each data-axis row);
4. the local expert MLP runs as one [n*cap*E, F] MXU matmul stack;
5. a second all_to_all ships scores back; scatter restores group order.

Everything is differentiable: the all_to_alls transpose to all_to_alls,
the scatters to gathers, and the expert-parameter gradients psum over
the ``data`` axis automatically (shard_map inserts the reduction for
inputs replicated along an axis).  The gate's gradient flows through
the selected-probability scaling exactly as in the dense model, so
sharded and unsharded training follow the same trajectory.

No reference analogue (SURVEY.md §2: EP ABSENT upstream).
"""
from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat.jaxshim import shard_map

from ..models.common import masked_ce_loss
from ..models.moe import MoETrafficModel, Params, expert_capacity
from ..models.traffic import Batch
from ..ops.weights import plan_weights
from .base import SnapshotPlannerMixin, opt_state_shardings


def moe_param_specs(expert_axis: str = "expert") -> dict:
    """Experts shard dim 0 over the expert axis; the gate replicates."""
    e = expert_axis
    return {
        "wg": P(),
        "w1": P(e, None, None),
        "b1": P(e, None),
        "w2": P(e, None, None),
        "b2": P(e, None),
    }


class ShardedMoEPlanner(SnapshotPlannerMixin):
    """pjit-compiled MoE forward + train step bound to a mesh.

    Requires ``model.n_experts == mesh.shape[expert_axis]`` (one expert
    per device along that axis) and G divisible by the full device
    count (the batch shards over every data axis plus the expert axis).
    ``data_axis`` accepts a single axis name or a sequence of them —
    e.g. ``("dcn_data", "data")`` to put a cross-host replica axis from
    ``make_hybrid_mesh`` outside the local data tile.
    """

    def __init__(self, model: MoETrafficModel, mesh: Mesh,
                 data_axis: "str | Sequence[str]" = "data",
                 expert_axis: str = "expert"):
        if model.n_experts != mesh.shape[expert_axis]:
            raise ValueError(
                f"model has {model.n_experts} experts but the "
                f"'{expert_axis}' mesh axis has "
                f"{mesh.shape[expert_axis]} devices — expert-parallel "
                f"layout is one expert per device")
        self.model = model
        self.mesh = mesh
        n = model.n_experts

        # data_axis may name several mesh axes (e.g. a DCN-outer
        # replica axis plus the local data tile from make_hybrid_mesh);
        # the batch dim shards over all of them plus the expert axis,
        # and the dispatch all_to_all stays on the expert axis only —
        # so expert traffic rides ICI while DCN carries just the
        # gradient all-reduce
        data_axes = ((data_axis,) if isinstance(data_axis, str)
                     else tuple(data_axis))
        both = data_axes + (expert_axis,)
        n_total = self._n_total = 1
        for axis in both:
            n_total = self._n_total = n_total * mesh.shape[axis]
        if (model.capacity_factor is not None
                and model.capacity_blocks != n_total):
            # capacity is enforced per dispatch block; the dense oracle
            # only computes the same function when its blocks match the
            # batch-shard granularity
            raise ValueError(
                f"capacity_factor needs model.capacity_blocks "
                f"({model.capacity_blocks}) == the batch shard count "
                f"({n_total}) so the sharded dispatch and the dense "
                f"model drop the same assignments")
        top_k = model.top_k
        ps = {k: NamedSharding(mesh, s)
              for k, s in moe_param_specs(expert_axis).items()}
        bs = Batch(features=NamedSharding(mesh, P(both, None, None)),
                   mask=NamedSharding(mesh, P(both, None)),
                   target=NamedSharding(mesh, P(both, None)))
        out_s = NamedSharding(mesh, P(both, None))

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(expert_axis, None, None),
                           P(expert_axis, None),
                           P(expert_axis, None, None),
                           P(expert_axis, None),
                           P(both, None, None),
                           P(both, None)),
                 out_specs=P(None, both, None),
                 check_vma=False)
        def dispatch(w1, b1, w2, b2, x_local, routes_local):
            # w1 [1, F, H], b1 [1, H], w2 [1, H, 1], b2 [1, 1]: this
            # device's expert.  x_local [G_l, E, F], routes_local
            # [G_l, K] best-first.  Returns per-slot expert outputs
            # [K, G_l, E] with capacity-dropped slots exactly zero —
            # the zero IS the degradation semantics (and its gradient).
            g_l, e_dim, f_dim = x_local.shape
            # per-expert load is bounded by g_l (top_k routes are
            # distinct experts per group), so clamp the buffers there —
            # an unbounded top-2 budget must not double ICI traffic
            cap = min(expert_capacity(g_l, top_k, n,
                                      model.capacity_factor), g_l)

            # k-major flat priority (primary choices beat secondary
            # ones, ties by group order) — must match the dense
            # model's keep_mask ordering exactly
            rf = routes_local.transpose(1, 0).reshape(top_k * g_l)
            onehot = jax.nn.one_hot(rf, n, dtype=jnp.int32)
            pos = jnp.cumsum(onehot, axis=0) - onehot
            mypos = pos[jnp.arange(top_k * g_l), rf]       # [K*G_l]
            keep = mypos < cap
            # overflow writes land in a dump row sliced off before the
            # collective; overflow reads hit the zero row appended to
            # the return buffer
            slot = jnp.where(keep, mypos, cap)

            x_rep = jnp.broadcast_to(
                x_local[None], (top_k,) + x_local.shape
            ).reshape(top_k * g_l, e_dim, f_dim)
            send = jnp.zeros((n, cap + 1, e_dim, f_dim), x_local.dtype)
            send = send.at[rf, slot].set(x_rep)[:, :cap]

            recv = jax.lax.all_to_all(
                send, expert_axis, split_axis=0, concat_axis=0,
                tiled=False).reshape(n, cap, e_dim, f_dim)

            flat = recv.reshape(n * cap * e_dim, f_dim)
            h = jnp.maximum(flat @ w1[0] + b1[0], 0)
            s = (h @ w2[0] + b2[0]).reshape(n, cap, e_dim)

            back = jax.lax.all_to_all(
                s, expert_axis, split_axis=0, concat_axis=0,
                tiled=False).reshape(n, cap, e_dim)
            back = jnp.concatenate(
                [back, jnp.zeros((n, 1, e_dim), back.dtype)], axis=1)
            return back[rf, slot].reshape(top_k, g_l, e_dim)

        def scores(params: Params, features, mask):
            routes, gate_p, probs = model.gate_topk(params, features,
                                                    mask)
            outs = dispatch(params["w1"], params["b1"], params["w2"],
                            params["b2"],
                            features.astype(jnp.bfloat16), routes)
            s = jnp.zeros(features.shape[:2], jnp.float32)
            for k in range(top_k):  # K is tiny and static: unrolled
                # dropped slots are already exactly zero from dispatch
                s = s + outs[k].astype(jnp.float32) * gate_p[:, k, None]
            return s, routes[:, 0], probs

        def loss_fn(params: Params, batch: Batch):
            s, route, probs = scores(params, batch.features, batch.mask)
            ce = masked_ce_loss(s, batch.mask, batch.target)
            return ce + model.aux_weight * model.aux_loss(route, probs)

        def step(params, opt_state, batch):
            # models/common.py owns the optimizer update; only the loss
            # (with its all_to_all dispatch) is planner-specific
            return model.train_step_with(loss_fn, params, opt_state,
                                         batch)

        self._forward = jax.jit(
            lambda params, features, mask: plan_weights(
                scores(params, features, mask)[0], mask),
            in_shardings=(ps, bs.features, bs.mask),
            out_shardings=out_s)
        opt_s = opt_state_shardings(model, ps, mesh)
        self._step = jax.jit(step, in_shardings=(ps, opt_s, bs),
                             out_shardings=(ps, opt_s, None),
                             donate_argnums=(0, 1))
        self.param_shardings = ps
        self.batch_shardings = bs

    def shard_batch(self, batch: Batch) -> Batch:
        g = batch.features.shape[0]
        if g % self._n_total:
            raise ValueError(
                f"groups ({g}) must be divisible by the mesh device "
                f"count ({self._n_total}) — the batch shards over both "
                f"axes")
        return SnapshotPlannerMixin.shard_batch(self, batch)
