"""Sequence-parallel telemetry aggregation over a device ring.

Long telemetry histories (endpoint health/latency time-series feeding the
traffic policy model) can exceed one chip's HBM.  This module shards the
time axis across the mesh and aggregates with the ring-attention
communication pattern: each device reduces its local time block, then the
block partials rotate around the ring via ``jax.lax.ppermute`` (one
neighbour hop per step, riding ICI) while every device accumulates them
with the position-dependent decay weight.  B-1 hops of an [G, E] partial
instead of gathering the full [T, G, E] history anywhere.

The aggregate is an exponentially-decayed weighted sum
``agg = sum_t decay^(T-1-t) * x[t]`` — genuinely order-dependent, so a
plain ``psum`` cannot replace the ring: each block's contribution is
scaled by ``decay^((B-1-b) * T_block)`` according to its position in time.

No reference analogue (SURVEY.md §2: sequence/context parallelism ABSENT
upstream); this is the compute track's long-context story.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..compat.jaxshim import shard_map


def ewma_reference(x: jax.Array, decay: float) -> jax.Array:
    """Unsharded oracle: sum_t decay^(T-1-t) x[t] over axis 0."""
    T = x.shape[0]
    w = decay ** jnp.arange(T - 1, -1, -1, dtype=jnp.float32)
    return jnp.tensordot(w, x.astype(jnp.float32), axes=(0, 0))


def make_ring_ewma(mesh: Mesh, decay: float, axis: str = "seq"):
    """Compile fn(x [T, ...] f32, time-sharded over ``axis``) -> [...] f32
    replicated, equal to :func:`ewma_reference`."""
    n = mesh.shape[axis]

    @partial(shard_map, mesh=mesh,
             in_specs=P(axis), out_specs=P(),
             check_vma=False)
    def ring(x_local):
        # local block reduction: [T_b, ...] -> [...]
        t_block = x_local.shape[0]
        w = decay ** jnp.arange(t_block - 1, -1, -1, dtype=jnp.float32)
        partial_sum = jnp.tensordot(w, x_local.astype(jnp.float32),
                                    axes=(0, 0))
        my = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def scaled(k, blk):
            # after k hops this device holds block (my - k) mod n
            src = jnp.mod(my - k, n)
            return decay ** ((n - 1 - src).astype(jnp.float32)
                             * t_block) * blk

        def body(k, carry):
            acc, blk = carry
            acc = acc + scaled(k, blk)
            blk = jax.lax.ppermute(blk, axis, perm)
            return acc, blk

        # n-1 hops; the block held after the last hop is accumulated
        # without a further (wasted) rotation
        acc = jnp.zeros_like(partial_sum)
        acc, blk = jax.lax.fori_loop(0, n - 1, body, (acc, partial_sum))
        return acc + scaled(n - 1, blk)

    return jax.jit(ring)


def make_mesh_1d(n_devices: int, axis: str = "seq") -> Mesh:
    import numpy as np
    return Mesh(np.asarray(jax.devices()[:n_devices]), axis_names=(axis,))
