"""Accelerator-resident whole-fleet planner.

The hot planning loop, moved off per-object Python: one XLA program
scores every rescored endpoint in the fleet (packed CSR rows, no
padding-lane matmuls), quantises scores into Global Accelerator weight
allocations, and diffs plan-vs-observed for EVERY group — memberships
and weights — in vectorized jnp ops whose nonzero rows decode straight
into ``EndpointOp`` mutation intents (reconcile/columnar.py) for the
sharded coalescer.

Rung dispatch (compat/capability.py, one ladder fleet-wide):

- ``jnp-reference`` — a single-device jit of the dense program; the
  ORACLE rung, bit-matching the per-object scalar path
  (``TrafficPolicyModel.forward_dense`` + ``ops.weights.plan_weights``
  + set diff) — tests/test_fleet_plan.py pins that equality.
- ``pallas-interpret`` — the sharded program (shimmed ``shard_map``
  over the mesh's 'data' axis, shard-major fleet slices resident per
  device) with the dense quantiser: the interpret probe proves the
  kernel path works, but interpreting a fleet-sized kernel would be
  slower than the reference math, so only the LAYOUT upgrades on this
  rung (same dispatch rule as models/traffic ``serve="auto"``).
- ``pallas-tpu`` — the sharded program with the fused Pallas weight
  kernel (ops/pallas_weights.py, one VMEM round-trip per group block)
  and, when the installed pallas resolves
  ``make_async_remote_copy``, the cross-shard stats reduce rides an
  explicit neighbour RDMA ring instead of a flat ``psum`` — the
  SNIPPETS.md shard_map + async-remote-copy pattern.

Cross-shard reduction is hierarchical either way (HiCCL's compose,
PAPERS.md): per-shard partial stats first collapse across the mesh's
'model' axis replicas (``pmean`` — intra-group, the cheap domain),
then reduce across shards ('data' axis) — never a flat all-to-all of
per-group state; only the [5]-vector of fleet totals crosses shards.

Incremental planning (ISSUE 16): :class:`ResidentFleetPlanner` keeps
the packed grids RESIDENT on device between waves (a
:class:`~.fleet.DeviceGridRing` double buffer) and replans only the
shards a :class:`~..reconcile.resident.ResidentFleet`'s dirty masks
name — row-granular splices in, whole-dirty-shard plan out, results
spliced into a persistent host-side plan.  The full-repack
:class:`WholeFleetPlanner` path stays the ORACLE: incremental output
must bit-match it (lint rule L118 confines full repacks to
oracle/verify entry points on the steady-state wave path).

Purity contract (lint rule L113): no ``apis.*`` reach anywhere in this
module, and no Python loops over fleet keys in the device programs
(``_device_*`` / jitted / shard_mapped functions) — the fleet is
arrays end to end between pack and decode.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..compat import RUNG_REFERENCE, RUNG_TPU, registry
from ..compat.jaxshim import shard_map
from ..ops.diff import EMPTY, plan_observed_diff
from ..ops.weights import plan_weights
from ..reconcile.columnar import (
    MODE_MODEL,
    MODE_NONE,
    MODE_SPEC,
    ColumnarFleet,
    GroupIntent,
    GroupState,
    _pad_rows_bucket,
    decode_group_intent,
    decode_intents,
    pack_fleet,
)

#: stats vector layout (float32, psum-reduced across shards)
STAT_ADDS, STAT_REMOVES, STAT_REWEIGHTS, STAT_LIVE, STAT_RESCORED = \
    range(5)


def _device_plan_block(score_rows, quantize, params, rows, seg, slot,
                       desired, observed, observed_w, cached_w,
                       rescored, mode, spec_w):
    """One block's whole plan: scores -> weights -> diff -> stats.

    ``rows [N, F]`` packed features with scatter coords ``seg``/``slot``
    (out-of-bounds seg = pad row, dropped); grids ``[G, E]``.  Runs as
    the entire fleet (reference rung) or one shard's slice (sharded
    rungs) — same math, so the layouts agree exactly.
    """
    import jax.numpy as jnp

    G, E = desired.shape
    s = score_rows(params, rows)                       # [N] float32
    grid = jnp.zeros((G, E), jnp.float32)
    grid = grid.at[seg, slot].set(s, mode="drop")
    mask = desired != EMPTY
    planned = quantize(grid, mask)                     # [G, E] int32
    fresh = jnp.where(rescored[:, None], planned, cached_w)
    spec_col = jnp.where(mask, jnp.maximum(spec_w, 0)[:, None], 0)
    desired_w = jnp.where((mode == MODE_SPEC)[:, None], spec_col, fresh)
    to_add, to_remove, in_both, obs_w = plan_observed_diff(
        desired, observed, observed_w)
    has_target = (mode != MODE_NONE)[:, None]
    to_reweight = in_both & has_target & (desired_w != obs_w)
    stats = jnp.stack([
        jnp.sum(to_add), jnp.sum(to_remove), jnp.sum(to_reweight),
        jnp.sum(mask), jnp.sum(rescored),
    ]).astype(jnp.float32)
    return desired_w, to_add, to_remove, to_reweight, stats


def _make_stats_ring(n: int, axis: str):
    """TPU-rung cross-shard stats all-reduce as a neighbour RDMA ring.

    Each hop is one shimmed ``make_async_remote_copy``: every device
    sends its block to the right neighbour (recv-semaphore wait = the
    hop barrier), accumulating what arrives — n-1 hops of an (8, 128)
    tile instead of a flat collective, the SNIPPETS.md pattern.  Only
    traced on the pallas-tpu rung with ``async_remote_copy`` resolved;
    execution requires a multi-chip TPU (the capability probe's
    documented limit), everything else reduces with pmean/psum.
    """
    import jax
    import jax.numpy as jnp

    from ..compat import jaxshim

    def _hop(x):
        def kernel(in_ref, out_ref, send_sem, recv_sem):
            my = jax.lax.axis_index(axis)
            right = jax.lax.rem(my + 1, n)
            op = jaxshim.make_async_remote_copy(
                src_ref=in_ref, dst_ref=out_ref,
                send_sem=send_sem, recv_sem=recv_sem,
                device_id=(right,),
                device_id_type=jaxshim.DeviceIdType.MESH)
            op.start()
            op.wait()

        return jaxshim.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            in_specs=[jaxshim.block_spec(memory_space=jaxshim.ANY)],
            out_specs=jaxshim.block_spec(memory_space=jaxshim.ANY),
            scratch_shapes=[jaxshim.SemaphoreType.DMA] * 2,
        )(x)

    def reduce(stats):
        k = stats.shape[0]
        tile = jnp.zeros((8, 128), jnp.float32).at[0, :k].set(stats)
        acc = tile
        blk = tile
        for _ in range(n - 1):   # static unroll over ring hops (not
            blk = _hop(blk)      # fleet keys — L113's loop rule is
            acc = acc + blk      # about per-object planning)
        return acc[0, :k]

    return reduce


def make_fleet_pass(model, rung: str, mesh=None):
    """Compile the whole-fleet pass for a rung.

    Without a mesh: the single-device reference program over flat
    ``[G, E]`` grids + global-seg rows.  With a mesh: the shard_mapped
    program over flat ``[S*Gs, E]`` grids + local-seg ``[S*Ns]`` rows,
    one shard slice per 'data'-axis device, hierarchical stats reduce.
    """
    import jax

    if rung == RUNG_TPU:
        from ..ops.pallas_weights import plan_weights_pallas as quantize
    else:
        quantize = plan_weights
    block = partial(_device_plan_block, model.score_rows, quantize)

    if mesh is None:
        return jax.jit(block)

    from jax.sharding import PartitionSpec as P

    n = mesh.shape["data"]
    use_ring = (rung == RUNG_TPU
                and registry.supports("async_remote_copy"))
    ring = _make_stats_ring(n, "data") if use_ring else None
    row = P("data")
    grid = P("data", None)

    @partial(shard_map, mesh=mesh,
             in_specs=(P(), grid, row, row, grid, grid, grid, grid,
                       row, row, row),
             out_specs=(grid, grid, grid, grid, P()))
    def _device_fleet_shard(params, rows, seg, slot, desired, observed,
                            observed_w, cached_w, rescored, mode,
                            spec_w):
        desired_w, to_add, to_remove, to_reweight, stats = block(
            params, rows, seg, slot, desired, observed, observed_w,
            cached_w, rescored, mode, spec_w)
        # hierarchical compose (HiCCL): collapse the 'model' axis
        # replica group first (cheap domain), then cross-shard
        if "model" in mesh.axis_names:
            stats = jax.lax.pmean(stats, "model")
        if ring is not None:
            stats = ring(stats)
        else:
            stats = jax.lax.psum(stats, "data")
        return desired_w, to_add, to_remove, to_reweight, stats

    return jax.jit(_device_fleet_shard)


@dataclass
class FleetPlanResult:
    """Whole-fleet plan outputs (numpy, shard-major ``[S, Gs, E]``)."""

    fleet: ColumnarFleet
    rung: str
    layout: str                       # "sharded" | "flat"
    desired_w: np.ndarray
    to_add: np.ndarray
    to_remove: np.ndarray
    to_reweight: np.ndarray
    stats: Dict[str, float]

    def intents(self) -> List[GroupIntent]:
        return decode_intents(self.fleet, self.desired_w, self.to_add,
                              self.to_remove, self.to_reweight)


class WholeFleetPlanner:
    """Host wrapper: packed fleets in, decoded mutation intents out.

    Owns the per-(rung, layout) compiled programs and the mesh; pure
    over its inputs and always a FULL repack+replan.  Steady-state
    waves do NOT come here: controller/fleetsweep.py drives the
    dirty-mask API (:class:`ResidentFleetPlanner` over a
    ``ResidentFleet``), which replans only dirty shards.  This full
    path is the ORACLE — the verification surface incremental output
    must bit-match (``ResidentFleetPlanner.verify_full_repack``) —
    and the one-shot path for callers without resident state.  Either
    way the planner never reaches the provider (rule L113).
    """

    def __init__(self, model=None, params=None, seed: int = 0):
        import jax

        from ..models.traffic import TrafficPolicyModel

        self.model = model or TrafficPolicyModel()
        self.params = (params if params is not None
                       else self.model.init_params(
                           jax.random.PRNGKey(seed)))
        self._fns: Dict[Tuple[str, Optional[int]], object] = {}
        self._meshes: Dict[int, object] = {}

    # -- dispatch ------------------------------------------------------

    def plan_rung(self) -> str:
        return registry.plan_rung()

    def _mesh_for(self, shards: int):
        """A ('data' = shards, 'model' = 1) mesh when the backend has
        the devices for it; None -> flat single-device layout."""
        import jax

        if shards <= 1 or shards > len(jax.devices()):
            return None
        mesh = self._meshes.get(shards)
        if mesh is None:
            from .mesh import make_mesh

            mesh = make_mesh(axis_shapes={"data": shards, "model": 1})
            self._meshes[shards] = mesh
        return mesh

    def _fn(self, rung: str, shards: Optional[int]):
        key = (rung, shards)
        fn = self._fns.get(key)
        if fn is None:
            mesh = self._mesh_for(shards) if shards else None
            fn = make_fleet_pass(self.model, rung, mesh=mesh)
            self._fns[key] = fn
        return fn

    # -- planning ------------------------------------------------------

    def prepare(self, fleet: ColumnarFleet):
        """Resolve the rung/layout and build the device program + its
        argument arrays for ``fleet``.  Returns
        ``(rung, layout, fn, rows, rest)`` with the pass invoked as
        ``fn(params, rows, *rest)`` — shared by :meth:`plan` and the
        bench leg so the program the bench times IS the one the
        controller runs (never a drifting re-implementation)."""
        import jax.numpy as jnp

        rung = self.plan_rung()
        sharded = (rung != RUNG_REFERENCE
                   and self._mesh_for(fleet.shards) is not None)
        if sharded:
            rows = fleet.feat_rows.reshape(-1, fleet.feat_rows.shape[-1])
            seg = fleet.row_seg.reshape(-1)
            slot = fleet.row_slot.reshape(-1)
        else:
            rows, seg, slot = fleet.flat_rows()
        desired, observed, observed_w, cached_w, mode, spec_w = \
            fleet.flat_grids()
        fn = self._fn(rung, fleet.shards if sharded else None)
        rest = tuple(jnp.asarray(a) for a in (
            seg, slot, desired, observed, observed_w, cached_w,
            fleet.rescored.reshape(-1), mode, spec_w))
        return (rung, "sharded" if sharded else "flat", fn,
                jnp.asarray(rows), rest)

    def plan(self, fleet: ColumnarFleet) -> FleetPlanResult:
        """One whole-fleet pass on the best live rung, under a
        ``fleet_plan.device`` span (nests under the fleet-sweep wave
        span when the sweep dispatch drives it — tracing.py) naming
        the rung/layout the pass actually ran on."""
        import jax

        from ..tracing import default_tracer

        rung, layout, fn, rows, rest = self.prepare(fleet)
        S, Gs, E = fleet.desired.shape
        with default_tracer.span("fleet_plan.device", rung=rung,
                                 layout=layout,
                                 groups=fleet.total_groups):
            desired_w, to_add, to_remove, to_reweight, stats = fn(
                self.params, rows, *rest)
            (desired_w, to_add, to_remove, to_reweight, stats) = \
                jax.device_get(
                    (desired_w, to_add, to_remove, to_reweight, stats))
        shape = (S, Gs, E)
        return FleetPlanResult(
            fleet=fleet, rung=rung, layout=layout,
            desired_w=np.asarray(desired_w).reshape(shape),
            to_add=np.asarray(to_add).reshape(shape),
            to_remove=np.asarray(to_remove).reshape(shape),
            to_reweight=np.asarray(to_reweight).reshape(shape),
            stats={
                "adds": float(stats[STAT_ADDS]),
                "removes": float(stats[STAT_REMOVES]),
                "reweights": float(stats[STAT_REWEIGHTS]),
                "live_endpoints": float(stats[STAT_LIVE]),
                "rescored_groups": float(stats[STAT_RESCORED]),
                "groups": float(fleet.total_groups),
            })

    def plan_groups(self, groups: Sequence[GroupState],
                    endpoints_cap: int = 16,
                    shards: int = 1) -> FleetPlanResult:
        """Convenience: pack + plan in one call."""
        fleet = pack_fleet(groups, endpoints_cap=endpoints_cap,
                           shards=shards,
                           feature_dim=self.model.feature_dim)
        return self.plan(fleet)


# ---------------------------------------------------------------------------
# incremental resident planner (ISSUE 16)
# ---------------------------------------------------------------------------


def make_incremental_pass(model, rung: str, splice):
    """Compile the dirty-shard pass: splice dirty rows into the
    resident grids, replan the dirty shards, write back fresh weight
    caches — one jit, device-resident end to end.

    Shapes (all static per compiled specialization): resident grids
    ``[S, cap, (E)]``; ``Kp`` spliced rows at ``(ks, kg)``; ``Dbp``
    gathered dirty shards named by ``idx`` (pad entries carry
    ``valid=False`` and scatter out of bounds on write-back); ``Np``
    packed score rows with batch-global ``seg`` (``Dbp*cap`` = pad).
    The planning math is :func:`_device_plan_block` — the SAME block
    the oracle runs, so per-group-row independence makes incremental
    == full bit-exact by construction.
    """
    import jax
    import jax.numpy as jnp

    if rung == RUNG_TPU:
        from ..ops.pallas_weights import plan_weights_pallas as quantize
    else:
        quantize = plan_weights
    block = partial(_device_plan_block, model.score_rows, quantize)

    def incremental(params, res, ks, kg, rows6, idx, valid,
                    srows, seg, slot, rescored):
        res_d, res_o, res_ow, res_cw, res_m, res_sw = res
        d_rows, o_rows, ow_rows, cw_rows, m_vals, sw_vals = rows6
        # 1. splice the wave's dirty rows into the resident grids
        res_d = splice(res_d, ks, kg, d_rows)
        res_o = splice(res_o, ks, kg, o_rows)
        res_ow = splice(res_ow, ks, kg, ow_rows)
        res_cw = splice(res_cw, ks, kg, cw_rows)
        res_m = res_m.at[ks, kg].set(m_vals)
        res_sw = res_sw.at[ks, kg].set(sw_vals)
        # 2. gather the dirty shards and replan them as one block
        Dbp = idx.shape[0]
        S, cap, E = res_d.shape
        flat = lambda a: a[idx].reshape(Dbp * cap, *a.shape[2:])
        desired_w, to_add, to_remove, to_reweight, _ = block(
            params, srows, seg, slot, flat(res_d), flat(res_o),
            flat(res_ow), flat(res_cw), rescored.reshape(-1),
            flat(res_m), flat(res_sw))
        # 3. write fresh caches back (rescored rows only); pad batches
        #    route out of bounds — duplicate-index scatter order is
        #    unspecified, so pads must never alias a real shard's write
        new_cw = jnp.where(rescored.reshape(-1)[:, None], desired_w,
                           flat(res_cw)).reshape(Dbp, cap, E)
        idx_w = jnp.where(valid, idx, S)
        res_cw = res_cw.at[idx_w].set(new_cw, mode="drop")
        shape = (Dbp, cap, E)
        return ((res_d, res_o, res_ow, res_cw, res_m, res_sw),
                desired_w.reshape(shape), to_add.reshape(shape),
                to_remove.reshape(shape), to_reweight.reshape(shape))

    return jax.jit(incremental)


@dataclass
class WaveResult:
    """One incremental wave's outcome."""

    rung: str
    dirty_shards: int
    dirty_groups: int
    device_call: bool                 # False = zero-dirty fast path
    intents: List[GroupIntent]        # dirty positions only
    stats: Dict[str, float] = field(default_factory=dict)


class ResidentFleetPlanner:
    """Incremental planner over a :class:`~..reconcile.resident.
    ResidentFleet`: drains the dirty masks, replans ONLY the dirty
    shards on device, and splices the results into a persistent
    host-side plan (``planned_w`` / ``to_add`` / ``to_remove`` /
    ``to_reweight``, ``[S, cap, E]``).

    Device residency is a :class:`~.fleet.DeviceGridRing` double
    buffer: each wave's pass returns NEW resident arrays
    (functionally-updated), the ring advances, and the previous
    buffer stays referenced until :meth:`flush_complete` — so the
    next wave's splice+plan can start while the previous wave's
    intents are still flushing.  A zero-dirty wave never touches the
    device at all.

    Correctness anchor: :meth:`verify_full_repack` repacks the
    resident truth through the :class:`WholeFleetPlanner` ORACLE and
    demands bit-equality — the only full-repack call site on the
    steady-state path (lint rule L118).
    """

    def __init__(self, fleet, model=None, params=None, seed: int = 0):
        import jax

        from ..models.traffic import TrafficPolicyModel

        from .fleet import DeviceGridRing, make_row_splice

        self.fleet = fleet
        self.model = model or TrafficPolicyModel()
        self.params = (params if params is not None
                       else self.model.init_params(
                           jax.random.PRNGKey(seed)))
        self.ring = DeviceGridRing()
        self._make_splice = make_row_splice
        self._fns: Dict[Tuple, object] = {}
        self._gen = fleet.generation
        self.device_calls = 0
        self.waves = 0
        S, cap, E = fleet.shards, fleet.cap, fleet.endpoints_cap
        self.planned_w = np.zeros((S, cap, E), np.int32)
        self.to_add = np.zeros((S, cap, E), bool)
        self.to_remove = np.zeros((S, cap, E), bool)
        self.to_reweight = np.zeros((S, cap, E), bool)

    # -- residency maintenance -----------------------------------------

    def plan_rung(self) -> str:
        return registry.plan_rung()

    def _sync_generation(self) -> None:
        """Capacity growth invalidates device residency AND compiled
        shapes; the host plan just pads (old positions kept)."""
        if self._gen == self.fleet.generation:
            return
        cap = self.fleet.cap
        grow = cap - self.planned_w.shape[1]
        if grow > 0:
            pad = ((0, 0), (0, grow), (0, 0))
            self.planned_w = np.pad(self.planned_w, pad)
            self.to_add = np.pad(self.to_add, pad)
            self.to_remove = np.pad(self.to_remove, pad)
            self.to_reweight = np.pad(self.to_reweight, pad)
        self.ring.drop()
        self._fns.clear()
        self._gen = self.fleet.generation

    def _resident_front(self):
        """Current device-resident grids; first wave (or post-growth)
        re-uploads the host truth wholesale."""
        import jax.numpy as jnp

        front = self.ring.front
        if front is None:
            f = self.fleet
            front = self.ring.reset(tuple(jnp.asarray(a) for a in (
                f.desired, f.observed, f.observed_w, f.cached_w,
                f.weight_mode, f.spec_w)))
        return front

    def _fn(self, rung: str, Kp: int, Dbp: int, Np: int):
        key = (rung, Kp, Dbp, Np, self.fleet.cap)
        fn = self._fns.get(key)
        if fn is None:
            fn = make_incremental_pass(self.model, rung,
                                       self._make_splice(rung))
            self._fns[key] = fn
        return fn

    # -- the wave ------------------------------------------------------

    def plan_wave(self) -> WaveResult:
        """Drain the fleet's dirty masks and replan exactly those
        shards, under a ``fleet_plan.incremental`` span.  Zero dirt =
        zero device work (the steady-state invariant tests pin via
        ``device_calls``)."""
        import jax
        import jax.numpy as jnp

        from ..tracing import default_tracer

        self._sync_generation()
        f = self.fleet
        dirty = f.take_dirty()
        rung = self.plan_rung()
        self.waves += 1
        if not dirty:
            return WaveResult(rung=rung, dirty_shards=0, dirty_groups=0,
                              device_call=False, intents=[],
                              stats={"adds": 0.0, "removes": 0.0,
                                     "reweights": 0.0,
                                     "rescored_groups": 0.0})

        S, cap, E, F = f.shards, f.cap, f.endpoints_cap, f.feature_dim
        ds = sorted(dirty)
        Db = len(ds)
        positions = [(s, gi) for s in ds for gi in dirty[s]]
        K = len(positions)

        # dirty-row splice batch (row-granular host->device traffic:
        # K rows, not S*cap)
        Kp = _pad_rows_bucket(K)
        ks = np.zeros(Kp, np.int32)
        kg = np.zeros(Kp, np.int32)
        for i, (s, gi) in enumerate(positions):
            ks[i], kg[i] = s, gi
        ks[K:], kg[K:] = ks[0], kg[0]   # pad rows re-write row 0's
        pos_idx = (ks[:K], kg[:K])      # value: scatter-order safe
        rows6 = (f.desired[pos_idx], f.observed[pos_idx],
                 f.observed_w[pos_idx], f.cached_w[pos_idx],
                 f.weight_mode[pos_idx], f.spec_w[pos_idx])
        rows6 = tuple(np.concatenate([r] + [r[:1]] * (Kp - K))
                      if Kp > K else r for r in rows6)

        # gathered dirty-shard batch + packed score rows for slots
        # needing a rescore
        Dbp = _pad_rows_bucket(Db, minimum=1)
        idx = np.full(Dbp, ds[0], np.int32)
        idx[:Db] = ds
        valid = np.zeros(Dbp, bool)
        valid[:Db] = True
        batch_of = {s: b for b, s in enumerate(ds)}
        rescored = np.zeros((Dbp, cap), bool)
        srow_list: List[Tuple[np.ndarray, int, int]] = []
        for s, gi in positions:
            slot = f.slot(s, gi)
            if (slot is None or slot.mode != MODE_MODEL
                    or f.has_cache[s, gi]):
                continue
            if slot.features is None:
                raise ValueError(
                    f"resident slot {slot.key!r} needs a rescore but "
                    f"holds no features")
            b = batch_of[s]
            rescored[b, gi] = True
            for j in range(slot.nd):
                srow_list.append((slot.features[j], b * cap + gi, j))
        Np = _pad_rows_bucket(len(srow_list))
        srows = np.zeros((Np, F), np.float32)
        seg = np.full(Np, Dbp * cap, np.int32)   # out of bounds = drop
        slot_col = np.zeros(Np, np.int32)
        for i, (row, sg, j) in enumerate(srow_list):
            srows[i], seg[i], slot_col[i] = row, sg, j

        fn = self._fn(rung, Kp, Dbp, Np)
        with default_tracer.span("fleet_plan.incremental", rung=rung,
                                 layout="resident", dirty_shards=Db,
                                 dirty_groups=K):
            res = self._resident_front()
            out = fn(self.params, res,
                     jnp.asarray(ks), jnp.asarray(kg),
                     tuple(jnp.asarray(r) for r in rows6),
                     jnp.asarray(idx), jnp.asarray(valid),
                     jnp.asarray(srows), jnp.asarray(seg),
                     jnp.asarray(slot_col), jnp.asarray(rescored))
            new_res, d_w, add, rm, rw = out
            self.ring.advance(new_res)
            d_w, add, rm, rw = jax.device_get((d_w, add, rm, rw))
        self.device_calls += 1

        # splice the replanned shards into the persistent host plan +
        # refresh the host weight cache for rescored slots
        d_w = np.asarray(d_w)
        add, rm, rw = (np.asarray(a) for a in (add, rm, rw))
        for b, s in enumerate(ds):
            self.planned_w[s] = d_w[b]
            self.to_add[s] = add[b]
            self.to_remove[s] = rm[b]
            self.to_reweight[s] = rw[b]
            resc = rescored[b]
            if resc.any():
                f.cached_w[s][resc] = d_w[b][resc]
        f.mark_scored([(s, gi) for s, gi in positions
                       if rescored[batch_of[s], gi]])

        live = int((f.desired[ds] != EMPTY).sum())
        stats = {"adds": float(add[:Db].sum()),
                 "removes": float(rm[:Db].sum()),
                 "reweights": float(rw[:Db].sum()),
                 "live_endpoints": float(live),
                 "rescored_groups": float(rescored[:Db].sum())}
        return WaveResult(
            rung=rung, dirty_shards=Db, dirty_groups=K,
            device_call=True,
            intents=self._decode_positions(positions), stats=stats)

    # -- decode / flush edges ------------------------------------------

    def _decode_positions(self, positions) -> List[GroupIntent]:
        out: List[GroupIntent] = []
        for s, gi in positions:
            slot = self.fleet.slot(s, gi)
            if slot is None:          # removed this wave: no intent
                continue
            out.append(self._decode_one(slot, s, gi))
        return out

    def _decode_one(self, slot, s: int, gi: int) -> GroupIntent:
        f = self.fleet
        sof = f.arns.string_of
        desired = [sof(int(i)) for i in f.desired[s, gi][:slot.nd]]
        observed = [sof(int(i)) for i in f.observed[s, gi][:slot.no]]
        return decode_group_intent(
            slot.key, slot.group_arn, desired, observed,
            slot.mode != MODE_NONE, slot.client_ip_preservation,
            self.planned_w[s, gi], self.to_add[s, gi],
            self.to_remove[s, gi], self.to_reweight[s, gi])

    def intents_for(self, keys: Sequence[str]) -> List[GroupIntent]:
        """Decode the RESIDENT plan for given keys — clean keys'
        entries are as current as dirty ones (their shard's last
        replan covered them)."""
        out: List[GroupIntent] = []
        for k in keys:
            loc = self.fleet.location(k)
            if loc is None:
                continue
            slot = self.fleet.slot(*loc)
            if slot is not None:
                out.append(self._decode_one(slot, *loc))
        return out

    def flush_complete(self) -> None:
        """The previous wave's intent flush drained through the
        coalescer: release the retired device buffer (the ring's
        hand-off rule)."""
        self.ring.release_retired()

    # -- the oracle edge (the ONE sanctioned full repack: rule L118) ---

    def verify_full_repack(self) -> Dict[str, object]:
        """Repack the resident truth from scratch and replan it with
        the :class:`WholeFleetPlanner` ORACLE; demand bit-equality
        against the resident plan, position by position.  Call with
        the dirty masks drained (an undrained wave is expected to
        mismatch — it hasn't been planned yet)."""
        f = self.fleet
        oracle = WholeFleetPlanner(model=self.model,
                                   params=self.params)
        res = oracle.plan_groups(f.snapshot_groups(),
                                 endpoints_cap=f.endpoints_cap,
                                 shards=f.shards)
        mismatches = 0
        first: Optional[str] = None
        pairs = zip(f.occupied_positions(), res.fleet.locations,
                    res.fleet.groups)
        for (s, gi), (s2, gp), g in pairs:
            ok = (s == s2
                  and np.array_equal(self.planned_w[s, gi],
                                     res.desired_w[s2, gp])
                  and np.array_equal(self.to_add[s, gi],
                                     res.to_add[s2, gp])
                  and np.array_equal(self.to_remove[s, gi],
                                     res.to_remove[s2, gp])
                  and np.array_equal(self.to_reweight[s, gi],
                                     res.to_reweight[s2, gp]))
            if not ok:
                mismatches += 1
                if first is None:
                    first = g.key
        return {"match": mismatches == 0, "groups": len(res.fleet.groups),
                "mismatches": mismatches, "first_mismatch": first,
                "oracle_rung": res.rung}
