"""Accelerator-resident whole-fleet planner.

The hot planning loop, moved off per-object Python: one XLA program
scores every rescored endpoint in the fleet (packed CSR rows, no
padding-lane matmuls), quantises scores into Global Accelerator weight
allocations, and diffs plan-vs-observed for EVERY group — memberships
and weights — in vectorized jnp ops whose nonzero rows decode straight
into ``EndpointOp`` mutation intents (reconcile/columnar.py) for the
sharded coalescer.

Rung dispatch (compat/capability.py, one ladder fleet-wide):

- ``jnp-reference`` — a single-device jit of the dense program; the
  ORACLE rung, bit-matching the per-object scalar path
  (``TrafficPolicyModel.forward_dense`` + ``ops.weights.plan_weights``
  + set diff) — tests/test_fleet_plan.py pins that equality.
- ``pallas-interpret`` — the sharded program (shimmed ``shard_map``
  over the mesh's 'data' axis, shard-major fleet slices resident per
  device) with the dense quantiser: the interpret probe proves the
  kernel path works, but interpreting a fleet-sized kernel would be
  slower than the reference math, so only the LAYOUT upgrades on this
  rung (same dispatch rule as models/traffic ``serve="auto"``).
- ``pallas-tpu`` — the sharded program with the fused Pallas weight
  kernel (ops/pallas_weights.py, one VMEM round-trip per group block)
  and, when the installed pallas resolves
  ``make_async_remote_copy``, the cross-shard stats reduce rides an
  explicit neighbour RDMA ring instead of a flat ``psum`` — the
  SNIPPETS.md shard_map + async-remote-copy pattern.

Cross-shard reduction is hierarchical either way (HiCCL's compose,
PAPERS.md): per-shard partial stats first collapse across the mesh's
'model' axis replicas (``pmean`` — intra-group, the cheap domain),
then reduce across shards ('data' axis) — never a flat all-to-all of
per-group state; only the [5]-vector of fleet totals crosses shards.

Purity contract (lint rule L113): no ``apis.*`` reach anywhere in this
module, and no Python loops over fleet keys in the device programs
(``_device_*`` / jitted / shard_mapped functions) — the fleet is
arrays end to end between pack and decode.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..compat import RUNG_REFERENCE, RUNG_TPU, registry
from ..compat.jaxshim import shard_map
from ..ops.diff import EMPTY, plan_observed_diff
from ..ops.weights import plan_weights
from ..reconcile.columnar import (
    MODE_NONE,
    MODE_SPEC,
    ColumnarFleet,
    GroupIntent,
    GroupState,
    decode_intents,
    pack_fleet,
)

#: stats vector layout (float32, psum-reduced across shards)
STAT_ADDS, STAT_REMOVES, STAT_REWEIGHTS, STAT_LIVE, STAT_RESCORED = \
    range(5)


def _device_plan_block(score_rows, quantize, params, rows, seg, slot,
                       desired, observed, observed_w, cached_w,
                       rescored, mode, spec_w):
    """One block's whole plan: scores -> weights -> diff -> stats.

    ``rows [N, F]`` packed features with scatter coords ``seg``/``slot``
    (out-of-bounds seg = pad row, dropped); grids ``[G, E]``.  Runs as
    the entire fleet (reference rung) or one shard's slice (sharded
    rungs) — same math, so the layouts agree exactly.
    """
    import jax.numpy as jnp

    G, E = desired.shape
    s = score_rows(params, rows)                       # [N] float32
    grid = jnp.zeros((G, E), jnp.float32)
    grid = grid.at[seg, slot].set(s, mode="drop")
    mask = desired != EMPTY
    planned = quantize(grid, mask)                     # [G, E] int32
    fresh = jnp.where(rescored[:, None], planned, cached_w)
    spec_col = jnp.where(mask, jnp.maximum(spec_w, 0)[:, None], 0)
    desired_w = jnp.where((mode == MODE_SPEC)[:, None], spec_col, fresh)
    to_add, to_remove, in_both, obs_w = plan_observed_diff(
        desired, observed, observed_w)
    has_target = (mode != MODE_NONE)[:, None]
    to_reweight = in_both & has_target & (desired_w != obs_w)
    stats = jnp.stack([
        jnp.sum(to_add), jnp.sum(to_remove), jnp.sum(to_reweight),
        jnp.sum(mask), jnp.sum(rescored),
    ]).astype(jnp.float32)
    return desired_w, to_add, to_remove, to_reweight, stats


def _make_stats_ring(n: int, axis: str):
    """TPU-rung cross-shard stats all-reduce as a neighbour RDMA ring.

    Each hop is one shimmed ``make_async_remote_copy``: every device
    sends its block to the right neighbour (recv-semaphore wait = the
    hop barrier), accumulating what arrives — n-1 hops of an (8, 128)
    tile instead of a flat collective, the SNIPPETS.md pattern.  Only
    traced on the pallas-tpu rung with ``async_remote_copy`` resolved;
    execution requires a multi-chip TPU (the capability probe's
    documented limit), everything else reduces with pmean/psum.
    """
    import jax
    import jax.numpy as jnp

    from ..compat import jaxshim

    def _hop(x):
        def kernel(in_ref, out_ref, send_sem, recv_sem):
            my = jax.lax.axis_index(axis)
            right = jax.lax.rem(my + 1, n)
            op = jaxshim.make_async_remote_copy(
                src_ref=in_ref, dst_ref=out_ref,
                send_sem=send_sem, recv_sem=recv_sem,
                device_id=(right,),
                device_id_type=jaxshim.DeviceIdType.MESH)
            op.start()
            op.wait()

        return jaxshim.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            in_specs=[jaxshim.block_spec(memory_space=jaxshim.ANY)],
            out_specs=jaxshim.block_spec(memory_space=jaxshim.ANY),
            scratch_shapes=[jaxshim.SemaphoreType.DMA] * 2,
        )(x)

    def reduce(stats):
        k = stats.shape[0]
        tile = jnp.zeros((8, 128), jnp.float32).at[0, :k].set(stats)
        acc = tile
        blk = tile
        for _ in range(n - 1):   # static unroll over ring hops (not
            blk = _hop(blk)      # fleet keys — L113's loop rule is
            acc = acc + blk      # about per-object planning)
        return acc[0, :k]

    return reduce


def make_fleet_pass(model, rung: str, mesh=None):
    """Compile the whole-fleet pass for a rung.

    Without a mesh: the single-device reference program over flat
    ``[G, E]`` grids + global-seg rows.  With a mesh: the shard_mapped
    program over flat ``[S*Gs, E]`` grids + local-seg ``[S*Ns]`` rows,
    one shard slice per 'data'-axis device, hierarchical stats reduce.
    """
    import jax

    if rung == RUNG_TPU:
        from ..ops.pallas_weights import plan_weights_pallas as quantize
    else:
        quantize = plan_weights
    block = partial(_device_plan_block, model.score_rows, quantize)

    if mesh is None:
        return jax.jit(block)

    from jax.sharding import PartitionSpec as P

    n = mesh.shape["data"]
    use_ring = (rung == RUNG_TPU
                and registry.supports("async_remote_copy"))
    ring = _make_stats_ring(n, "data") if use_ring else None
    row = P("data")
    grid = P("data", None)

    @partial(shard_map, mesh=mesh,
             in_specs=(P(), grid, row, row, grid, grid, grid, grid,
                       row, row, row),
             out_specs=(grid, grid, grid, grid, P()))
    def _device_fleet_shard(params, rows, seg, slot, desired, observed,
                            observed_w, cached_w, rescored, mode,
                            spec_w):
        desired_w, to_add, to_remove, to_reweight, stats = block(
            params, rows, seg, slot, desired, observed, observed_w,
            cached_w, rescored, mode, spec_w)
        # hierarchical compose (HiCCL): collapse the 'model' axis
        # replica group first (cheap domain), then cross-shard
        if "model" in mesh.axis_names:
            stats = jax.lax.pmean(stats, "model")
        if ring is not None:
            stats = ring(stats)
        else:
            stats = jax.lax.psum(stats, "data")
        return desired_w, to_add, to_remove, to_reweight, stats

    return jax.jit(_device_fleet_shard)


@dataclass
class FleetPlanResult:
    """Whole-fleet plan outputs (numpy, shard-major ``[S, Gs, E]``)."""

    fleet: ColumnarFleet
    rung: str
    layout: str                       # "sharded" | "flat"
    desired_w: np.ndarray
    to_add: np.ndarray
    to_remove: np.ndarray
    to_reweight: np.ndarray
    stats: Dict[str, float]

    def intents(self) -> List[GroupIntent]:
        return decode_intents(self.fleet, self.desired_w, self.to_add,
                              self.to_remove, self.to_reweight)


class WholeFleetPlanner:
    """Host wrapper: packed fleets in, decoded mutation intents out.

    Owns the per-(rung, layout) compiled programs and the mesh; pure
    over its inputs — the fingerprint/weight caches that make waves
    incremental live with the caller (controller/fleetsweep.py), the
    planner itself never reaches the provider (rule L113).
    """

    def __init__(self, model=None, params=None, seed: int = 0):
        import jax

        from ..models.traffic import TrafficPolicyModel

        self.model = model or TrafficPolicyModel()
        self.params = (params if params is not None
                       else self.model.init_params(
                           jax.random.PRNGKey(seed)))
        self._fns: Dict[Tuple[str, Optional[int]], object] = {}
        self._meshes: Dict[int, object] = {}

    # -- dispatch ------------------------------------------------------

    def plan_rung(self) -> str:
        return registry.plan_rung()

    def _mesh_for(self, shards: int):
        """A ('data' = shards, 'model' = 1) mesh when the backend has
        the devices for it; None -> flat single-device layout."""
        import jax

        if shards <= 1 or shards > len(jax.devices()):
            return None
        mesh = self._meshes.get(shards)
        if mesh is None:
            from .mesh import make_mesh

            mesh = make_mesh(axis_shapes={"data": shards, "model": 1})
            self._meshes[shards] = mesh
        return mesh

    def _fn(self, rung: str, shards: Optional[int]):
        key = (rung, shards)
        fn = self._fns.get(key)
        if fn is None:
            mesh = self._mesh_for(shards) if shards else None
            fn = make_fleet_pass(self.model, rung, mesh=mesh)
            self._fns[key] = fn
        return fn

    # -- planning ------------------------------------------------------

    def prepare(self, fleet: ColumnarFleet):
        """Resolve the rung/layout and build the device program + its
        argument arrays for ``fleet``.  Returns
        ``(rung, layout, fn, rows, rest)`` with the pass invoked as
        ``fn(params, rows, *rest)`` — shared by :meth:`plan` and the
        bench leg so the program the bench times IS the one the
        controller runs (never a drifting re-implementation)."""
        import jax.numpy as jnp

        rung = self.plan_rung()
        sharded = (rung != RUNG_REFERENCE
                   and self._mesh_for(fleet.shards) is not None)
        if sharded:
            rows = fleet.feat_rows.reshape(-1, fleet.feat_rows.shape[-1])
            seg = fleet.row_seg.reshape(-1)
            slot = fleet.row_slot.reshape(-1)
        else:
            rows, seg, slot = fleet.flat_rows()
        desired, observed, observed_w, cached_w, mode, spec_w = \
            fleet.flat_grids()
        fn = self._fn(rung, fleet.shards if sharded else None)
        rest = tuple(jnp.asarray(a) for a in (
            seg, slot, desired, observed, observed_w, cached_w,
            fleet.rescored.reshape(-1), mode, spec_w))
        return (rung, "sharded" if sharded else "flat", fn,
                jnp.asarray(rows), rest)

    def plan(self, fleet: ColumnarFleet) -> FleetPlanResult:
        """One whole-fleet pass on the best live rung, under a
        ``fleet_plan.device`` span (nests under the fleet-sweep wave
        span when the sweep dispatch drives it — tracing.py) naming
        the rung/layout the pass actually ran on."""
        import jax

        from ..tracing import default_tracer

        rung, layout, fn, rows, rest = self.prepare(fleet)
        S, Gs, E = fleet.desired.shape
        with default_tracer.span("fleet_plan.device", rung=rung,
                                 layout=layout,
                                 groups=fleet.total_groups):
            desired_w, to_add, to_remove, to_reweight, stats = fn(
                self.params, rows, *rest)
            (desired_w, to_add, to_remove, to_reweight, stats) = \
                jax.device_get(
                    (desired_w, to_add, to_remove, to_reweight, stats))
        shape = (S, Gs, E)
        return FleetPlanResult(
            fleet=fleet, rung=rung, layout=layout,
            desired_w=np.asarray(desired_w).reshape(shape),
            to_add=np.asarray(to_add).reshape(shape),
            to_remove=np.asarray(to_remove).reshape(shape),
            to_reweight=np.asarray(to_reweight).reshape(shape),
            stats={
                "adds": float(stats[STAT_ADDS]),
                "removes": float(stats[STAT_REMOVES]),
                "reweights": float(stats[STAT_REWEIGHTS]),
                "live_endpoints": float(stats[STAT_LIVE]),
                "rescored_groups": float(stats[STAT_RESCORED]),
                "groups": float(fleet.total_groups),
            })

    def plan_groups(self, groups: Sequence[GroupState],
                    endpoints_cap: int = 16,
                    shards: int = 1) -> FleetPlanResult:
        """Convenience: pack + plan in one call."""
        fleet = pack_fleet(groups, endpoints_cap=endpoints_cap,
                           shards=shards,
                           feature_dim=self.model.feature_dim)
        return self.plan(fleet)
