"""Ring attention: exact blockwise attention over a sequence-sharded ring.

Long-context attention over telemetry histories whose time axis exceeds
one chip's HBM.  The sequence axis is sharded across the mesh; each
device keeps its query block resident while the key/value blocks rotate
around the device ring via ``jax.lax.ppermute`` (one neighbour hop per
step, riding ICI).  Softmax is accumulated online, flash-attention
style — a running row max ``m``, denominator ``l``, and output ``o`` are
rescaled as each incoming block raises the max — so the result is
*exact* full attention without any device ever materialising the global
[T, T] score matrix or the full [T, H, D] keys/values.

Peak per-device memory is O(T/n · H · D) for the resident blocks plus
O(T/n · S/n) for one block-pair of scores; communication is n-1 hops of
the local K/V blocks over the ring.

Supports causal masking: global positions are reconstructed from the
ring step (after k hops device i holds block (i - k) mod n), so blocks
strictly in the future contribute nothing and the diagonal block is
triangularly masked — identical semantics to the dense oracle.

No reference analogue (SURVEY.md §2: sequence/context parallelism and
attention itself are ABSENT upstream — the reference is a Go k8s
controller); this module is the compute track's long-context backbone.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30  # finite stand-in: exp(-1e30 - m) underflows to 0 cleanly


def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False) -> jax.Array:
    """Unsharded oracle: dense softmax attention.

    q, k, v: [T, H, D] -> [T, H, D] (float32 accumulation).
    """
    q, k, v = (x.astype(jnp.float32) for x in (q, k, v))
    scale = q.shape[-1] ** -0.5
    # [H, T, S]
    s = jnp.einsum("thd,shd->hts", q, k) * scale
    if causal:
        t, srange = q.shape[0], k.shape[0]
        mask = jnp.arange(t)[:, None] >= jnp.arange(srange)[None, :]
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hts,shd->thd", p, v)


def make_ring_attention(mesh: Mesh, axis: str = "seq",
                        causal: bool = False, local: str = "einsum"):
    """Compile fn(q, k, v: [T, H, D], time-sharded over ``axis``) ->
    [T, H, D] time-sharded, equal to :func:`attention_reference`.

    Each of the n ring steps attends the resident query block against the
    currently-held K/V block, folds the partial scores into the online
    softmax state, then rotates K/V one hop; the final step skips the
    (wasted) rotation.

    ``local`` selects the per-block attend implementation:
    - ``"einsum"``: XLA einsums over the whole [H, T_b, S_b] score block;
    - ``"flash"``: the Pallas MXU kernel (ops.pallas_attention), which
      tiles the block and never materialises its scores — the two-level
      long-context path, ring over ICI outside, flash in VMEM inside.
      Block stats (unnormalised o, m, l) merge with the same flash
      recurrence the einsum path applies tile-by-tile.
    """
    if local not in ("einsum", "flash"):
        raise ValueError(f"unknown local attend {local!r}")
    n = mesh.shape[axis]

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(P(axis), P(axis), P(axis)), out_specs=P(axis),
             check_vma=False)
    def ring(q_local, k_local, v_local):
        t_b = q_local.shape[0]
        h, d = q_local.shape[1], q_local.shape[2]
        scale = d ** -0.5
        qf = q_local.astype(jnp.float32)
        my = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % n) for i in range(n)]
        q_pos = my * t_b + jnp.arange(t_b)  # global query positions

        def attend_einsum(carry, step):
            o, m, l, kb, vb = carry
            # [H, T_b, S_b] partial scores vs the block currently held
            s = jnp.einsum("thd,shd->hts", qf,
                           kb.astype(jnp.float32)) * scale
            if causal:
                src = jnp.mod(my - step, n)  # whose block we hold
                k_pos = src * t_b + jnp.arange(t_b)
                keep = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(keep[None], s, _NEG_INF)
            m_new = jnp.maximum(m, s.max(axis=-1))          # [H, T_b]
            alpha = jnp.exp(m - m_new)                      # rescale old
            p = jnp.exp(s - m_new[..., None])               # [H, T_b, S_b]
            l = l * alpha + p.sum(axis=-1)
            o = o * alpha[..., None] + jnp.einsum(
                "hts,shd->htd", p, vb.astype(jnp.float32))
            return o, m_new, l, kb, vb

        def attend_flash(carry, step):
            from ..ops.pallas_attention import flash_attention_stats

            o, m, l, kb, vb = carry
            qh = jnp.transpose(qf, (1, 0, 2))              # [H, T_b, D]
            kh = jnp.transpose(kb, (1, 0, 2))
            vh = jnp.transpose(vb, (1, 0, 2))

            def block_stats(diag_causal):
                return lambda: flash_attention_stats(
                    qh, kh, vh, causal=diag_causal)

            if causal:
                # the only causal-masked block is the diagonal (src ==
                # my: same global offset, so relative == global mask);
                # strictly-past blocks attend in full
                src = jnp.mod(my - step, n)
                o_b, m_b, l_b = jax.lax.cond(
                    src == my, block_stats(True), block_stats(False))
            else:
                o_b, m_b, l_b = block_stats(False)()
            # two-level flash merge of disjoint-key partials
            m_new = jnp.maximum(m, m_b)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(m_b - m_new)
            l = l * alpha + l_b * beta
            o = o * alpha[..., None] + o_b * beta[..., None]
            return o, m_new, l, kb, vb

        attend = attend_einsum if local == "einsum" else attend_flash

        def fold(step, carry):
            if not causal:
                return attend(carry, step)
            # a block strictly in the future is fully masked for every
            # resident query -- skip its einsums instead of multiplying
            # them by exp(-inf): saves ~half the attention FLOPs
            src = jnp.mod(my - step, n)
            return jax.lax.cond(src <= my, attend,
                                lambda c, _: c, carry, step)

        def body(step, carry):
            o, m, l, kb, vb = fold(step, carry)
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return o, m, l, kb, vb

        carry = (jnp.zeros((h, t_b, d), jnp.float32),
                 jnp.full((h, t_b), _NEG_INF, jnp.float32),
                 jnp.zeros((h, t_b), jnp.float32),
                 k_local, v_local)
        carry = jax.lax.fori_loop(0, n - 1, body, carry)
        o, _, l, _, _ = fold(n - 1, carry)
        # causal first block: every query attends at least itself, so l>0
        return jnp.transpose(o / l[..., None], (1, 0, 2)).astype(
            q_local.dtype)

    return jax.jit(ring)
