"""Ring attention: exact blockwise attention over a sequence-sharded ring.

Long-context attention over telemetry histories whose time axis exceeds
one chip's HBM.  The sequence axis is sharded across the mesh; each
device keeps its query block resident while the key/value blocks rotate
around the device ring via ``jax.lax.ppermute`` (one neighbour hop per
step, riding ICI).  Softmax is accumulated online, flash-attention
style — a running row max ``m``, denominator ``l``, and output ``o`` are
rescaled as each incoming block raises the max — so the result is
*exact* full attention without any device ever materialising the global
[T, T] score matrix or the full [T, H, D] keys/values.

Peak per-device memory is O(T/n · H · D) for the resident blocks plus
O(T/n · S/n) for one block-pair of scores; communication is n-1 hops of
the local K/V blocks over the ring.

Supports causal masking: global positions are reconstructed from the
ring step (after k hops device i holds block (i - k) mod n), so blocks
strictly in the future contribute nothing and the diagonal block is
triangularly masked — identical semantics to the dense oracle.

No reference analogue (SURVEY.md §2: sequence/context parallelism and
attention itself are ABSENT upstream — the reference is a Go k8s
controller); this module is the compute track's long-context backbone.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..compat.jaxshim import shard_map

_NEG_INF = -1e30  # finite stand-in: exp(-1e30 - m) underflows to 0 cleanly


def _merge_block_stats(o, m, l, o_b, m_b, l_b):
    """Fold a disjoint-key block's unnormalised softmax stats
    (o_b, m_b, l_b) into the running (o, m, l) — the flash recurrence
    every ring variant shares (contiguous flash-local merge, zigzag
    full- and half-block merges)."""
    m_new = jnp.maximum(m, m_b)
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(m_b - m_new)
    return (o * alpha[..., None] + o_b * beta[..., None],
            m_new,
            l * alpha + l_b * beta)


def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False) -> jax.Array:
    """Unsharded oracle: dense softmax attention.

    q, k, v: [T, H, D] -> [T, H, D] (float32 accumulation).
    """
    q, k, v = (x.astype(jnp.float32) for x in (q, k, v))
    scale = q.shape[-1] ** -0.5
    # [H, T, S]
    s = jnp.einsum("thd,shd->hts", q, k) * scale
    if causal:
        t, srange = q.shape[0], k.shape[0]
        mask = jnp.arange(t)[:, None] >= jnp.arange(srange)[None, :]
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hts,shd->thd", p, v)


def zigzag_indices(t: int, n_shards: int):
    """Global row order for the zigzag layout: shard i holds chunks
    ``i`` and ``2n-1-i`` of the time axis split into 2n chunks (so each
    shard still owns T/n rows, in two pieces).  ``x[zigzag_indices(...)]``
    produces the zigzag-ordered array whose contiguous T/n slices are
    the per-shard blocks; invert with :func:`inverse_zigzag_indices`.

    Why: under causal masking a CONTIGUOUS layout gives shard i work
    proportional to i+1 blocks — the last shard does n× the first's,
    and since every ring step ends at a ppermute barrier the wall time
    is that of the busiest device: ~n full block-attends.  The zigzag
    pairing makes every (holder, source) step cost exactly half a
    block on every device (see make_ring_attention), so causal wall
    time drops to ~n/2 + 1/2 block-attends — a ~2× win at scale with
    identical communication."""
    import numpy as np

    c, rem = divmod(t, 2 * n_shards)
    if rem:
        raise ValueError(f"t={t} must divide into 2*{n_shards} chunks")
    order = []
    for i in range(n_shards):
        order.extend(range(i * c, (i + 1) * c))
        j = 2 * n_shards - 1 - i
        order.extend(range(j * c, (j + 1) * c))
    return np.asarray(order)


def inverse_zigzag_indices(t: int, n_shards: int):
    """Inverse permutation: ``y[inverse_zigzag_indices(...)]`` restores
    time order from a zigzag-ordered array."""
    import numpy as np

    perm = zigzag_indices(t, n_shards)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(t)
    return inv


def make_ring_attention(mesh: Mesh, axis: str = "seq",
                        causal: bool = False, local: str = "einsum",
                        head_axis: "str | None" = None,
                        layout: str = "contiguous"):
    """Compile fn(q, k, v: [T, H, D], time-sharded over ``axis``) ->
    [T, H, D] time-sharded, equal to :func:`attention_reference`.

    Each of the n ring steps attends the resident query block against the
    currently-held K/V block, folds the partial scores into the online
    softmax state, then rotates K/V one hop; the final step skips the
    (wasted) rotation.

    ``local`` selects the per-block attend implementation:
    - ``"einsum"``: XLA einsums over the whole [H, T_b, S_b] score block;
    - ``"flash"``: the Pallas MXU kernel (ops.pallas_attention), which
      tiles the block and never materialises its scores — the two-level
      long-context path, ring over ICI outside, flash in VMEM inside.
      Block stats (unnormalised o, m, l) merge with the same flash
      recurrence the einsum path applies tile-by-tile.

    ``head_axis`` optionally shards the head dim H over a second mesh
    axis (e.g. the data axis when the G*E endpoint streams of the
    temporal model are the heads) — heads are embarrassingly parallel in
    attention, so the ring collectives stay on ``axis`` only.

    ``layout`` picks the time-axis placement (causal only):
    - ``"contiguous"``: shard i holds rows [i·T/n, (i+1)·T/n).  Simple,
      but causally imbalanced — every ring step some device attends a
      full block, so wall ≈ n block-attends.
    - ``"zigzag"``: shard i holds chunks i and 2n-1-i of a 2n-way time
      split (``zigzag_indices`` produces the global order; callers
      place data accordingly and invert outputs).  Each shard's local
      rows stay globally sorted, so: the diagonal step is a plain
      local causal attend; a block from an EARLIER shard sits entirely
      below the low chunk and entirely above the high one, so only its
      low half is visible — ``q_all × k_low`` unmasked; a block from a
      LATER shard is visible only to the high queries — ``q_high ×
      k_all`` unmasked.  Every non-diagonal step therefore costs
      exactly half a block on every device, no masking arithmetic at
      all, and causal wall time halves.  Exact per the oracle on the
      zigzag-permuted axis (softmax accumulation is order-free).

    Differentiable: the returned fn carries a custom VJP implementing
    the ring backward — a second ring pass in which each device keeps
    (q, dO, lse, D) resident and the (k, v, dK, dV) quadruple rotates,
    so dK/dV partials accumulate hop by hop and land on their owner
    after n hops.  Per-device memory stays O(T/n); no [T, T] score
    matrix exists in either direction.
    """
    if local not in ("einsum", "flash"):
        raise ValueError(f"unknown local attend {local!r}")
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]
    if layout == "zigzag":
        if not causal:
            raise ValueError(
                "zigzag layout only pays off (and is only implemented) "
                "for causal attention — non-causal rings are already "
                "balanced")
        return _make_zigzag_ring(mesh, axis, local, head_axis, n, perm)

    def _fwd_local(q_local, k_local, v_local):
        """Per-shard forward.  Returns (o_local [T_b, H_l, D], lse_local
        [H_l, T_b]) — lse is the softmax log-normaliser the backward
        needs to re-materialise probability blocks."""
        t_b = q_local.shape[0]
        h, d = q_local.shape[1], q_local.shape[2]
        scale = d ** -0.5
        qf = q_local.astype(jnp.float32)
        my = jax.lax.axis_index(axis)
        q_pos = my * t_b + jnp.arange(t_b)  # global query positions

        def attend_einsum(carry, step):
            o, m, l, kb, vb = carry
            # [H, T_b, S_b] partial scores vs the block currently held
            s = jnp.einsum("thd,shd->hts", qf,
                           kb.astype(jnp.float32)) * scale
            if causal:
                src = jnp.mod(my - step, n)  # whose block we hold
                k_pos = src * t_b + jnp.arange(t_b)
                keep = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(keep[None], s, _NEG_INF)
            m_new = jnp.maximum(m, s.max(axis=-1))          # [H, T_b]
            alpha = jnp.exp(m - m_new)                      # rescale old
            p = jnp.exp(s - m_new[..., None])               # [H, T_b, S_b]
            l = l * alpha + p.sum(axis=-1)
            o = o * alpha[..., None] + jnp.einsum(
                "hts,shd->htd", p, vb.astype(jnp.float32))
            return o, m_new, l, kb, vb

        def attend_flash(carry, step):
            from ..ops.pallas_attention import flash_attention_stats

            o, m, l, kb, vb = carry
            qh = jnp.transpose(qf, (1, 0, 2))              # [H, T_b, D]
            kh = jnp.transpose(kb, (1, 0, 2))
            vh = jnp.transpose(vb, (1, 0, 2))

            def block_stats(diag_causal):
                return lambda: flash_attention_stats(
                    qh, kh, vh, causal=diag_causal)

            if causal:
                # the only causal-masked block is the diagonal (src ==
                # my: same global offset, so relative == global mask);
                # strictly-past blocks attend in full
                src = jnp.mod(my - step, n)
                o_b, m_b, l_b = jax.lax.cond(
                    src == my, block_stats(True), block_stats(False))
            else:
                o_b, m_b, l_b = block_stats(False)()
            # two-level flash merge of disjoint-key partials
            o, m, l = _merge_block_stats(o, m, l, o_b, m_b, l_b)
            return o, m, l, kb, vb

        attend = attend_einsum if local == "einsum" else attend_flash

        def fold(step, carry):
            if not causal:
                return attend(carry, step)
            # a block strictly in the future is fully masked for every
            # resident query -- skip its einsums instead of multiplying
            # them by exp(-inf): saves ~half the attention FLOPs
            src = jnp.mod(my - step, n)
            return jax.lax.cond(src <= my, attend,
                                lambda c, _: c, carry, step)

        def body(step, carry):
            o, m, l, kb, vb = fold(step, carry)
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return o, m, l, kb, vb

        carry = (jnp.zeros((h, t_b, d), jnp.float32),
                 jnp.full((h, t_b), _NEG_INF, jnp.float32),
                 jnp.zeros((h, t_b), jnp.float32),
                 k_local, v_local)
        carry = jax.lax.fori_loop(0, n - 1, body, carry)
        o, m, l, _, _ = fold(n - 1, carry)
        # causal first block: every query attends at least itself, so l>0
        o_norm = jnp.transpose(o / l[..., None], (1, 0, 2)).astype(
            q_local.dtype)
        return o_norm, m + jnp.log(l)

    @jax.custom_vjp
    def ring_local(q_local, k_local, v_local):
        return _fwd_local(q_local, k_local, v_local)[0]

    def ring_fwd(q_local, k_local, v_local):
        o, lse = _fwd_local(q_local, k_local, v_local)
        return o, (q_local, k_local, v_local, o, lse)

    def ring_bwd(res, do):
        """Ring backward: q/dO/lse/D stay resident; (k, v, dK, dV)
        rotate.  After the n-th hop each dK/dV block has collected every
        device's contribution and is back on its owner."""
        q_local, k_local, v_local, o, lse = res
        t_b = q_local.shape[0]
        d = q_local.shape[2]
        scale = d ** -0.5
        qf = jnp.transpose(q_local.astype(jnp.float32), (1, 0, 2))
        dof = jnp.transpose(do.astype(jnp.float32), (1, 0, 2))
        of = jnp.transpose(o.astype(jnp.float32), (1, 0, 2))
        dvec = jnp.sum(dof * of, axis=-1)                  # [H, T_b]
        my = jax.lax.axis_index(axis)
        q_pos = my * t_b + jnp.arange(t_b)

        def contribute(carry, step):
            dq, kb, vb, dkb, dvb = carry
            kf = jnp.transpose(kb.astype(jnp.float32), (1, 0, 2))
            vf = jnp.transpose(vb.astype(jnp.float32), (1, 0, 2))
            s = jnp.einsum("htd,hsd->hts", qf, kf) * scale
            if causal:
                src = jnp.mod(my - step, n)
                k_pos = src * t_b + jnp.arange(t_b)
                keep = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(keep[None], s, _NEG_INF)
            p = jnp.exp(s - lse[..., None])                # [H, T_b, S_b]
            dp = jnp.einsum("htd,hsd->hts", dof, vf)
            ds = p * (dp - dvec[..., None]) * scale
            dq = dq + jnp.einsum("hts,hsd->htd", ds, kf)
            dkb = dkb + jnp.einsum("hts,htd->hsd", ds, qf)
            dvb = dvb + jnp.einsum("hts,htd->hsd", p, dof)
            return dq, kb, vb, dkb, dvb

        def fold(step, carry):
            if not causal:
                return contribute(carry, step)
            src = jnp.mod(my - step, n)
            return jax.lax.cond(src <= my, contribute,
                                lambda c, _: c, carry, step)

        def body(step, carry):
            dq, kb, vb, dkb, dvb = fold(step, carry)
            # dK/dV ride the same ring as K/V so the partials stay
            # aligned with the block they belong to
            kb, vb, dkb, dvb = (jax.lax.ppermute(x, axis, perm)
                                for x in (kb, vb, dkb, dvb))
            return dq, kb, vb, dkb, dvb

        h, t_loc, dd = qf.shape[0], qf.shape[1], qf.shape[2]
        carry = (jnp.zeros((h, t_loc, dd), jnp.float32),
                 k_local, v_local,
                 jnp.zeros((h, t_b, d), jnp.float32),
                 jnp.zeros((h, t_b, d), jnp.float32))
        carry = jax.lax.fori_loop(0, n - 1, body, carry)
        dq, _, _, dkb, dvb = fold(n - 1, carry)
        # final hop: only dK/dV need to travel home — K/V are done
        # (mirrors the forward's skipped last rotation)
        dk = jax.lax.ppermute(dkb, axis, perm)
        dv = jax.lax.ppermute(dvb, axis, perm)
        back = lambda g, x: jnp.transpose(g, (1, 0, 2)).astype(x.dtype)
        return (back(dq, q_local), back(dk, k_local), back(dv, v_local))

    ring_local.defvjp(ring_fwd, ring_bwd)

    spec = P(axis, head_axis)

    @partial(shard_map, mesh=mesh,
             in_specs=(spec, spec, spec), out_specs=spec,
             check_vma=False)
    def ring(q_local, k_local, v_local):
        return ring_local(q_local, k_local, v_local)

    return jax.jit(ring)


def _make_zigzag_ring(mesh: Mesh, axis: str, local: str,
                      head_axis: "str | None", n: int, perm):
    """Causal ring attention over the zigzag layout (see
    make_ring_attention's ``layout`` doc).  Local blocks are the
    concatenation of a low and a high time chunk, each T/(2n) rows,
    globally sorted WITHIN the block — so the step kinds are:

    - diagonal (source == holder): plain local causal attend over the
      full block (the concatenated positions are sorted, and k == q
      positions, so the triangular mask IS the causal mask);
    - source earlier in the ring: the incoming low chunk is entirely in
      every resident query's past and the incoming high chunk entirely
      in its future — ``q_all × k_low``, no mask;
    - source later: only the resident high chunk may look at it, and it
      sees both its chunks — ``q_high × k_all``, no mask.

    Each non-diagonal step is exactly half a block of work on every
    device — the balance that halves causal wall time.  The backward is
    the same decomposition transposed, with (k, v, dK, dV) rotating as
    in the contiguous ring."""

    def _fwd_local(q_local, k_local, v_local):
        t_b = q_local.shape[0]
        if t_b % 2:
            raise ValueError(
                f"zigzag needs an even per-shard block, got {t_b}")
        c = t_b // 2
        h, d = q_local.shape[1], q_local.shape[2]
        scale = d ** -0.5
        qh = jnp.transpose(q_local.astype(jnp.float32),
                           (1, 0, 2))                    # [H, T_b, D]
        my = jax.lax.axis_index(axis)

        merge = _merge_block_stats

        def stats(q_rows, kb, vb, diag):
            """Block softmax stats for q_rows [H, R, D] vs kb/vb
            [S, H, D]; ``diag`` applies the triangular mask (static
            Python bool — each switch branch is its own trace)."""
            if local == "flash":
                from ..ops.pallas_attention import (
                    flash_attention_stats,
                )

                kh = jnp.transpose(kb, (1, 0, 2))
                vh = jnp.transpose(vb, (1, 0, 2))
                return flash_attention_stats(q_rows, kh, vh,
                                             causal=diag)
            kf = kb.astype(jnp.float32)
            vf = vb.astype(jnp.float32)
            s = jnp.einsum("hrd,shd->hrs", q_rows, kf) * scale
            if diag:
                r, srange = q_rows.shape[1], kf.shape[0]
                keep = (jnp.arange(r)[:, None]
                        >= jnp.arange(srange)[None, :])
                s = jnp.where(keep[None], s, _NEG_INF)
            m_b = s.max(axis=-1)
            p = jnp.exp(s - m_b[..., None])
            return (jnp.einsum("hrs,shd->hrd", p, vf), m_b,
                    p.sum(axis=-1))

        def step_diag(carry):
            o, m, l, kb, vb = carry
            o_b, m_b, l_b = stats(qh, kb, vb, diag=True)
            o, m, l = merge(o, m, l, o_b, m_b, l_b)
            return o, m, l, kb, vb

        def step_low(carry):      # source earlier: q_all × k_low
            o, m, l, kb, vb = carry
            o_b, m_b, l_b = stats(qh, kb[:c], vb[:c], diag=False)
            o, m, l = merge(o, m, l, o_b, m_b, l_b)
            return o, m, l, kb, vb

        def step_high(carry):     # source later: q_high × k_all
            o, m, l, kb, vb = carry
            o_b, m_b, l_b = stats(qh[:, c:], kb, vb, diag=False)
            o2, m2, l2 = merge(o[:, c:], m[:, c:], l[:, c:],
                               o_b, m_b, l_b)
            return (jnp.concatenate([o[:, :c], o2], axis=1),
                    jnp.concatenate([m[:, :c], m2], axis=1),
                    jnp.concatenate([l[:, :c], l2], axis=1), kb, vb)

        def fold(step, carry):
            src = jnp.mod(my - step, n)
            idx = jnp.where(src == my, 0,
                            jnp.where(src < my, 1, 2))
            return jax.lax.switch(idx, [step_diag, step_low,
                                        step_high], carry)

        def body(step, carry):
            o, m, l, kb, vb = fold(step, carry)
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return o, m, l, kb, vb

        carry = (jnp.zeros((h, t_b, d), jnp.float32),
                 jnp.full((h, t_b), _NEG_INF, jnp.float32),
                 jnp.zeros((h, t_b), jnp.float32),
                 k_local, v_local)
        carry = jax.lax.fori_loop(0, n - 1, body, carry)
        o, m, l, _, _ = fold(n - 1, carry)
        # the diagonal step gives every query at least itself: l > 0
        o_norm = jnp.transpose(o / l[..., None], (1, 0, 2)).astype(
            q_local.dtype)
        return o_norm, m + jnp.log(l)

    @jax.custom_vjp
    def ring_local(q_local, k_local, v_local):
        return _fwd_local(q_local, k_local, v_local)[0]

    def ring_fwd(q_local, k_local, v_local):
        o, lse = _fwd_local(q_local, k_local, v_local)
        return o, (q_local, k_local, v_local, o, lse)

    def ring_bwd(res, do):
        q_local, k_local, v_local, o, lse = res
        t_b = q_local.shape[0]
        c = t_b // 2
        d = q_local.shape[2]
        scale = d ** -0.5
        qf = jnp.transpose(q_local.astype(jnp.float32), (1, 0, 2))
        dof = jnp.transpose(do.astype(jnp.float32), (1, 0, 2))
        of = jnp.transpose(o.astype(jnp.float32), (1, 0, 2))
        dvec = jnp.sum(dof * of, axis=-1)                 # [H, T_b]
        my = jax.lax.axis_index(axis)

        def block_grads(q_rows, do_rows, lse_rows, dvec_rows,
                        kb, vb, diag):
            """(dq_rows, dk_block, dv_block) for the sub-attend of
            q_rows against the FULL passed kb/vb (callers slice)."""
            kf = jnp.transpose(kb.astype(jnp.float32), (1, 0, 2))
            vf = jnp.transpose(vb.astype(jnp.float32), (1, 0, 2))
            s = jnp.einsum("hrd,hsd->hrs", q_rows, kf) * scale
            if diag:
                r, srange = q_rows.shape[1], kf.shape[1]
                keep = (jnp.arange(r)[:, None]
                        >= jnp.arange(srange)[None, :])
                s = jnp.where(keep[None], s, _NEG_INF)
            p = jnp.exp(s - lse_rows[..., None])
            dp = jnp.einsum("hrd,hsd->hrs", do_rows, vf)
            ds = p * (dp - dvec_rows[..., None]) * scale
            return (jnp.einsum("hrs,hsd->hrd", ds, kf),
                    jnp.einsum("hrs,hrd->hsd", ds, q_rows),
                    jnp.einsum("hrs,hrd->hsd", p, do_rows))

        def bwd_diag(carry):
            dq, kb, vb, dkb, dvb = carry
            dq_b, dk_b, dv_b = block_grads(qf, dof, lse, dvec,
                                           kb, vb, diag=True)
            return dq + dq_b, kb, vb, dkb + dk_b, dvb + dv_b

        def bwd_low(carry):       # q_all × k_low
            dq, kb, vb, dkb, dvb = carry
            dq_b, dk_b, dv_b = block_grads(qf, dof, lse, dvec,
                                           kb[:c], vb[:c], diag=False)
            dkb = jnp.concatenate([dkb[:, :c] + dk_b, dkb[:, c:]],
                                  axis=1)
            dvb = jnp.concatenate([dvb[:, :c] + dv_b, dvb[:, c:]],
                                  axis=1)
            return dq + dq_b, kb, vb, dkb, dvb

        def bwd_high(carry):      # q_high × k_all
            dq, kb, vb, dkb, dvb = carry
            dq_b, dk_b, dv_b = block_grads(
                qf[:, c:], dof[:, c:], lse[:, c:], dvec[:, c:],
                kb, vb, diag=False)
            dq = jnp.concatenate([dq[:, :c], dq[:, c:] + dq_b],
                                 axis=1)
            return dq, kb, vb, dkb + dk_b, dvb + dv_b

        def fold(step, carry):
            src = jnp.mod(my - step, n)
            idx = jnp.where(src == my, 0,
                            jnp.where(src < my, 1, 2))
            return jax.lax.switch(idx, [bwd_diag, bwd_low, bwd_high],
                                  carry)

        def body(step, carry):
            dq, kb, vb, dkb, dvb = fold(step, carry)
            kb, vb, dkb, dvb = (jax.lax.ppermute(x, axis, perm)
                                for x in (kb, vb, dkb, dvb))
            return dq, kb, vb, dkb, dvb

        h = qf.shape[0]
        carry = (jnp.zeros((h, t_b, d), jnp.float32),
                 k_local, v_local,
                 jnp.zeros((h, t_b, d), jnp.float32),
                 jnp.zeros((h, t_b, d), jnp.float32))
        carry = jax.lax.fori_loop(0, n - 1, body, carry)
        dq, _, _, dkb, dvb = fold(n - 1, carry)
        dk = jax.lax.ppermute(dkb, axis, perm)
        dv = jax.lax.ppermute(dvb, axis, perm)
        back = lambda g, x: jnp.transpose(g, (1, 0, 2)).astype(x.dtype)
        return (back(dq, q_local), back(dk, k_local),
                back(dv, v_local))

    ring_local.defvjp(ring_fwd, ring_bwd)

    spec = P(axis, head_axis)

    @partial(shard_map, mesh=mesh,
             in_specs=(spec, spec, spec), out_specs=spec,
             check_vma=False)
    def ring(q_local, k_local, v_local):
        return ring_local(q_local, k_local, v_local)

    return jax.jit(ring)


def make_last_attention(mesh: Mesh, axis: str = "seq",
                        head_axis: "str | None" = None):
    """fn(q_last [S, D], k, v [T, S, D] time-sharded over ``axis``) ->
    [S, D]: the final row of causal attention, in O(T/n) per device.

    The serving counterpart of :func:`make_ring_attention`: planning
    weights needs only the last step's attended representation, so
    instead of ring-rotating full K/V blocks this computes each
    shard's partial softmax stats (o, m, l) for the single query row
    and merges them with the flash recurrence after one all_gather of
    [S_l, D]-sized rows — no ppermute loop, no [T, T] anything.
    Differentiable through the all_gather's transpose; equal to
    ``models.temporal.attention_last_reference`` up to float
    association."""
    kv_spec = P(axis, head_axis, None)
    q_spec = P(head_axis, None)

    @partial(shard_map, mesh=mesh,
             in_specs=(q_spec, kv_spec, kv_spec), out_specs=q_spec,
             check_vma=False)
    def last(q_l, k_l, v_l):
        qf = q_l.astype(jnp.float32)
        kf = k_l.astype(jnp.float32)
        vf = v_l.astype(jnp.float32)
        scale = qf.shape[-1] ** -0.5
        s = jnp.einsum("sd,tsd->st", qf, kf) * scale   # [S_l, T_b]
        m = jnp.max(s, axis=-1)                        # [S_l]
        p = jnp.exp(s - m[:, None])
        el = jnp.sum(p, axis=-1)                       # [S_l]
        o = jnp.einsum("st,tsd->sd", p, vf)            # [S_l, D]

        os_ = jax.lax.all_gather(o, axis)              # [n, S_l, D]
        ms = jax.lax.all_gather(m, axis)               # [n, S_l]
        ls = jax.lax.all_gather(el, axis)
        mm = jnp.max(ms, axis=0)                       # [S_l]
        w = jnp.exp(ms - mm[None])
        denom = jnp.sum(ls * w, axis=0)                # [S_l]
        num = jnp.sum(os_ * w[..., None], axis=0)      # [S_l, D]
        return num / denom[:, None]

    return jax.jit(last)
