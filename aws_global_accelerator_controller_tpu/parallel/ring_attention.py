"""Ring attention: exact blockwise attention over a sequence-sharded ring.

Long-context attention over telemetry histories whose time axis exceeds
one chip's HBM.  The sequence axis is sharded across the mesh; each
device keeps its query block resident while the key/value blocks rotate
around the device ring via ``jax.lax.ppermute`` (one neighbour hop per
step, riding ICI).  Softmax is accumulated online, flash-attention
style — a running row max ``m``, denominator ``l``, and output ``o`` are
rescaled as each incoming block raises the max — so the result is
*exact* full attention without any device ever materialising the global
[T, T] score matrix or the full [T, H, D] keys/values.

Peak per-device memory is O(T/n · H · D) for the resident blocks plus
O(T/n · S/n) for one block-pair of scores; communication is n-1 hops of
the local K/V blocks over the ring.

Supports causal masking: global positions are reconstructed from the
ring step (after k hops device i holds block (i - k) mod n), so blocks
strictly in the future contribute nothing and the diagonal block is
triangularly masked — identical semantics to the dense oracle.

No reference analogue (SURVEY.md §2: sequence/context parallelism and
attention itself are ABSENT upstream — the reference is a Go k8s
controller); this module is the compute track's long-context backbone.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30  # finite stand-in: exp(-1e30 - m) underflows to 0 cleanly


def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False) -> jax.Array:
    """Unsharded oracle: dense softmax attention.

    q, k, v: [T, H, D] -> [T, H, D] (float32 accumulation).
    """
    q, k, v = (x.astype(jnp.float32) for x in (q, k, v))
    scale = q.shape[-1] ** -0.5
    # [H, T, S]
    s = jnp.einsum("thd,shd->hts", q, k) * scale
    if causal:
        t, srange = q.shape[0], k.shape[0]
        mask = jnp.arange(t)[:, None] >= jnp.arange(srange)[None, :]
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hts,shd->thd", p, v)


def make_ring_attention(mesh: Mesh, axis: str = "seq",
                        causal: bool = False, local: str = "einsum",
                        head_axis: "str | None" = None):
    """Compile fn(q, k, v: [T, H, D], time-sharded over ``axis``) ->
    [T, H, D] time-sharded, equal to :func:`attention_reference`.

    Each of the n ring steps attends the resident query block against the
    currently-held K/V block, folds the partial scores into the online
    softmax state, then rotates K/V one hop; the final step skips the
    (wasted) rotation.

    ``local`` selects the per-block attend implementation:
    - ``"einsum"``: XLA einsums over the whole [H, T_b, S_b] score block;
    - ``"flash"``: the Pallas MXU kernel (ops.pallas_attention), which
      tiles the block and never materialises its scores — the two-level
      long-context path, ring over ICI outside, flash in VMEM inside.
      Block stats (unnormalised o, m, l) merge with the same flash
      recurrence the einsum path applies tile-by-tile.

    ``head_axis`` optionally shards the head dim H over a second mesh
    axis (e.g. the data axis when the G*E endpoint streams of the
    temporal model are the heads) — heads are embarrassingly parallel in
    attention, so the ring collectives stay on ``axis`` only.

    Differentiable: the returned fn carries a custom VJP implementing
    the ring backward — a second ring pass in which each device keeps
    (q, dO, lse, D) resident and the (k, v, dK, dV) quadruple rotates,
    so dK/dV partials accumulate hop by hop and land on their owner
    after n hops.  Per-device memory stays O(T/n); no [T, T] score
    matrix exists in either direction.
    """
    if local not in ("einsum", "flash"):
        raise ValueError(f"unknown local attend {local!r}")
    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def _fwd_local(q_local, k_local, v_local):
        """Per-shard forward.  Returns (o_local [T_b, H_l, D], lse_local
        [H_l, T_b]) — lse is the softmax log-normaliser the backward
        needs to re-materialise probability blocks."""
        t_b = q_local.shape[0]
        h, d = q_local.shape[1], q_local.shape[2]
        scale = d ** -0.5
        qf = q_local.astype(jnp.float32)
        my = jax.lax.axis_index(axis)
        q_pos = my * t_b + jnp.arange(t_b)  # global query positions

        def attend_einsum(carry, step):
            o, m, l, kb, vb = carry
            # [H, T_b, S_b] partial scores vs the block currently held
            s = jnp.einsum("thd,shd->hts", qf,
                           kb.astype(jnp.float32)) * scale
            if causal:
                src = jnp.mod(my - step, n)  # whose block we hold
                k_pos = src * t_b + jnp.arange(t_b)
                keep = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(keep[None], s, _NEG_INF)
            m_new = jnp.maximum(m, s.max(axis=-1))          # [H, T_b]
            alpha = jnp.exp(m - m_new)                      # rescale old
            p = jnp.exp(s - m_new[..., None])               # [H, T_b, S_b]
            l = l * alpha + p.sum(axis=-1)
            o = o * alpha[..., None] + jnp.einsum(
                "hts,shd->htd", p, vb.astype(jnp.float32))
            return o, m_new, l, kb, vb

        def attend_flash(carry, step):
            from ..ops.pallas_attention import flash_attention_stats

            o, m, l, kb, vb = carry
            qh = jnp.transpose(qf, (1, 0, 2))              # [H, T_b, D]
            kh = jnp.transpose(kb, (1, 0, 2))
            vh = jnp.transpose(vb, (1, 0, 2))

            def block_stats(diag_causal):
                return lambda: flash_attention_stats(
                    qh, kh, vh, causal=diag_causal)

            if causal:
                # the only causal-masked block is the diagonal (src ==
                # my: same global offset, so relative == global mask);
                # strictly-past blocks attend in full
                src = jnp.mod(my - step, n)
                o_b, m_b, l_b = jax.lax.cond(
                    src == my, block_stats(True), block_stats(False))
            else:
                o_b, m_b, l_b = block_stats(False)()
            # two-level flash merge of disjoint-key partials
            m_new = jnp.maximum(m, m_b)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(m_b - m_new)
            l = l * alpha + l_b * beta
            o = o * alpha[..., None] + o_b * beta[..., None]
            return o, m_new, l, kb, vb

        attend = attend_einsum if local == "einsum" else attend_flash

        def fold(step, carry):
            if not causal:
                return attend(carry, step)
            # a block strictly in the future is fully masked for every
            # resident query -- skip its einsums instead of multiplying
            # them by exp(-inf): saves ~half the attention FLOPs
            src = jnp.mod(my - step, n)
            return jax.lax.cond(src <= my, attend,
                                lambda c, _: c, carry, step)

        def body(step, carry):
            o, m, l, kb, vb = fold(step, carry)
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return o, m, l, kb, vb

        carry = (jnp.zeros((h, t_b, d), jnp.float32),
                 jnp.full((h, t_b), _NEG_INF, jnp.float32),
                 jnp.zeros((h, t_b), jnp.float32),
                 k_local, v_local)
        carry = jax.lax.fori_loop(0, n - 1, body, carry)
        o, m, l, _, _ = fold(n - 1, carry)
        # causal first block: every query attends at least itself, so l>0
        o_norm = jnp.transpose(o / l[..., None], (1, 0, 2)).astype(
            q_local.dtype)
        return o_norm, m + jnp.log(l)

    @jax.custom_vjp
    def ring_local(q_local, k_local, v_local):
        return _fwd_local(q_local, k_local, v_local)[0]

    def ring_fwd(q_local, k_local, v_local):
        o, lse = _fwd_local(q_local, k_local, v_local)
        return o, (q_local, k_local, v_local, o, lse)

    def ring_bwd(res, do):
        """Ring backward: q/dO/lse/D stay resident; (k, v, dK, dV)
        rotate.  After the n-th hop each dK/dV block has collected every
        device's contribution and is back on its owner."""
        q_local, k_local, v_local, o, lse = res
        t_b = q_local.shape[0]
        d = q_local.shape[2]
        scale = d ** -0.5
        qf = jnp.transpose(q_local.astype(jnp.float32), (1, 0, 2))
        dof = jnp.transpose(do.astype(jnp.float32), (1, 0, 2))
        of = jnp.transpose(o.astype(jnp.float32), (1, 0, 2))
        dvec = jnp.sum(dof * of, axis=-1)                  # [H, T_b]
        my = jax.lax.axis_index(axis)
        q_pos = my * t_b + jnp.arange(t_b)

        def contribute(carry, step):
            dq, kb, vb, dkb, dvb = carry
            kf = jnp.transpose(kb.astype(jnp.float32), (1, 0, 2))
            vf = jnp.transpose(vb.astype(jnp.float32), (1, 0, 2))
            s = jnp.einsum("htd,hsd->hts", qf, kf) * scale
            if causal:
                src = jnp.mod(my - step, n)
                k_pos = src * t_b + jnp.arange(t_b)
                keep = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(keep[None], s, _NEG_INF)
            p = jnp.exp(s - lse[..., None])                # [H, T_b, S_b]
            dp = jnp.einsum("htd,hsd->hts", dof, vf)
            ds = p * (dp - dvec[..., None]) * scale
            dq = dq + jnp.einsum("hts,hsd->htd", ds, kf)
            dkb = dkb + jnp.einsum("hts,htd->hsd", ds, qf)
            dvb = dvb + jnp.einsum("hts,htd->hsd", p, dof)
            return dq, kb, vb, dkb, dvb

        def fold(step, carry):
            if not causal:
                return contribute(carry, step)
            src = jnp.mod(my - step, n)
            return jax.lax.cond(src <= my, contribute,
                                lambda c, _: c, carry, step)

        def body(step, carry):
            dq, kb, vb, dkb, dvb = fold(step, carry)
            # dK/dV ride the same ring as K/V so the partials stay
            # aligned with the block they belong to
            kb, vb, dkb, dvb = (jax.lax.ppermute(x, axis, perm)
                                for x in (kb, vb, dkb, dvb))
            return dq, kb, vb, dkb, dvb

        h, t_loc, dd = qf.shape[0], qf.shape[1], qf.shape[2]
        carry = (jnp.zeros((h, t_loc, dd), jnp.float32),
                 k_local, v_local,
                 jnp.zeros((h, t_b, d), jnp.float32),
                 jnp.zeros((h, t_b, d), jnp.float32))
        carry = jax.lax.fori_loop(0, n - 1, body, carry)
        dq, _, _, dkb, dvb = fold(n - 1, carry)
        # final hop: only dK/dV need to travel home — K/V are done
        # (mirrors the forward's skipped last rotation)
        dk = jax.lax.ppermute(dkb, axis, perm)
        dv = jax.lax.ppermute(dvb, axis, perm)
        back = lambda g, x: jnp.transpose(g, (1, 0, 2)).astype(x.dtype)
        return (back(dq, q_local), back(dk, k_local), back(dv, v_local))

    ring_local.defvjp(ring_fwd, ring_bwd)

    spec = P(axis, head_axis)

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(spec, spec, spec), out_specs=spec,
             check_vma=False)
    def ring(q_local, k_local, v_local):
        return ring_local(q_local, k_local, v_local)

    return jax.jit(ring)


def make_last_attention(mesh: Mesh, axis: str = "seq",
                        head_axis: "str | None" = None):
    """fn(q_last [S, D], k, v [T, S, D] time-sharded over ``axis``) ->
    [S, D]: the final row of causal attention, in O(T/n) per device.

    The serving counterpart of :func:`make_ring_attention`: planning
    weights needs only the last step's attended representation, so
    instead of ring-rotating full K/V blocks this computes each
    shard's partial softmax stats (o, m, l) for the single query row
    and merges them with the flash recurrence after one all_gather of
    [S_l, D]-sized rows — no ppermute loop, no [T, T] anything.
    Differentiable through the all_gather's transpose; equal to
    ``models.temporal.attention_last_reference`` up to float
    association."""
    kv_spec = P(axis, head_axis, None)
    q_spec = P(head_axis, None)

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(q_spec, kv_spec, kv_spec), out_specs=q_spec,
             check_vma=False)
    def last(q_l, k_l, v_l):
        qf = q_l.astype(jnp.float32)
        kf = k_l.astype(jnp.float32)
        vf = v_l.astype(jnp.float32)
        scale = qf.shape[-1] ** -0.5
        s = jnp.einsum("sd,tsd->st", qf, kf) * scale   # [S_l, T_b]
        m = jnp.max(s, axis=-1)                        # [S_l]
        p = jnp.exp(s - m[:, None])
        el = jnp.sum(p, axis=-1)                       # [S_l]
        o = jnp.einsum("st,tsd->sd", p, vf)            # [S_l, D]

        os_ = jax.lax.all_gather(o, axis)              # [n, S_l, D]
        ms = jax.lax.all_gather(m, axis)               # [n, S_l]
        ls = jax.lax.all_gather(el, axis)
        mm = jnp.max(ms, axis=0)                       # [S_l]
        w = jnp.exp(ms - mm[None])
        denom = jnp.sum(ls * w, axis=0)                # [S_l]
        num = jnp.sum(os_ * w[..., None], axis=0)      # [S_l, D]
        return num / denom[:, None]

    return jax.jit(last)
