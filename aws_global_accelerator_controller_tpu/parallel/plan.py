"""Sharded traffic planning and training over a device mesh.

Sharding layout (dp x tp, the scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert the collectives):
- batch [G, E, F]: groups sharded over 'data'; E/F replicated
- layer 1 weight [F, H]: H sharded over 'model' (column parallel)
- layer 2 weight [H, H]: input dim sharded over 'model' (row parallel;
  XLA inserts the psum when the activations contract)
- layer 3 weight [H, 1]: input dim sharded over 'model'
- outputs [G, E]: sharded over 'data'

Gradients reduce over 'data' automatically (XLA all-reduce over ICI);
optimizer state follows the parameter shardings.
"""
from __future__ import annotations

from typing import Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.traffic import Batch, Params, TrafficPolicyModel


def param_specs() -> dict:
    return {
        "w1": P(None, "model"),
        "b1": P("model"),
        "w2": P("model", None),
        "b2": P(None),
        "w3": P("model", None),
        "b3": P(None),
    }


def batch_specs() -> Batch:
    return Batch(features=P("data", None, None), mask=P("data", None),
                 target=P("data", None))


class ShardedTrafficPlanner:
    """pjit-compiled forward + train step bound to a mesh."""

    def __init__(self, model: TrafficPolicyModel, mesh: Mesh):
        self.model = model
        self.mesh = mesh
        ps = {k: NamedSharding(mesh, s) for k, s in param_specs().items()}
        bs = Batch(*[NamedSharding(mesh, s) for s in batch_specs()])
        out_s = NamedSharding(mesh, P("data", None))

        self._forward = jax.jit(
            model.forward,
            in_shardings=(ps, bs.features, bs.mask),
            out_shardings=out_s)

        def step(params, opt_state, batch):
            return model.train_step(params, opt_state, batch)

        self._step = jax.jit(
            step,
            in_shardings=(ps, None, bs),
            out_shardings=(ps, None, None))
        self.param_shardings = ps
        self.batch_shardings = bs

    def shard_params(self, params: Params) -> Params:
        return {k: jax.device_put(v, self.param_shardings[k])
                for k, v in params.items()}

    def shard_batch(self, batch: Batch) -> Batch:
        return Batch(*[jax.device_put(v, s)
                       for v, s in zip(batch, self.batch_shardings)])

    def forward(self, params: Params, features, mask):
        return self._forward(params, features, mask)

    def train_step(self, params: Params, opt_state,
                   batch: Batch) -> Tuple[Params, object, jax.Array]:
        return self._step(params, opt_state, batch)
