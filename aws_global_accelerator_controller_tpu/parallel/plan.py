"""Sharded traffic planning and training over a device mesh.

Sharding layout (dp x tp, the scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert the collectives):
- batch [G, E, F]: groups sharded over 'data'; E/F replicated
- layer 1 weight [F, H]: H sharded over 'model' (column parallel)
- layer 2 weight [H, H]: input dim sharded over 'model' (row parallel;
  XLA inserts the psum when the activations contract)
- layer 3 weight [H, 1]: input dim sharded over 'model'
- outputs [G, E]: sharded over 'data'

Gradients reduce over 'data' automatically (XLA all-reduce over ICI);
optimizer state follows the parameter shardings.

``ShardedTemporalPlanner`` composes the second model family with the
long-context stack: the telemetry window [T, G, E, F] is sharded T over
'seq' and G over 'data', ring attention (parallel.ring_attention, with
its custom ring VJP) carries the time axis, and everything outside the
attention island is plain jit — XLA propagates the shardings and
inserts the data-axis gradient all-reduce.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.temporal import TemporalTrafficModel
from ..ops.weights import plan_weights
from ..models.traffic import Batch, TrafficPolicyModel
from .base import SnapshotPlannerMixin, opt_state_shardings
from .ring_attention import make_last_attention, make_ring_attention


def param_specs() -> dict:
    return {
        "w1": P(None, "model"),
        "b1": P("model"),
        "w2": P("model", None),
        "b2": P(None),
        "w3": P("model", None),
        "b3": P(None),
    }


def batch_specs() -> Batch:
    return Batch(features=P("data", None, None), mask=P("data", None),
                 target=P("data", None))


class ShardedTrafficPlanner(SnapshotPlannerMixin):
    """pjit-compiled forward + train step bound to a mesh."""

    def __init__(self, model: TrafficPolicyModel, mesh: Mesh):
        self.model = model
        self.mesh = mesh
        ps = {k: NamedSharding(mesh, s) for k, s in param_specs().items()}
        bs = Batch(*[NamedSharding(mesh, s) for s in batch_specs()])
        out_s = NamedSharding(mesh, P("data", None))

        self._forward = jax.jit(
            # dense explicitly: pallas_call does not self-partition
            # under pjit, so the sharded path stays pure XLA
            model.forward_dense,
            in_shardings=(ps, bs.features, bs.mask),
            out_shardings=out_s)

        def step(params, opt_state, batch):
            return model.train_step(params, opt_state, batch)

        opt_s = opt_state_shardings(model, ps, mesh)
        self._step = jax.jit(
            step,
            in_shardings=(ps, opt_s, bs),
            out_shardings=(ps, opt_s, None),
            # params/opt_state are consumed and replaced every step:
            # donation lets XLA update Adam state in place instead of
            # allocating + copying 3x param bytes of HBM per step
            # (opt shardings pinned on BOTH sides — see
            # base.opt_state_shardings)
            donate_argnums=(0, 1))
        self.param_shardings = ps
        self.batch_shardings = bs


class ShardedTemporalPlanner:
    """dp x sp training + planning for the temporal model.

    Mesh axes: ``data`` shards the G endpoint groups (and with them the
    G*E attention streams), ``seq`` shards the telemetry window's time
    axis.  The ring-attention collectives (ppermute per hop, forward and
    backward) are the only cross-``seq`` traffic; the loss/gradient
    all-reduce is the only cross-``data`` traffic.

    Requires T % mesh.shape[seq] == 0 and G % mesh.shape[data] == 0
    (static shapes — XLA sees even blocks).

    ``local`` picks the per-block attend inside the ring: default
    follows the model's dispatch — flash only where the model itself
    would use it (backend gate AND the per-device block length
    T/n_seq >= FLASH_MIN_WINDOW; pass ``window`` so the planner can
    apply that check — without it the default stays on einsum).  Pass
    ``local`` explicitly to force.
    """

    def __init__(self, model: TemporalTrafficModel, mesh: Mesh,
                 data_axis: "str | Sequence[str]" = "data",
                 seq_axis: str = "seq",
                 local: "str | None" = None,
                 window: "int | None" = None,
                 layout: str = "contiguous"):
        from ..models.temporal import FLASH_MIN_WINDOW

        if layout not in ("contiguous", "zigzag"):
            raise ValueError(f"unknown layout {layout!r}")
        if layout == "zigzag" and model.supervision != "sequence":
            # last supervision never runs the ring (O(T) last-query
            # path both for training and serving), so zigzag placement
            # would cost a permutation and buy nothing
            raise ValueError(
                "layout='zigzag' requires supervision='sequence' — "
                "the balanced ring only pays off when the full causal "
                "attention is load-bearing")
        self.layout = layout
        self.model = model
        self.mesh = mesh
        # data_axis may name several mesh axes (a DCN-outer replica
        # axis plus the local data tile from make_hybrid_mesh, like
        # ShardedMoEPlanner) — groups shard over all of them while the
        # ring/all_gather collectives stay on the seq axis, so
        # cross-host traffic is only the gradient all-reduce
        data_axes = ((data_axis,) if isinstance(data_axis, str)
                     else tuple(data_axis))
        data_axis = (data_axes if len(data_axes) > 1
                     else data_axes[0])
        if local is None:
            from ..compat import registry
            on_tpu = registry.on_tpu_rung()
            want_flash = (model.attention == "flash_always"
                          or (model.attention == "flash" and on_tpu))
            block_len = (window // mesh.shape[seq_axis]) if window else 0
            local = ("flash"
                     if want_flash and block_len >= FLASH_MIN_WINDOW
                     else "einsum")
        ring = make_ring_attention(mesh, seq_axis, causal=True,
                                   local=local, head_axis=data_axis,
                                   layout=layout)
        self._attend = ring
        self._n_seq = mesh.shape[seq_axis]

        rep = NamedSharding(mesh, P())
        win_s = NamedSharding(mesh, P(seq_axis, data_axis, None, None))
        ge_s = NamedSharding(mesh, P(data_axis, None))
        # sequence supervision carries per-step targets [T, G, E],
        # sharded like the window's leading axes
        target_s = (NamedSharding(mesh, P(seq_axis, data_axis, None))
                    if model.supervision == "sequence" else ge_s)
        batch_s = Batch(features=NamedSharding(
            mesh, P(data_axis, None, None)), mask=ge_s,
            target=target_s)

        self.window_sharding = win_s
        self.batch_shardings = batch_s
        self.param_sharding = rep

        # serving: the O(T) last-query path with the softmax merged
        # across the seq shards by the flash recurrence (shard_map
        # all_gather of per-block (o, m, l) — tiny: one [S, D] row set
        # per shard), regardless of supervision mode
        last_attend = self._last_attend = make_last_attention(
            mesh, seq_axis, data_axis)
        n_seq = self._n_seq

        def _fwd(params, window, mask):
            # zigzag places the final timestep at the end of shard 0's
            # block — global row T/n - 1 of the permuted array; the
            # attended key set is order-free so only the query row
            # moves.  Contiguous keeps the plain -1.
            last_index = (window.shape[0] // n_seq - 1
                          if layout == "zigzag" else -1)
            return plan_weights(
                model.scores_last(params, window,
                                  attend_last=last_attend,
                                  last_index=last_index), mask)

        self._forward = jax.jit(
            _fwd, in_shardings=(rep, win_s, ge_s), out_shardings=ge_s)

        if model.supervision == "sequence":
            def step(params, opt_state, window, batch):
                # attend rides as trailing *data so the shared
                # TrainableModel.train_step (common.py) stays the
                # single optimizer-update implementation across
                # families; the full causal attention is load-bearing
                # here (every step supervised) — ring over seq
                return model.train_step(params, opt_state, window,
                                        batch, ring)
        else:
            def last_loss(params, window, batch):
                from ..models.common import masked_ce_loss

                return masked_ce_loss(
                    model.scores_last(params, window,
                                      attend_last=last_attend),
                    batch.mask, batch.target)

            def step(params, opt_state, window, batch):
                # last supervision trains through the same O(T) path
                # it serves with (the dense model does too) — the ring
                # machinery stays out of a loss whose attention rows
                # would have zero gradient
                return model.train_step_with(last_loss, params,
                                             opt_state, window, batch)

        self._step = jax.jit(
            step,
            # rep broadcasts over the whole opt subtree (params are
            # replicated here, so adam's moments and count are too);
            # pinned on both sides for the donation — see
            # base.opt_state_shardings' rationale
            in_shardings=(rep, rep, win_s, batch_s),
            out_shardings=(rep, rep, None),
            donate_argnums=(0, 1))  # in-place param/Adam-state update

    def shard_params(self, params):
        # jnp.array(copy=True): same aliasing hazard as
        # base.shard_params — the donated sharded handle must never
        # share storage with the caller's params (may_alias=False is
        # not sufficient; see base.shard_params)
        return {k: jax.device_put(jnp.array(v, copy=True),
                                  self.param_sharding)
                for k, v in params.items()}

    def shard_window(self, window):
        if self.layout == "zigzag":
            from .ring_attention import zigzag_indices

            window = jnp.take(window, zigzag_indices(
                window.shape[0], self._n_seq), axis=0)
        return jax.device_put(window, self.window_sharding)

    def shard_batch(self, batch: Batch) -> Batch:
        if (self.layout == "zigzag"
                and self.model.supervision == "sequence"):
            # per-step targets ride the window's time axis: permute
            # them identically so step t's scores still meet step t's
            # targets (the mean-over-steps loss is order-free)
            from .ring_attention import zigzag_indices

            batch = Batch(
                features=batch.features, mask=batch.mask,
                target=jnp.take(batch.target, zigzag_indices(
                    batch.target.shape[0], self._n_seq), axis=0))
        return Batch(*[jax.device_put(v, s)
                       for v, s in zip(batch, self.batch_shardings)])

    def forward(self, params, window, mask):
        return self._forward(params, window, mask)

    def train_step(self, params, opt_state, window, batch):
        return self._step(params, opt_state, window, batch)
