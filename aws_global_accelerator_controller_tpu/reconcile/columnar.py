"""Columnar whole-fleet desired-state packing.

The planner leg used to go object-at-a-time: one ``[1, E]`` forward +
two Python set loops per binding per sweep.  This module packs the
WHOLE fleet's planning inputs into dense arrays once per wave so one
XLA program (parallel/fleet_plan.py) plans every endpoint group at
once:

- **Intern tables** (:class:`InternTable`): every ARN / object key is
  interned to a dense int32 id — ids are the comparable tokens on
  device (no hashing, no collisions), strings never leave the host.
- **Id grids**: desired and observed endpoint memberships as
  ``[S, Gs, E]`` int32 grids (``EMPTY``-padded), observed weights as a
  parallel int32 grid — the shard-major layout: axis 0 is the owning
  shard, so ``shard_map`` hands each device exactly the slice its
  shard owns (Cloud Collectives' rank-reordering move: planning
  traffic stays resident with its owner).
- **Packed score rows** (the columnar trick): model features are NOT a
  dense ``[G, E, F]`` block.  Realistic endpoint groups hold 1-4 load
  balancers against a pad width of 16+, so dense scoring burns 4-16x
  of the fleet's MXU time on padding lanes.  Features pack as CSR-like
  rows ``[S, Ns, F]`` — one row per VALID (rescored, model-planned)
  endpoint — with ``row_seg``/``row_slot`` scatter coordinates; the
  device pass scores rows and scatters into the grid (out-of-bounds
  pad rows drop).
- **Fingerprints + cached weights**: a per-group fingerprint column
  and the last-planned weight grid ride along so an incremental wave
  rescores only groups whose planning inputs changed; unchanged groups
  reuse cached weights while the (cheap, vectorized) plan-vs-observed
  diff still covers the WHOLE fleet — drift detection never narrows.

Decode (:func:`decode_intents`) is the inverse edge: the planner's
nonzero diff rows come back as :class:`~..cloudprovider.aws.batcher.
EndpointOp` mutation intents per group, ready for the sharded
coalescer's submit surface — removes first, then adds (at the planned
weight), then re-weights, mirroring the per-object reconcile order.

Purity contract (lint rule L113): this module and the device programs
it feeds never reach ``apis.*`` and never loop Python over fleet keys
inside the jit path — packing is host-side preparation, planning is
one array program.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.diff import EMPTY
from .interning import InternTable, intern_str  # noqa: F401  (PR-11 import site)

# weight_mode column values: how a group's desired weights are decided
MODE_MODEL = 0   # spec.weight null -> model-planned 255-budget split
MODE_SPEC = 1    # explicit spec.weight broadcast to every endpoint
MODE_NONE = 2    # no target at all (static policy, null weight):
                 # membership still diffs, weights are left alone


@dataclass
class GroupState:
    """One endpoint group's planning inputs (host-side, pre-pack)."""

    key: str                      # object key (ns/name)
    group_arn: str                # AWS-side container (routing key)
    desired: Sequence[str]        # desired endpoint ARNs
    observed: Sequence[str]       # observed endpoint ARNs
    #: observed weights aligned with ``observed``; None = unknown
    observed_weights: Sequence[Optional[int]] = ()
    #: [len(desired), F] float features; required for MODE_MODEL groups
    features: Optional[np.ndarray] = None
    #: explicit spec.weight (MODE_SPEC) or None
    spec_weight: Optional[int] = None
    #: False = static policy with null weight (MODE_NONE)
    model_planned: bool = True
    client_ip_preservation: bool = False
    #: stable planning-input fingerprint; drives incremental rescore
    fingerprint: int = 0
    #: owning shard (shard-major placement)
    shard: int = 0
    #: cached desired weights from the last plan, aligned with
    #: ``desired``; when the fingerprint still matches, the pass
    #: reuses these instead of rescoring
    cached_weights: Optional[Sequence[int]] = None

    def mode(self) -> int:
        if self.spec_weight is not None:
            return MODE_SPEC
        return MODE_MODEL if self.model_planned else MODE_NONE


@dataclass
class ColumnarFleet:
    """The packed fleet: shard-major grids + CSR score rows.

    Shapes: ``S`` shards x ``Gs`` groups per shard (padded) x ``E``
    endpoint slots; ``Ns`` packed score rows per shard (padded).
    Grids are numpy; the planner device_puts / shards them.
    """

    arns: InternTable
    groups: List[GroupState]          # real groups, shard-major order
    shards: int                       # S
    groups_per_shard: int             # Gs
    endpoints_cap: int                # E

    desired: np.ndarray               # [S, Gs, E] int32 intern ids
    observed: np.ndarray              # [S, Gs, E] int32 intern ids
    observed_w: np.ndarray            # [S, Gs, E] int32 (EMPTY=unknown)
    cached_w: np.ndarray              # [S, Gs, E] int32 last-planned
    weight_mode: np.ndarray           # [S, Gs] int32 MODE_*
    rescored: np.ndarray              # [S, Gs] bool
    fingerprints: np.ndarray          # [S, Gs] int64
    spec_w: np.ndarray                # [S, Gs] int32 (EMPTY if n/a)

    feat_rows: np.ndarray             # [S, Ns, F] float32
    row_seg: np.ndarray               # [S, Ns] int32 local group (Gs=pad)
    row_slot: np.ndarray              # [S, Ns] int32 endpoint slot

    #: (shard, local index) of each real group, aligned with ``groups``
    locations: List[Tuple[int, int]] = field(default_factory=list)

    @property
    def total_groups(self) -> int:
        return len(self.groups)

    @property
    def row_width(self) -> int:
        return self.feat_rows.shape[1]

    # -- flat views (the single-jit reference rung) ---------------------

    def flat_grids(self):
        """Grids flattened to [S*Gs, ...] for the unsharded program."""
        S, Gs, E = self.desired.shape
        return (self.desired.reshape(S * Gs, E),
                self.observed.reshape(S * Gs, E),
                self.observed_w.reshape(S * Gs, E),
                self.cached_w.reshape(S * Gs, E),
                self.weight_mode.reshape(S * Gs),
                self.spec_w.reshape(S * Gs))

    def flat_rows(self):
        """CSR rows flattened with GLOBAL group indices; pad rows get
        seg == S*Gs so a ``mode='drop'`` scatter discards them."""
        S, Ns, F = self.feat_rows.shape
        Gs = self.groups_per_shard
        seg = self.row_seg.astype(np.int64)
        shard_base = (np.arange(S, dtype=np.int64)[:, None]
                      * np.int64(Gs))
        global_seg = np.where(seg >= Gs, np.int64(S) * Gs,
                              seg + shard_base)
        return (self.feat_rows.reshape(S * Ns, F),
                global_seg.reshape(S * Ns).astype(np.int32),
                self.row_slot.reshape(S * Ns))


def _pad_rows_bucket(n: int, minimum: int = 8) -> int:
    """Round row counts up to a power-of-two bucket so the compiled
    program is reused across waves instead of recompiling per churn
    count."""
    b = max(minimum, 1)
    while b < n:
        b *= 2
    return b


def pack_fleet(groups: Sequence[GroupState], endpoints_cap: int,
               shards: int = 1, feature_dim: int = 8) -> ColumnarFleet:
    """Pack per-group planning state into the columnar fleet layout.

    Groups are placed shard-major (``GroupState.shard``); each shard's
    group count pads to the fleet-wide maximum, each shard's packed
    score-row count pads to a shared power-of-two bucket.  A group
    whose endpoint lists exceed ``endpoints_cap`` raises — silent
    truncation would strand endpoints exactly like the FleetPlanner
    encode path refuses to.
    """
    if shards < 1:
        raise ValueError("shards must be >= 1")
    table = InternTable()
    per_shard: List[List[GroupState]] = [[] for _ in range(shards)]
    for g in groups:
        if not 0 <= g.shard < shards:
            raise ValueError(
                f"group {g.key!r} names shard {g.shard}, fleet has "
                f"{shards}")
        for what, ids in (("desired", g.desired),
                          ("observed", g.observed)):
            if len(ids) > endpoints_cap:
                raise ValueError(
                    f"group {g.key!r} has {len(ids)} {what} endpoints, "
                    f"exceeding endpoints_cap={endpoints_cap}; raise "
                    f"the cap (silent truncation would strand "
                    f"endpoints)")
        per_shard[g.shard].append(g)

    S, E = shards, endpoints_cap
    Gs = max(1, max(len(b) for b in per_shard))
    desired = np.full((S, Gs, E), EMPTY, np.int32)
    observed = np.full((S, Gs, E), EMPTY, np.int32)
    observed_w = np.full((S, Gs, E), EMPTY, np.int32)
    cached_w = np.zeros((S, Gs, E), np.int32)
    weight_mode = np.full((S, Gs), MODE_NONE, np.int32)
    rescored = np.zeros((S, Gs), bool)
    fingerprints = np.zeros((S, Gs), np.int64)
    spec_w = np.full((S, Gs), EMPTY, np.int32)

    rows: List[List[Tuple[np.ndarray, int, int]]] = [
        [] for _ in range(shards)]
    ordered: List[GroupState] = []
    locations: List[Tuple[int, int]] = []
    for s, bucket in enumerate(per_shard):
        for gi, g in enumerate(bucket):
            ordered.append(g)
            locations.append((s, gi))
            for j, arn in enumerate(g.desired):
                desired[s, gi, j] = table.intern(arn)
            obs_w = list(g.observed_weights)
            for j, arn in enumerate(g.observed):
                observed[s, gi, j] = table.intern(arn)
                if j < len(obs_w) and obs_w[j] is not None:
                    observed_w[s, gi, j] = int(obs_w[j])
            mode = g.mode()
            weight_mode[s, gi] = mode
            fingerprints[s, gi] = np.int64(g.fingerprint)
            if mode == MODE_SPEC:
                spec_w[s, gi] = int(g.spec_weight)
            if g.cached_weights is not None:
                for j, w in enumerate(g.cached_weights):
                    if j < E and w is not None:
                        cached_w[s, gi, j] = int(w)
            # a MODE_MODEL group with no usable cache packs one feature
            # row per desired endpoint; a cache hit packs nothing (the
            # incremental wave's whole point) — the caller clears
            # ``cached_weights`` when the fingerprint moved
            if mode == MODE_MODEL and g.cached_weights is None:
                if g.features is None:
                    raise ValueError(
                        f"group {g.key!r} is model-planned with no "
                        f"cached weights but carries no features")
                feats = np.asarray(g.features, np.float32)
                if feats.shape != (len(g.desired), feature_dim):
                    raise ValueError(
                        f"group {g.key!r} features shape "
                        f"{feats.shape} != "
                        f"({len(g.desired)}, {feature_dim})")
                rescored[s, gi] = True
                for j in range(len(g.desired)):
                    rows[s].append((feats[j], gi, j))

    Ns = _pad_rows_bucket(max((len(r) for r in rows), default=1))
    feat_rows = np.zeros((S, Ns, feature_dim), np.float32)
    row_seg = np.full((S, Ns), Gs, np.int32)   # Gs = out-of-bounds pad
    row_slot = np.zeros((S, Ns), np.int32)
    for s in range(S):
        for k, (f, gi, j) in enumerate(rows[s]):
            feat_rows[s, k] = f
            row_seg[s, k] = gi
            row_slot[s, k] = j

    return ColumnarFleet(
        arns=table, groups=ordered, shards=S, groups_per_shard=Gs,
        endpoints_cap=E, desired=desired, observed=observed,
        observed_w=observed_w, cached_w=cached_w,
        weight_mode=weight_mode, rescored=rescored,
        fingerprints=fingerprints, spec_w=spec_w, feat_rows=feat_rows,
        row_seg=row_seg, row_slot=row_slot, locations=locations)


@dataclass
class GroupIntent:
    """One group's decoded mutation intents.  An empty ``ops`` list is
    the planner's converged verdict for the group — the read-only
    sweep answer."""

    key: str
    group_arn: str
    ops: List[object]
    #: planned desired weights by endpoint ARN (the cache feed)
    weights: Dict[str, int]


def decode_group_intent(key: str, group_arn: str,
                        desired: Sequence[str],
                        observed: Sequence[str],
                        has_target: bool,
                        client_ip_preservation: bool,
                        desired_w_row: np.ndarray,
                        add_row: np.ndarray, remove_row: np.ndarray,
                        reweight_row: np.ndarray) -> GroupIntent:
    """Decode ONE group's planner output rows into a
    :class:`GroupIntent` — removes, then adds at the planned weight,
    then re-weights, mirroring the per-object reconcile order.  Shared
    by the full-repack decode below and the resident planner's
    dirty-position decode (parallel/fleet_plan.py) so the two paths
    cannot drift apart."""
    from ..cloudprovider.aws.batcher import op_remove, op_set, op_weight

    ops: List[object] = []
    for j, arn in enumerate(observed):
        if remove_row[j]:
            ops.append(op_remove(arn))
    weights: Dict[str, int] = {}
    for j, arn in enumerate(desired):
        w = int(desired_w_row[j])
        if has_target:
            weights[arn] = w
        if add_row[j]:
            ops.append(op_set(
                arn, weight=w if has_target else None,
                client_ip_preservation=client_ip_preservation))
        elif has_target and reweight_row[j]:
            ops.append(op_weight(arn, w))
    return GroupIntent(key=key, group_arn=group_arn, ops=ops,
                       weights=weights)


def decode_intents(fleet: ColumnarFleet, desired_w: np.ndarray,
                   to_add: np.ndarray, to_remove: np.ndarray,
                   to_reweight: np.ndarray) -> List[GroupIntent]:
    """Nonzero diff rows -> EndpointOp intents, per real group.

    Inputs are the planner outputs reshaped ``[S, Gs, E]`` (numpy,
    post device_get).  The host loop here runs over DECODE output, not
    inside the jit path — rule L113 polices the device side.
    """
    out: List[GroupIntent] = []
    for g, (s, gi) in zip(fleet.groups, fleet.locations):
        out.append(decode_group_intent(
            g.key, g.group_arn, g.desired, g.observed,
            g.mode() != MODE_NONE, g.client_ip_preservation,
            desired_w[s, gi], to_add[s, gi], to_remove[s, gi],
            to_reweight[s, gi]))
    return out
