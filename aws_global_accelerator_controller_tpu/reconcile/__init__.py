"""Generic reconcile worker loop.

Mirrors reference pkg/reconcile/reconcile.go:17-91:

- pop a key from the rate-limited queue;
- resolve key -> object via the lister (``key_to_obj``); NotFound means the
  object was deleted -> ``process_delete``; otherwise hand a deep copy to
  ``process_create_or_update`` — listers return SHARED views of the
  informer cache (kube/informers.py), so this copy is the ONE defensive
  copy between the watch stream and the process func;
- dispatch on the outcome: NoRetryError -> drop (Forget is NOT called, as
  in the reference, so the failure count survives); an error carrying a
  ``retry_after`` hint (the resilience layer's budget/deadline/circuit
  errors, errors.retry_after_hint) -> Forget + AddAfter(hint); other
  error -> AddRateLimited; Result.requeue_after -> Forget + AddAfter;
  Result.requeue -> AddRateLimited; success -> Forget.

Steady-state fast path (``fingerprints``, reconcile/fingerprint.py):
when the dispatched key's pending enqueue originated from an informer
RESYNC and the live object still matches the fingerprint recorded at
its last successful sync, the key is skipped — Forget, one counter
bump, no ``apis.*`` call (lint rule L107 enforces that lexically).
Sweep-origin dispatches bypass the gate and run inside the cache's
sweep context so out-of-band AWS drift is re-verified and repaired on
a slow tier; event-origin dispatches always take the full path.
"""
from __future__ import annotations

import logging
import zlib
from contextlib import nullcontext
from dataclasses import dataclass
from typing import Callable, Optional

from .. import metrics
from ..simulation import clock as simclock
from ..errors import is_no_retry, is_not_found, retry_after_hint
from ..kube.workqueue import CLASS_INTERACTIVE, CLASS_KEEP, RateLimitingQueue
from ..tracing import default_ledger, default_tracer
from .fingerprint import (
    ORIGIN_RESYNC,
    ORIGIN_SWEEP,
    FingerprintCache,
)
from .traffic import dispatch_class

logger = logging.getLogger(__name__)


@dataclass
class Result:
    """Reconcile outcome (reference pkg/reconcile/reconcile.go:17-20)."""
    requeue: bool = False
    requeue_after: float = 0.0


KeyToObjFunc = Callable[[str], object]
ProcessDeleteFunc = Callable[[str], Result]
ProcessCreateOrUpdateFunc = Callable[[object], Result]


def process_next_work_item(
    queue: RateLimitingQueue,
    key_to_obj: KeyToObjFunc,
    process_delete: ProcessDeleteFunc,
    process_create_or_update: ProcessCreateOrUpdateFunc,
    get_timeout: Optional[float] = None,
    fingerprints: Optional[FingerprintCache] = None,
    shards=None,
) -> bool:
    """One worker iteration; returns False only on queue shutdown.

    ``get_timeout`` is an addition over the reference for clean thread
    shutdown: a ``get`` timeout yields True without processing.
    ``fingerprints`` arms the steady-state fast path (module
    docstring); None keeps the reference dispatch exactly.
    ``shards`` (sharding/shardset.py :class:`~..sharding.ShardSet`)
    arms shard-routed dispatch: keys whose shard this replica does not
    own are dropped (the owner converges them), and owned syncs run
    inside the shard's route guard — the thread is marked with the
    governing shard and the shard's fence gates every write attempt.
    """
    item, shutdown = queue.get(timeout=get_timeout)
    if shutdown:
        return False
    if item is None:  # timed out waiting; let the caller re-check stop state
        return True

    try:
        _reconcile_handler(item, queue, key_to_obj, process_delete,
                           process_create_or_update, fingerprints,
                           shards)
    except Exception:
        logger.exception("unhandled error reconciling %r", item)
    finally:
        queue.done(item)
    return True


def _reconcile_handler(key, queue, key_to_obj, process_delete,
                       process_create_or_update,
                       fingerprints: Optional[FingerprintCache] = None,
                       shards=None,
                       ) -> None:
    if not isinstance(key, str):
        queue.forget(key)
        logger.error("expected string in workqueue but got %r", key)
        return

    if shards is not None and not shards.owns_key(key):
        # routed to another replica's shard (a rebalance landed
        # between enqueue and this get): drop without error — the
        # owning replica converges the key on its own re-delivery
        queue.forget(key)
        if fingerprints is not None:
            fingerprints.claim_origin(key)
            fingerprints.clear_pending(key)
        logger.debug("key %r not owned by this replica's shards, "
                     "dropped", key)
        return

    start = simclock.monotonic()
    res = Result()
    err: Optional[Exception] = None
    obj = None
    origin = (fingerprints.claim_origin(key)
              if fingerprints is not None else None)
    # the tier this delivery rode (kube/workqueue.py): the class labels
    # the latency sample and marks the sync's thread for downstream
    # scheduling decisions (the coalescer's deadline-aware linger);
    # first_enqueued spans requeues so latency is honest event->converged
    meta = queue.claimed_meta(key) if hasattr(queue, "claimed_meta") \
        else None
    klass, enqueued_at = meta if meta is not None \
        else (CLASS_INTERACTIVE, start)
    first_enqueued = (fingerprints.pending_since(key, enqueued_at)
                      if fingerprints is not None else enqueued_at)
    # shard route guard (sharding/shardset.py): the sync runs marked
    # with its governing shard, whose fence gates every write attempt;
    # a rebalance racing this dispatch raises ShardNotOwnedError (a
    # NoRetryError) out of the guard and the key is dropped below
    route_guard = ((lambda: shards.guard(key)) if shards is not None
                   else nullcontext)
    # causal continuation (tracing.py): the event's trace context rode
    # the queue item — attach it so the reconcile span (and every
    # provider child, coalescer intent, chaos mark beneath it) joins
    # the event's trace across the queue/thread boundary
    ctx = queue.claimed_trace(key) if hasattr(queue, "claimed_trace") \
        else None
    if ctx is not None:
        ctx.hop("claimed")
    with default_tracer.attach(ctx), \
            default_tracer.span("reconcile", queue=queue.name or "queue",
                                key=key) as span:
        try:
            obj = key_to_obj(key)
        except Exception as e:
            if is_not_found(e):
                if fingerprints is not None:
                    fingerprints.invalidate(key)
                try:
                    with route_guard(), dispatch_class(klass):
                        res = process_delete(key) or Result()
                except Exception as de:
                    err = de
            else:
                span.attributes["outcome"] = "store_error"
                logger.error("unable to retrieve %r from store: %s", key, e)
                return
        else:
            # steady-state fast path: a resync-originated key whose
            # live object still matches its recorded fingerprint needs
            # no provider verification — skip before any apis.* call
            # (L107).  Event and sweep origins never match here:
            # note_event dropped the record, and sweep bypasses the
            # gate by design.
            if (fingerprints is not None and origin == ORIGIN_RESYNC
                    and fingerprints.matches(key, obj)):
                queue.forget(key)
                fingerprints.clear_pending(key)
                metrics.record_fastpath_skip(fingerprints.controller)
                span.attributes["outcome"] = "fastpath_skip"
                logger.debug("fingerprint unchanged for %r, skipped "
                             "(%.6fs)", key, simclock.monotonic() - start)
                return
            # a sweep delivery is a DEEP VERIFY only when the recorded
            # fingerprint still matches (the Kubernetes side is
            # provably unchanged, so any provider mutation it submits
            # repairs out-of-band drift).  A sweep hitting a changed
            # or never-synced object is just an ordinary sync —
            # counting its real convergence work as "drift repair"
            # would make the counter lie at cold start.
            sweep = (fingerprints is not None and origin == ORIGIN_SWEEP
                     and fingerprints.matches(key, obj))
            try:
                if sweep:
                    with route_guard(), fingerprints.sweep_verify(), \
                            dispatch_class(klass):
                        res = (process_create_or_update(obj.deep_copy())
                               or Result())
                else:
                    with route_guard(), dispatch_class(klass):
                        res = (process_create_or_update(obj.deep_copy())
                               or Result())
            except Exception as ce:
                err = ce

        if err is not None:
            if fingerprints is not None:
                # any provider error means the recorded fingerprint no
                # longer proves a converged state
                fingerprints.invalidate(key)
            if is_no_retry(err):
                outcome = "no_retry_error"
                if fingerprints is not None:
                    # terminally dropped: the pending change will never
                    # converge via retries — close its latency window
                    fingerprints.clear_pending(key)
                logger.error("error syncing %r: %s", key, err)
            elif (hint := retry_after_hint(err)) > 0:
                # the resilient call layer already burned an in-call
                # retry budget (or found the circuit open) and knows
                # when trying again is worthwhile: park the key for
                # that long instead of hot-requeuing into the same
                # brownout (Forget resets the failure count — the
                # in-call budget IS the backoff; the park bounds the
                # requeue rate)
                outcome = "retry_exhausted"
                queue.forget(key)
                # a coalesced flush failure (cloudprovider/aws/batcher)
                # hands the SAME hint to every key whose intent rode
                # the batch; identical parks would re-converge the
                # whole cohort into one thundering requeue wave, so a
                # key-stable jitter in [1.0, 1.25) decorrelates them
                # (deterministic per key — no park-time flapping)
                jitter = 1.0 + 0.25 * (zlib.crc32(key.encode()) / 2**32)
                if ctx is not None:
                    ctx.hop("requeue")
                queue.add_after(key, hint * jitter, klass=CLASS_KEEP,
                                ctx=ctx)
                logger.warning("error syncing %r, retry budget "
                               "exhausted; parked %.2fs: %s",
                               key, hint * jitter, err)
            else:
                outcome = "error"
                if ctx is not None:
                    ctx.hop("requeue")
                queue.add_rate_limited(key, klass=CLASS_KEEP, ctx=ctx)
                logger.error("error syncing %r, and requeued: %s", key, err)
            span.error = f"{type(err).__name__}: {err}"
        elif res.requeue_after > 0:
            outcome = "requeue_after"
            queue.forget(key)
            # rollout step waits and other timed re-deliveries carry
            # the trace forward: a ramp's whole multi-requeue journey
            # stays one trace id
            if ctx is not None:
                ctx.hop("requeue")
            queue.add_after(key, res.requeue_after, klass=CLASS_KEEP,
                            ctx=ctx)
            logger.info("successfully synced %r, but requeued after %.1fs",
                        key, res.requeue_after)
        elif res.requeue:
            outcome = "requeue"
            if ctx is not None:
                ctx.hop("requeue")
            queue.add_rate_limited(key, klass=CLASS_KEEP, ctx=ctx)
            logger.info("successfully synced %r, but requeued", key)
        else:
            outcome = "success"
            queue.forget(key)
            if fingerprints is not None and obj is not None:
                # the state this sync verified/converged is what the
                # next resync re-delivery will be compared against
                fingerprints.record(key, obj)
            if fingerprints is not None:
                fingerprints.clear_pending(key)
            # event->converged: first enqueue of the pending change to
            # this success, spanning any requeues/parks in between
            metrics.record_reconcile_latency(
                queue.name or "queue", klass,
                simclock.monotonic() - first_enqueued)
            if ctx is not None:
                # close the trace and assemble the per-stage ledger
                # record (queued/planned/coalesced/inflight/baked) —
                # the stage-attributable event->converged story
                ctx.hop("converged")
                default_ledger.record(queue.name or "queue", key, ctx)
            logger.debug("successfully synced %r (%.3fs)",
                         key, simclock.monotonic() - start)
        span.attributes["outcome"] = outcome
    metrics.record_sync(queue.name or "queue", outcome,
                        simclock.monotonic() - start)
