"""Resident columnar fleet state + per-shard dirty masks (ISSUE 16).

PR 11's :func:`~.columnar.pack_fleet` rebuilds the whole columnar
layout from scratch every wave — a Python loop over every group.  At
1M endpoint-groups that re-pack is the new quadratic: steady state
mutates <1% of the fleet per wave, yet every wave re-paid the 1M-row
pack.  This module keeps the packed arrays RESIDENT between waves and
tracks exactly what changed:

- **Host truth**: the same shard-major ``[S, cap, E]`` grids
  ``pack_fleet`` builds, plus per-slot metadata, mutated in place by
  :meth:`ResidentFleet.upsert` / :meth:`ResidentFleet.remove`.  The
  :class:`~.interning.InternTable` is append-only, so table growth
  never invalidates a clean shard — dense ids are stable for the
  fleet's lifetime.
- **Dirty masks**: every mutation marks its (shard, slot); informer
  watch events feed :meth:`note_dirty` (controller/fleetsweep.py wires
  update notifications through it).  A wave's planner drains
  :meth:`take_dirty` and replans ONLY the dirty shards
  (parallel/fleet_plan.py ``ResidentFleetPlanner``), splicing results
  into the resident plan.
- **Capacity growth**: slot capacity doubles when a shard fills;
  growth bumps ``generation`` so the planner knows its device-resident
  copies (and compiled shapes) are stale.  Host state survives growth
  untouched — only the padding changes.
- **Oracle snapshot**: :meth:`snapshot_groups` reconstructs the
  :class:`~.columnar.GroupState` list for the full-repack ORACLE path
  (``pack_fleet`` + ``WholeFleetPlanner``) — the authoritative
  verification surface the incremental plan must bit-match (lint rule
  L118 keeps full repacks confined to oracle/verify entry points).

Memory bound: ``max_groups`` LRU-evicts the least-recently-upserted
key (binding churn over a controller's months-long life must never
grow the resident arrays without bound; an evicted key just
re-inserts — and rescores — on its next wave).

Purity contract (lint rule L113 covers this module like columnar.py):
host-side state maintenance only, never ``apis.*``; the device pass
lives in parallel/fleet_plan.py.
"""
from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..ops.diff import EMPTY
from .columnar import MODE_MODEL, MODE_NONE, MODE_SPEC, GroupState
from .interning import InternTable

#: upsert outcomes (returned so callers/tests can assert dirtiness
#: without reaching into the mask internals)
UPSERT_INSERTED = "inserted"
UPSERT_UPDATED = "updated"
UPSERT_MOVED = "moved"        # shard handoff: old AND new shard dirty
UPSERT_UNCHANGED = "unchanged"


@dataclass
class _Slot:
    """Per-slot host metadata the grids cannot carry (strings live on
    the host side of the interning boundary; features feed rescores)."""

    __slots__ = ("key", "group_arn", "nd", "no", "mode",
                 "client_ip_preservation", "spec_weight", "features")

    key: str
    group_arn: str
    nd: int                        # len(desired)
    no: int                        # len(observed)
    mode: int                      # MODE_* at upsert time
    client_ip_preservation: bool
    spec_weight: Optional[int]
    features: Optional[np.ndarray]  # [nd, F] float32 (MODE_MODEL)


class ResidentFleet:
    """Persistent columnar fleet arrays + per-shard dirty masks.

    NOT thread-safe by itself: the one consumer (the sweep planner's
    wave, or the bench driver) owns mutation; concurrent
    :meth:`note_dirty` from event handlers is safe under the GIL
    (set.add on an existing shard set).
    """

    def __init__(self, shards: int, endpoints_cap: int,
                 feature_dim: int = 8, groups_per_shard: int = 8,
                 max_groups: Optional[int] = None):
        if shards < 1:
            raise ValueError("shards must be >= 1")
        self.shards = shards
        self.endpoints_cap = endpoints_cap
        self.feature_dim = feature_dim
        self.cap = max(1, groups_per_shard)
        self.max_groups = max_groups
        self.arns = InternTable()
        #: bumps on capacity growth — device residency + compiled
        #: shapes keyed on it are stale when it moves
        self.generation = 0

        S, cap, E = shards, self.cap, endpoints_cap
        self.desired = np.full((S, cap, E), EMPTY, np.int32)
        self.observed = np.full((S, cap, E), EMPTY, np.int32)
        self.observed_w = np.full((S, cap, E), EMPTY, np.int32)
        self.cached_w = np.zeros((S, cap, E), np.int32)
        self.weight_mode = np.full((S, cap), MODE_NONE, np.int32)
        self.spec_w = np.full((S, cap), EMPTY, np.int32)
        self.fingerprints = np.zeros((S, cap), np.int64)
        #: cached_w row valid (False = model group needs a rescore)
        self.has_cache = np.zeros((S, cap), bool)

        # guarded-by: external: sweep-owner thread only — the fleet
        # is single-writer by contract (see class docstring)
        self._slots: List[List[Optional[_Slot]]] = [
            [None] * cap for _ in range(S)]
        # guarded-by: external: sweep-owner thread only
        self._free: List[List[int]] = [
            list(range(cap - 1, -1, -1)) for _ in range(S)]
        # guarded-by: external: sweep-owner thread only
        self._index: Dict[str, Tuple[int, int]] = {}
        # guarded-by: external: sweep-owner thread only
        self._lru: "OrderedDict[str, None]" = OrderedDict()
        # guarded-by: external: sweep owner clears; note_dirty()'s
        # cross-thread set.add is a single GIL-atomic op by design
        self._dirty: List[Set[int]] = [set() for _ in range(S)]

    # -- introspection --------------------------------------------------

    def __len__(self) -> int:
        return len(self._index)

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def location(self, key: str) -> Optional[Tuple[int, int]]:
        return self._index.get(key)

    def slot(self, s: int, gi: int) -> Optional[_Slot]:
        return self._slots[s][gi]

    def dirty_shard_count(self) -> int:
        return sum(1 for d in self._dirty if d)

    def dirty_group_count(self) -> int:
        return sum(len(d) for d in self._dirty)

    # -- mutation (the dirty-mask feed) ---------------------------------

    def _id_row(self, what: str, key: str,
                ids: Sequence[str]) -> np.ndarray:
        E = self.endpoints_cap
        if len(ids) > E:
            raise ValueError(
                f"group {key!r} has {len(ids)} {what} endpoints, "
                f"exceeding endpoints_cap={E}; raise the cap (silent "
                f"truncation would strand endpoints)")
        row = np.full(E, EMPTY, np.int32)
        for j, a in enumerate(ids):
            row[j] = self.arns.intern(a)
        return row

    def _weight_row(self, weights: Sequence[Optional[int]],
                    n: int) -> np.ndarray:
        row = np.full(self.endpoints_cap, EMPTY, np.int32)
        for j, w in enumerate(weights):
            if j < n and w is not None:
                row[j] = int(w)
        return row

    def upsert(self, g: GroupState, force_rescore: bool = False) -> str:
        """Install/refresh one group's planning inputs; marks the
        owning shard dirty IFF something changed (an identical upsert
        is free — the steady-state fast path).

        ``g.features`` semantics: ``None`` on a MODE_MODEL group means
        "score inputs unchanged, reuse the resident cache" (the
        caller's fingerprint said so); provided features are compared
        and trigger a rescore when they moved.  ``g.cached_weights``
        is ignored — the resident ``cached_w`` grid IS the cache.
        """
        if not 0 <= g.shard < self.shards:
            raise ValueError(f"group {g.key!r} names shard {g.shard}, "
                             f"fleet has {self.shards}")
        moved = False
        prior_feats: Optional[np.ndarray] = None
        loc = self._index.get(g.key)
        if loc is not None and loc[0] != g.shard:
            # shard handoff: clear the old placement (old shard dirty),
            # then insert fresh on the new owner — carrying the stored
            # features across so an input-preserving move needs no
            # re-featurize from the caller
            old = self._slots[loc[0]][loc[1]]
            if old is not None:
                prior_feats = old.features
            self.remove(g.key)
            loc = None
            moved = True

        mode = g.mode()
        d_row = self._id_row("desired", g.key, g.desired)
        o_row = self._id_row("observed", g.key, g.observed)
        ow_row = self._weight_row(g.observed_weights, len(g.observed))
        sw = int(g.spec_weight) if mode == MODE_SPEC else EMPTY
        feats = (np.asarray(g.features, np.float32)
                 if g.features is not None else None)
        if feats is not None and feats.shape != (len(g.desired),
                                                 self.feature_dim):
            raise ValueError(
                f"group {g.key!r} features shape {feats.shape} != "
                f"({len(g.desired)}, {self.feature_dim})")

        if loc is None:
            s, gi = self._place(g.key, g.shard)
            verdict = UPSERT_MOVED if moved else UPSERT_INSERTED
            rescore = mode == MODE_MODEL
        else:
            s, gi = loc
            slot = self._slots[s][gi]
            desired_changed = not (
                np.array_equal(self.desired[s, gi], d_row))
            changed = (
                desired_changed
                or int(self.fingerprints[s, gi]) != int(g.fingerprint)
                or int(self.weight_mode[s, gi]) != mode
                or int(self.spec_w[s, gi]) != sw
                or slot.client_ip_preservation
                != g.client_ip_preservation
                or not np.array_equal(self.observed[s, gi], o_row)
                or not np.array_equal(self.observed_w[s, gi], ow_row))
            feats_changed = (
                feats is not None
                and (slot.features is None
                     or not np.array_equal(slot.features, feats)))
            if not changed and not feats_changed and not force_rescore:
                self._touch(g.key)
                return UPSERT_UNCHANGED
            verdict = UPSERT_UPDATED
            rescore = mode == MODE_MODEL and (
                desired_changed or feats_changed or force_rescore
                or not bool(self.has_cache[s, gi]))

        if mode == MODE_MODEL and feats is None:
            prior = self._slots[s][gi]
            if prior is not None and prior.features is not None:
                prior_feats = prior.features
            if (prior_feats is not None
                    and prior_feats.shape[0] == len(g.desired)):
                feats = prior_feats      # inputs intact, keep stored
            elif rescore:
                raise ValueError(
                    f"group {g.key!r} is model-planned and needs a "
                    f"rescore but carries no features")

        self.desired[s, gi] = d_row
        self.observed[s, gi] = o_row
        self.observed_w[s, gi] = ow_row
        self.weight_mode[s, gi] = mode
        self.spec_w[s, gi] = sw
        self.fingerprints[s, gi] = np.int64(g.fingerprint)
        if rescore:
            self.has_cache[s, gi] = False
        self._slots[s][gi] = _Slot(
            key=g.key, group_arn=g.group_arn, nd=len(g.desired),
            no=len(g.observed), mode=mode,
            client_ip_preservation=g.client_ip_preservation,
            spec_weight=g.spec_weight if mode == MODE_SPEC else None,
            features=feats if mode == MODE_MODEL else None)
        self._dirty[s].add(gi)
        self._touch(g.key)
        self._evict(keep=g.key)
        return verdict

    def remove(self, key: str) -> bool:
        """Drop a group: slot cleared to padding, shard dirty (the
        wave must replan the shard so the resident plan forgets it)."""
        loc = self._index.pop(key, None)
        if loc is None:
            return False
        s, gi = loc
        self.desired[s, gi] = EMPTY
        self.observed[s, gi] = EMPTY
        self.observed_w[s, gi] = EMPTY
        self.cached_w[s, gi] = 0
        self.weight_mode[s, gi] = MODE_NONE
        self.spec_w[s, gi] = EMPTY
        self.fingerprints[s, gi] = 0
        self.has_cache[s, gi] = False
        self._slots[s][gi] = None
        self._free[s].append(gi)
        self._dirty[s].add(gi)
        self._lru.pop(key, None)
        return True

    def note_dirty(self, key: str) -> bool:
        """Mark a key's shard dirty WITHOUT changing state — the
        informer watch-event feed: an update notification forces the
        next wave to replan the shard even though the describe hasn't
        happened yet (the wave's upsert then carries the real delta)."""
        loc = self._index.get(key)
        if loc is None:
            return False
        self._dirty[loc[0]].add(loc[1])
        return True

    def invalidate_scores(self) -> int:
        """Model hot-reload: every model-planned group's cached
        weights are stale — drop the caches and dirty their shards so
        the next wave rescores the lot (from the stored features)."""
        n = 0
        for s in range(self.shards):
            for gi, slot in enumerate(self._slots[s]):
                if slot is not None and slot.mode == MODE_MODEL:
                    self.has_cache[s, gi] = False
                    self._dirty[s].add(gi)
                    n += 1
        return n

    def take_dirty(self) -> Dict[int, List[int]]:
        """Drain the dirty masks: {shard: sorted dirty slots}.  The
        caller (one wave) owns everything drained; a crash between
        take and splice re-dirties via the next upsert/describe."""
        out: Dict[int, List[int]] = {}
        for s in range(self.shards):
            if self._dirty[s]:
                out[s] = sorted(self._dirty[s])
                self._dirty[s] = set()
        return out

    def mark_scored(self, positions: Sequence[Tuple[int, int]]) -> None:
        """The wave planned these positions: model slots' caches are
        valid again (the planner wrote the fresh rows to cached_w)."""
        for s, gi in positions:
            if self.weight_mode[s, gi] == MODE_MODEL \
                    and self._slots[s][gi] is not None:
                self.has_cache[s, gi] = True

    # -- placement / growth ---------------------------------------------

    def _place(self, key: str, s: int) -> Tuple[int, int]:
        if not self._free[s]:
            self._grow()
        gi = self._free[s].pop()
        self._index[key] = (s, gi)
        return s, gi

    def _grow(self) -> None:
        """Double slot capacity fleet-wide.  Host arrays pad in place;
        ``generation`` bumps so the planner re-uploads device state
        and re-specialises its compiled shapes.  Dirty masks and the
        resident plan survive — only padding was added."""
        old, new = self.cap, max(2, self.cap * 2)
        grow = new - old

        def pad3(a, fill):
            return np.pad(a, ((0, 0), (0, grow), (0, 0)),
                          constant_values=fill)

        def pad2(a, fill):
            return np.pad(a, ((0, 0), (0, grow)), constant_values=fill)

        self.desired = pad3(self.desired, EMPTY)
        self.observed = pad3(self.observed, EMPTY)
        self.observed_w = pad3(self.observed_w, EMPTY)
        self.cached_w = pad3(self.cached_w, 0)
        self.weight_mode = pad2(self.weight_mode, MODE_NONE)
        self.spec_w = pad2(self.spec_w, EMPTY)
        self.fingerprints = pad2(self.fingerprints, 0)
        self.has_cache = pad2(self.has_cache, False)
        for s in range(self.shards):
            self._slots[s].extend([None] * grow)
            self._free[s].extend(range(new - 1, old - 1, -1))
        self.cap = new
        self.generation += 1

    def _touch(self, key: str) -> None:
        self._lru[key] = None
        self._lru.move_to_end(key)

    def _evict(self, keep: str) -> None:
        if self.max_groups is None:
            return
        while len(self._index) > self.max_groups:
            evicted, _ = self._lru.popitem(last=False)
            if evicted == keep:      # never evict the key just placed
                self._touch(keep)
                continue
            self.remove(evicted)

    # -- the oracle edge ------------------------------------------------

    def group_state(self, key: str) -> Optional[GroupState]:
        loc = self._index.get(key)
        if loc is None:
            return None
        return self._state_at(*loc)

    def _state_at(self, s: int, gi: int) -> GroupState:
        slot = self._slots[s][gi]
        sof = self.arns.string_of
        desired = [sof(int(i)) for i in self.desired[s, gi][:slot.nd]]
        observed = [sof(int(i)) for i in self.observed[s, gi][:slot.no]]
        observed_w = [None if int(w) == EMPTY else int(w)
                      for w in self.observed_w[s, gi][:slot.no]]
        return GroupState(
            key=slot.key, group_arn=slot.group_arn, desired=desired,
            observed=observed, observed_weights=observed_w,
            features=slot.features,
            spec_weight=slot.spec_weight,
            model_planned=slot.mode == MODE_MODEL,
            client_ip_preservation=slot.client_ip_preservation,
            fingerprint=int(self.fingerprints[s, gi]), shard=s,
            cached_weights=None)

    def snapshot_groups(self) -> List[GroupState]:
        """Reconstruct every resident group for the FULL-REPACK ORACLE
        (``cached_weights=None`` throughout: the oracle rescores
        everything, and determinism makes rescored == cached bit-exact
        — tests/test_resident_planner.py pins it).  Shard-major order,
        matching ``pack_fleet``'s placement so oracle outputs align
        positionally with the resident arrays per shard."""
        out: List[GroupState] = []
        for s in range(self.shards):
            for gi in range(self.cap):
                if self._slots[s][gi] is not None:
                    out.append(self._state_at(s, gi))
        return out

    def occupied_positions(self) -> List[Tuple[int, int]]:
        """(shard, slot) of every resident group, shard-major — the
        order :meth:`snapshot_groups` emits, which is also the order
        ``pack_fleet`` re-places the snapshot in per shard."""
        return [(s, gi)
                for s in range(self.shards)
                for gi in range(self.cap)
                if self._slots[s][gi] is not None]
