"""Traffic-class dispatch context: which tier's work this thread runs.

The reconcile dispatch wraps every sync in :func:`dispatch_class` with
the traffic class the workqueue delivered the key under (interactive =
watch events / user-visible changes, background = resync waves, drift
sweeps — kube/workqueue.py).  Downstream layers consult
:func:`current_class` instead of threading a parameter through every
provider signature — the same thread-local pattern the sweep context
uses (reconcile/fingerprint.py ``in_sweep``).

The one consumer today is the write coalescer's deadline-aware linger
(cloudprovider/aws/batcher.py): a cohort with an interactive waiter
flushes immediately instead of paying the batching linger tuned for
bulk cohorts — the NCCL move of picking the low-latency protocol for
small messages and the bandwidth protocol for bulk (PAPERS.md),
applied to flush scheduling.

Unset (no dispatch on the stack — tests, CLI seeding tools, provider
internals) reads as BACKGROUND: the linger/batching contract predates
traffic classes, so anything not explicitly delivered as interactive
by the workqueue keeps the bulk size-or-deadline semantics.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

from ..kube.workqueue import CLASS_BACKGROUND, CLASS_INTERACTIVE  # noqa: F401

_tls = threading.local()


@contextmanager
def dispatch_class(klass: str):
    """Mark this thread as running a sync delivered under ``klass``
    for the duration of the block (re-entrant: restores the prior
    value on exit)."""
    prior = getattr(_tls, "klass", None)
    _tls.klass = klass
    try:
        yield
    finally:
        _tls.klass = prior


def current_class() -> str:
    """The traffic class of the sync on this thread's stack
    (CLASS_BACKGROUND when none is marked)."""
    return getattr(_tls, "klass", None) or CLASS_BACKGROUND
