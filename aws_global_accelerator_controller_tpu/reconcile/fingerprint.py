"""Desired-state fingerprints: the steady-state fast path's gate.

The informer resync is the level-triggered backstop the reconcile
design relies on — but re-running a full provider-verifying sync for
every object every period makes an IDLE fleet of N services cost O(N)
reconciles (and a burst of AWS reads) per period.  The fingerprint
layer removes that cost the same way the read path removed O(fleet)
scans: do the cheap local check always, the expensive global one
rarely.

Each controller computes a canonical fingerprint of exactly the
spec/annotation/status fields its sync READS (the builder is a pure
function over informer-cache state — lint rule L107 keeps ``apis.*``
out of it).  On a successful sync the fingerprint is recorded here,
keyed by object key + generation.  A later RESYNC-originated delivery
of the same key whose live object still matches is skipped by the
reconcile dispatch before any provider call; everything else — real
watch events, provider errors, circuit-breaker opens — invalidates the
record and the next dispatch takes the full path.

Because a fingerprint only proves the KUBERNETES side is unchanged,
it can go stale against out-of-band AWS mutation.  The tiered
drift-verification sweep covers that: every ``sweep_every`` resync
waves each key gets ONE delivery tagged ``ORIGIN_SWEEP`` which
bypasses the gate entirely (key-stable spread, so ~1/sweep_every of
the fleet deep-verifies per wave).  The sweep sync is an ordinary
full sync — it rides the provider's singleflight verify pairs and
fleet sweeps, repairs whatever drifted, and re-records the
fingerprint on success.  Provider mutations submitted while a sweep
sync is on the stack are counted as drift repairs
(``drift_repairs_total``; the write coalescer calls
:func:`note_provider_mutation` on every submit).

Origins (per pending enqueue, event wins over sweep wins over resync):

- ``ORIGIN_EVENT``   a real watch event enqueued the key: never skip
- ``ORIGIN_SWEEP``   this key's deep-verify wave: never skip; when
                     the recorded fingerprint still MATCHES the live
                     object the sync runs inside the sweep context
                     (verify counted, mutations attributed to drift
                     repair — the Kubernetes side is provably
                     unchanged), otherwise it is an ordinary sync
- ``ORIGIN_RESYNC``  plain resync re-delivery: skip iff the live
                     object matches the recorded fingerprint
"""
from __future__ import annotations

import hashlib
import logging
import threading
import weakref
import zlib
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass, replace as dc_replace
from typing import Callable, Optional

from .. import metrics
from .interning import intern_str
from ..analysis import locks
from ..autotune import knobs as knobcat

logger = logging.getLogger(__name__)

ORIGIN_EVENT = "event"
ORIGIN_SWEEP = "sweep"
ORIGIN_RESYNC = "resync"

# event > sweep > resync: a pending enqueue's origin is only ever
# upgraded (a resync re-delivery must not demote a real event's claim
# while the key waits in the queue)
_PRECEDENCE = {ORIGIN_RESYNC: 0, ORIGIN_SWEEP: 1, ORIGIN_EVENT: 2}


@dataclass(frozen=True)
class FingerprintConfig:
    """Steady-state fast-path knobs.  ``enabled=False`` is the A/B
    escape hatch: every resync re-delivery takes the full
    provider-verifying sync (what ``bench.py steady-state`` measures
    the win against)."""

    enabled: bool = True
    # tiered drift verification: each key gets one gate-bypassing deep
    # verify every this-many resync waves (~10 periods ≈ 5 minutes at
    # the default 30s resync); 0 disables the sweep entirely (resync
    # re-deliveries then never reach the provider while unchanged —
    # out-of-band AWS drift goes undetected until a real event).
    # Default owned by the knob catalog (autotune/knobs.py, L117).
    sweep_every: int = knobcat.SWEEP_EVERY
    # bound on recorded fingerprints; oldest-recorded evicted first
    # (an evicted key just takes one full sync on its next resync)
    max_entries: int = 100_000


# live caches, so resilience-layer signals (a circuit opening) can
# drop every recorded fingerprint at once: an open circuit means the
# provider's answers were failing regionally — nothing recorded
# through that window deserves trust
_caches: "weakref.WeakSet[FingerprintCache]" = weakref.WeakSet()
_caches_lock = threading.Lock()

# thread-local sweep context: set by the reconcile dispatch around a
# sweep-origin sync so provider mutations submitted on this stack are
# attributed to drift repair
_sweep_tls = threading.local()


def invalidate_all_caches(reason: str = "") -> None:
    """Drop every recorded fingerprint in every live cache (the
    circuit/chaos invalidation hook — resilience/breaker.py calls this
    on a transition to open)."""
    with _caches_lock:
        caches = list(_caches)
    for cache in caches:
        cache.invalidate_all(reason)


def in_sweep() -> bool:
    """True while a sweep-origin (deep-verify) sync runs on this
    thread — controllers consult this to bypass their own no-change
    short-circuits (the EndpointGroupBinding controller's early
    return would otherwise hide out-of-band endpoint-group drift from
    the sweep)."""
    return getattr(_sweep_tls, "depth", 0) > 0


def note_provider_mutation(n: int = 1) -> None:
    """``n`` provider mutation intents COMMITTED (the write
    coalescer's submit surface calls this after the flush carrying
    them succeeded — a rejected or parked flush counts nothing).
    Attributed as drift repairs when a sweep-origin sync is on this
    thread's stack: the Kubernetes side was provably unchanged
    (fingerprints warm), so the mutations can only be repairing
    AWS-side drift."""
    if n > 0 and in_sweep():
        for _ in range(n):
            metrics.record_drift_repair()


class FingerprintCache:
    """One controller queue's fingerprint gate.

    ``fingerprint_fn(obj)`` returns the canonical tuple of fields the
    controller's sync reads (pure over informer state; never
    ``apis.*`` — L107).  The digest is recorded on successful sync
    and consulted only for resync-originated dispatches.
    """

    def __init__(self, controller: str,
                 fingerprint_fn: Callable[[object], object],
                 config: Optional[FingerprintConfig] = None,
                 skip_veto: Optional[Callable[[object], bool]] = None,
                 sweep_gate: Optional[Callable[[str, int], bool]]
                 = None):
        self.controller = controller
        self.config = config or FingerprintConfig()
        self._fn = fingerprint_fn
        # sweep_gate(key, wave) -> True downgrades a sweep-due key to
        # an ordinary resync delivery: its deep verify is already
        # answered elsewhere — the multi-region digest exchange
        # (topology/digest.py RegionDigestGate.allow_skip), one
        # gateway read per region per wave instead of N cross-region
        # verifying sweeps.  Fail-open: a gate error (or None, the
        # default) leaves the sweep tier untouched.  Unlike the
        # builder, the gate MAY reach the provider — it runs only for
        # sweep-due keys, which were headed for a full provider
        # verify anyway; the fast-path skip itself stays
        # provider-free (L107).
        self._sweep_gate = sweep_gate
        # skip_veto(obj) -> True forces the full sync path regardless
        # of a matching record: the safe-rollout interplay — a mid-ramp
        # object's convergence is DRIVEN by timed re-deliveries, and a
        # stale skip would stall the ramp at its current step forever.
        # Pure over object state like the builder itself (L107).
        self._skip_veto = skip_veto
        self._lock = locks.make_lock(f"fingerprint[{controller}]")
        # key -> (generation, digest), insertion-ordered for eviction
        self._fp: "OrderedDict[str, tuple]" = OrderedDict()
        # key -> pending enqueue origin (claimed at dispatch)
        self._origin: dict = {}
        # key -> wave of the last deep verify (or digest answer): the
        # stride-robust sweep schedule (note_resync docstring)
        self._sweep_last: dict = {}
        # key -> first-enqueue monotonic time of the change currently
        # converging: event->converged latency must span requeues and
        # parks, so the first dispatch records it and retries reuse it
        # until the key converges (or is dropped) — reconcile dispatch
        self._pending_since: dict = {}
        with _caches_lock:
            _caches.add(self)

    # -- fingerprinting -------------------------------------------------

    def fingerprint(self, obj) -> "tuple[int, bytes]":
        """(generation, digest) of the live object.  The digest
        canonicalizes whatever the builder returns via ``repr`` — the
        builders return tuples of primitives, so the representation is
        deterministic across processes.  Raw 20-byte digest, not the
        hex string: at the 100k-entry cache bound the hex spelling
        alone cost ~4 MB (the ISSUE-13 memory diet)."""
        fields = self._fn(obj)
        digest = hashlib.sha1(repr(fields).encode()).digest()
        return obj.metadata.generation, digest

    def set_sweep_every(self, sweep_every: int) -> None:
        """Retune the drift-sweep period live (the autotune registry's
        apply surface).  The config object is swapped, never mutated —
        it may be shared by every controller's cache, and a tuned
        period must not rewrite a sibling registry's defaults."""
        self.config = dc_replace(self.config,
                                 sweep_every=max(0, int(sweep_every)))

    # -- enqueue-origin bookkeeping ------------------------------------

    def note_event(self, key: str) -> None:
        """A real watch event enqueued ``key``: the recorded
        fingerprint no longer describes a successfully synced state,
        and the pending dispatch must take the full path."""
        with self._lock:
            self._fp.pop(key, None)
            self._origin[key] = ORIGIN_EVENT

    def note_resync(self, key: str, wave: int) -> str:
        """A resync wave re-delivered ``key``; returns the origin the
        pending dispatch will carry.  Key-stable sweep tiering: each
        key deep-verifies once per ``sweep_every`` waves, phased at
        ``crc32(key) mod sweep_every`` so the fleet's sweeps spread
        evenly across the period's waves.  Dueness is tracked as
        LAST-SWEPT WAVE (``wave - last >= sweep_every``), not as an
        exact residue match: under the virtual clock resync ticks
        quantize (simulation/clock.py) and wave numbers advance in
        strides, so an exact-residue test silently starves every key
        whose residue the stride sequence never lands on — with a 2s
        period under the 5s quantum, ~60% of a fleet would NEVER deep
        verify.  The stride-robust form also reacts correctly when
        the autotune engine retunes ``sweep_every`` live.
        ``sweep_every <= 0`` disables the sweep (no delivery is ever
        sweep-tagged)."""
        every = self.config.sweep_every
        due = False
        if every > 0:
            with self._lock:
                last = self._sweep_last.get(key)
                if last is None:
                    # phase the first due wave at the key's residue
                    # slot (the spread), then once per period after
                    r = zlib.crc32(key.encode()) % every
                    last = wave + ((r - wave) % every) - every
                    self._sweep_last[intern_str(key)] = last
                due = (wave - last) >= every
        answered = False
        if due and self._sweep_gate is not None:
            # outside the cache lock: the gate's digest exchange is a
            # (once-per-region-per-wave) provider read
            try:
                if self._sweep_gate(key, wave):
                    due = False
                    answered = True   # the exchange WAS the verify
            except Exception:
                logger.debug("sweep gate failed for %r; sweeping",
                             key, exc_info=True)
        origin = ORIGIN_SWEEP if due else ORIGIN_RESYNC
        with self._lock:
            if due or answered:
                self._sweep_last[intern_str(key)] = wave
            have = self._origin.get(key)
            if have is None or _PRECEDENCE[origin] > _PRECEDENCE[have]:
                self._origin[key] = origin
            return self._origin[key]

    def claim_origin(self, key: str) -> Optional[str]:
        """Consume the pending origin for ``key`` at dispatch.  None
        (no recorded origin — e.g. a directly ``add``-ed key) is
        treated like an event by callers: full sync."""
        with self._lock:
            return self._origin.pop(key, None)

    # -- event->converged latency bookkeeping --------------------------

    def pending_since(self, key: str, enqueued_at: float) -> float:
        """First-enqueue time of the change ``key`` is converging:
        records ``enqueued_at`` (the queue's claimed-delivery stamp)
        on the first dispatch, returns the recorded one on retries —
        so the latency a success records spans requeues and parks."""
        with self._lock:
            return self._pending_since.setdefault(key, enqueued_at)

    def clear_pending(self, key: str) -> None:
        """The change converged (or was terminally dropped): the next
        dispatch of ``key`` starts a fresh latency window."""
        with self._lock:
            self._pending_since.pop(key, None)

    # -- the gate -------------------------------------------------------

    def matches(self, key: str, obj) -> bool:
        """True iff the live object's fingerprint equals the one
        recorded at the last successful sync (same generation AND same
        digest) and no skip veto is in force (a mid-ramp rollout pins
        the key to the full path).  Never consults the provider
        (L107)."""
        if not self.config.enabled:
            return False
        if self._skip_veto is not None and self._skip_veto(obj):
            return False
        with self._lock:
            have = self._fp.get(key)
        if have is None:
            return False
        return have == self.fingerprint(obj)

    def record(self, key: str, obj) -> None:
        """Record a successful sync of ``obj``.  A real event that
        landed mid-sync keeps its claim: the pending event-origin
        dispatch re-syncs regardless of what is recorded here."""
        if not self.config.enabled:
            return
        fp = self.fingerprint(obj)
        key = intern_str(key)  # one canonical key string per cache entry
        with self._lock:
            self._fp.pop(key, None)
            self._fp[key] = fp
            while len(self._fp) > self.config.max_entries:
                self._fp.popitem(last=False)

    def invalidate(self, key: str) -> None:
        """Drop one key's record (provider error, deletion)."""
        with self._lock:
            self._fp.pop(key, None)
            self._sweep_last.pop(key, None)

    def invalidate_all(self, reason: str = "") -> None:
        with self._lock:
            self._fp.clear()

    def invalidate_shard(self, shard_id: int, shard_of_key) -> int:
        """Drop every record whose key maps to ``shard_id`` under
        ``shard_of_key`` — the per-shard partition of this cache.
        Called on shard-lease LOSS (sharding; controller shard
        listeners): while another replica owns the shard its syncs
        mutate AWS state this cache's records know nothing about, so a
        later re-acquisition must re-verify cold (the PR-6
        restart-recovery path per shard) instead of trusting a
        pre-loss skip.  ``shard_of_key`` runs OUTSIDE the cache lock
        (it may consult listers); returns how many records dropped."""
        with self._lock:
            keys = list(self._fp)
        # route mapping runs UNLOCKED (it may consult listers), then
        # every matched key drops in ONE locked pass — O(n) separate
        # lock round-trips here would contend with reconcile workers
        # from the shard-lease manager's handoff path
        matched = [key for key in keys if shard_of_key(key) == shard_id]
        dropped = 0
        with self._lock:
            for key in matched:
                dropped += self._fp.pop(key, None) is not None
                self._pending_since.pop(key, None)
                self._sweep_last.pop(key, None)
        return dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._fp)

    # -- sweep context --------------------------------------------------

    @contextmanager
    def sweep_verify(self):
        """Wraps a sweep-origin sync: counts the deep verify and marks
        the thread so provider mutations submitted inside are
        attributed to drift repair."""
        metrics.record_drift_sweep_verify()
        _sweep_tls.depth = getattr(_sweep_tls, "depth", 0) + 1
        try:
            yield
        finally:
            _sweep_tls.depth -= 1
