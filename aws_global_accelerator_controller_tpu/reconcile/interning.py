"""Dense string interning (PR-11's ``InternTable``) + the shared
map-side canonicalizer (ISSUE 13's memory diet).

Extracted from reconcile/columnar.py so the provider's fleet index and
the informer caches can intern ARN/hostname strings WITHOUT importing
the columnar planner (which pulls jax at module load — the controller
import path must stay accelerator-free).  columnar re-exports both
names, so planner call sites are unchanged.
"""
from __future__ import annotations

import sys
from typing import Dict, List


class InternTable:
    """Dense string <-> int32 interning (append-only).

    Dense ids — not hashes — are the device-side tokens: equality on
    device is exact (no 31-bit CRC collisions silently merging two
    ARNs into one endpoint) and decode is an O(1) list index.
    """

    def __init__(self):
        self._ids: Dict[str, int] = {}
        self._strings: List[str] = []

    def intern(self, s: str) -> int:
        got = self._ids.get(s)
        if got is not None:
            return got
        i = len(self._strings)
        self._ids[s] = i
        self._strings.append(s)
        return i

    def string_of(self, i: int) -> str:
        return self._strings[i]

    def __len__(self) -> int:
        return len(self._strings)

    def canonical(self, s: str) -> str:
        """The table's single shared instance of ``s`` (dense-id side;
        map-side callers use :func:`intern_str`)."""
        return self._strings[self.intern(s)]


def intern_str(s: str) -> str:
    """Canonicalize ``s`` so equal strings from different parses share
    ONE allocation — the fleet index, discovery cache, fingerprint
    keys and informer maps at 100k-1M keys pay for each distinct
    ARN/hostname once.  Backed by ``sys.intern``: lock-free, and an
    interned string is RELEASED when its last reference dies, so
    delete churn cannot grow the table forever (the planner's
    append-only :class:`InternTable` keeps its dense-id contract for
    arrays; maps only need the canonical-instance half)."""
    return sys.intern(s)
