"""Chaos flight recorder: a bounded black-box for convergence stalls.

When something goes visibly wrong — a circuit breaker opens, a rollout
rolls back, the overload shedder fires, a bench leg breaches its SLO,
or a test asks explicitly — the recorder freezes the recent span ring,
the convergence ledger, a metrics-registry counter delta since arming,
and every registered seeded-chaos decision log into ONE correlated
JSON dump under ``bench_artifacts/``.  ``hack/flight_replay.py``
renders a dump as a per-key timeline and as Chrome trace-event format
(viewable in chrome://tracing / Perfetto).

Contracts:

- **Bounded**: the dump reads bounded rings only (span ring, ledger
  ring, chaos decision deques) and snapshots counters — never gauge
  callbacks (a gauge callback may take the very lock the triggering
  subsystem holds: the breaker's state gauge vs a trigger fired from
  inside the breaker transition).
- **Debounced**: one dump per trigger reason per ``cooldown`` seconds;
  a brownout tripping breakers across regions writes one black box,
  not one per failure.
- **Fail-open**: a dump that cannot be written logs and returns None —
  the recorder must never add a failure mode to the failure path it
  observes.
"""
from __future__ import annotations

import json
import logging
import os
import threading
from typing import Callable, Dict, List, Optional

from .tracing import default_ledger, default_tracer
from .simulation import clock as simclock

logger = logging.getLogger(__name__)

DEFAULT_DIR = os.path.join("bench_artifacts", "flight")

#: dumps kept per directory: arm() prunes the oldest beyond this so a
#: long-lived process (or a chaos suite re-arming per scenario) never
#: grows the black box without bound
KEEP_DUMPS = 20

#: trigger reasons wired into the runtime (tests may use any string)
TRIGGER_CIRCUIT_OPEN = "circuit_open"
TRIGGER_ROLLOUT_ROLLBACK = "rollout_rollback"
TRIGGER_OVERLOAD_SHED = "overload_shed"
TRIGGER_SLO_BREACH = "slo_breach"


class FlightRecorder:
    def __init__(self, directory: str = DEFAULT_DIR,
                 cooldown: float = 30.0,
                 tracer=None, ledger=None, registry=None):
        self.directory = directory
        self.cooldown = cooldown
        self._tracer = tracer or default_tracer
        self._ledger = ledger or default_ledger
        self._registry = registry
        self._lock = threading.Lock()
        self._armed = False
        self._baseline: Dict[str, float] = {}
        self._last_dump: Dict[str, float] = {}
        self._seq = 0
        # name -> fn() -> list of decision dicts (the seeded chaos
        # engines' decision logs; fake cloud, kube plane)
        self._chaos_sources: Dict[str, Callable[[], List[dict]]] = {}
        self._dumps: List[str] = []

    # -- wiring ---------------------------------------------------------

    def _resolve_registry(self):
        if self._registry is not None:
            return self._registry
        from . import metrics
        return metrics.default_registry

    def arm(self, registry=None) -> None:
        """Start recording: snapshot the metrics baseline the next
        dump's delta is computed against.  Re-arming re-baselines."""
        if registry is not None:
            self._registry = registry
        reg = self._resolve_registry()
        with self._lock:
            self._armed = True
            self._baseline = reg.counters_snapshot()
            self._last_dump.clear()
        self._prune()

    def _prune(self, keep: Optional[int] = None) -> None:
        """Retention: drop the oldest dumps beyond ``keep`` (bounded
        black box on disk, like the rings in memory).  ``None`` reads
        the module's ``KEEP_DUMPS`` at call time (testable knob)."""
        if keep is None:
            keep = KEEP_DUMPS
        try:
            if not os.path.isdir(self.directory):
                return
            dumps = sorted(
                (os.path.join(self.directory, f)
                 for f in os.listdir(self.directory)
                 if f.startswith("flight_") and f.endswith(".json")),
                key=os.path.getmtime)
            for path in dumps[:-keep] if keep else dumps:
                os.unlink(path)
        except OSError:
            logger.debug("flight recorder: prune failed", exc_info=True)

    def disarm(self) -> None:
        with self._lock:
            self._armed = False

    def armed(self) -> bool:
        with self._lock:
            return self._armed

    def add_chaos_source(self, name: str,
                         fn: Callable[[], List[dict]]) -> None:
        """Register a seeded chaos engine's decision log (its bounded
        ``decision_log()``) under ``name`` in every future dump."""
        with self._lock:
            self._chaos_sources[name] = fn

    def dumps(self) -> List[str]:
        with self._lock:
            return list(self._dumps)

    # -- the trigger ----------------------------------------------------

    def trigger(self, reason: str, detail: str = "") -> Optional[str]:
        """Freeze the black box NOW (debounced per reason).  Returns
        the dump path, or None when disarmed / cooling down / the
        write failed."""
        now = simclock.monotonic()
        with self._lock:
            if not self._armed:
                return None
            last = self._last_dump.get(reason)
            if last is not None and now - last < self.cooldown:
                return None
            self._last_dump[reason] = now
            self._seq += 1
            seq = self._seq
            sources = dict(self._chaos_sources)
            baseline = dict(self._baseline)
        try:
            reg = self._resolve_registry()
            current = reg.counters_snapshot()
            delta = {k: round(v - baseline.get(k, 0.0), 6)
                     for k, v in sorted(current.items())
                     if v != baseline.get(k, 0.0)}
            chaos = {}
            for name, fn in sources.items():
                try:
                    chaos[name] = list(fn())
                except Exception as e:
                    chaos[name] = [{"error": str(e)}]
            dump = {
                "reason": reason,
                "detail": detail,
                "wall": simclock.wall(),
                "pid": os.getpid(),
                "spans": self._tracer.recent(limit=0),
                "ledger": self._ledger.snapshot(limit=0),
                "metrics_delta": delta,
                "chaos": chaos,
            }
            os.makedirs(self.directory, exist_ok=True)
            path = os.path.join(
                self.directory,
                f"flight_{reason}_{os.getpid()}_{seq}.json")
            with open(path, "w") as f:
                json.dump(dump, f, indent=1, default=str)
            with self._lock:
                self._dumps.append(path)
            from . import metrics
            metrics.record_flight_dump(reason)
            logger.warning("flight recorder: dumped %s (%s) to %s",
                           reason, detail, path)
            return path
        except Exception:
            logger.exception("flight recorder: dump for %r failed "
                             "(fail-open)", reason)
            return None


default_recorder = FlightRecorder()


def trigger(reason: str, detail: str = "") -> Optional[str]:
    """Module-level trigger against the default recorder — what the
    runtime hook points (breaker open, rollout rollback, overload
    shed) call; a no-op until someone arms the recorder."""
    return default_recorder.trigger(reason, detail)
