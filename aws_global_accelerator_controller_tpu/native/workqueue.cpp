// Native rate-limited delaying workqueue.
//
// C++ implementation of the client-go util/workqueue semantics that the
// reference's controllers rely on (workqueue.NewNamedRateLimitingQueue with
// the default controller rate limiter, e.g. reference
// pkg/controller/globalaccelerator/controller.go:64-65).  Exposed through a
// plain C ABI consumed via ctypes (kube/native_workqueue.py); drop-in
// behavioural match for kube/workqueue.py:RateLimitingQueue so the two are
// interchangeable behind new_rate_limiting_queue().
//
// Semantics mirrored exactly:
//  - dedup invariants: an item is queued at most once (dirty set); re-adds
//    while a worker holds the item (processing set) are deferred to done();
//  - delaying adds via a min-heap, promoted inside get() (no waker thread:
//    the waiting consumer computes its own wakeup deadline and add_after
//    notifies, so the earliest-deadline sleeper re-evaluates); pending
//    entries are deduped per item keeping the EARLIEST deadline (two parks
//    must keep the earliest wake time — the Python queue's _waiting_index);
//  - per-item exponential backoff (base*2^failures, capped) maxed with a
//    global token bucket whose token count may go negative, matching
//    client-go's rate.Limiter reservation behaviour and the Python port;
//  - shutdown() wakes all waiters; get() on a drained shut-down queue
//    reports shutdown.
//
// Priority tiers (kube/workqueue.py module docstring): items carry a
// traffic class — interactive (1) or background (0) — each with its own
// FIFO deque.  get() draws by AGED priority: effective priority = class
// base + head wait / aging_horizon, higher head wins, interactive on
// ties; so interactive changes bypass resync backlogs while a background
// item is served within ~one aging horizon even under a saturating
// interactive storm.  The class is a property of the item across requeues
// (klass = -1 on the *2 entry points means "keep"); an interactive add of
// an item waiting in the background deque promotes it in place.
//
// Thread-safety: one mutex per queue; get() blocks with the GIL released
// (ctypes releases it for the duration of the foreign call), so Python
// worker threads block here truly concurrently.

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <queue>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

constexpr int kBackground = 0;
constexpr int kInteractive = 1;
constexpr int kKeepClass = -1;

struct WaitingEntry {
  Clock::time_point ready_at;
  uint64_t seq;
  std::string item;
  bool operator>(const WaitingEntry& o) const {
    if (ready_at != o.ready_at) return ready_at > o.ready_at;
    return seq > o.seq;
  }
};

struct Queue {
  std::mutex mu;
  std::condition_variable cv;

  // one FIFO per tier: [kBackground], [kInteractive]
  std::deque<std::string> tiers[2];
  std::unordered_set<std::string> dirty;
  std::unordered_set<std::string> processing;
  // item -> traffic class while anywhere in the queue machinery
  std::unordered_map<std::string, int> klass;
  // item -> REQUEST time of the pending delivery (backoff included —
  // the latency stamp)
  std::unordered_map<std::string, Clock::time_point> enqueued_at;
  // item -> time the item became RUNNABLE (entered its tier deque) —
  // what aging, tier_oldest_age and the age watermark measure: a
  // parked retry's deliberate backoff is latency, not queue wait
  std::unordered_map<std::string, Clock::time_point> runnable_at;
  bool shutting_down = false;

  std::priority_queue<WaitingEntry, std::vector<WaitingEntry>,
                      std::greater<WaitingEntry>>
      waiting;
  // item -> (deadline, seq) of the LIVE heap entry: dedupe keeping the
  // earliest wake; heap entries not matching are stale and skipped
  std::unordered_map<std::string, std::pair<Clock::time_point, uint64_t>>
      waiting_index;
  uint64_t waiting_seq = 0;

  // ItemExponentialFailureRateLimiter state.
  std::unordered_map<std::string, int> failures;
  double base_delay;
  double max_delay;

  // aged-priority horizon (seconds); <= 0 disables aging
  double aging_horizon;

  // BucketRateLimiter state (tokens may go negative, like golang.org/x/time
  // reservations and the Python port).
  double qps;
  double burst;
  double tokens;
  Clock::time_point last_refill;

  Queue(double qps_, int burst_, double base_delay_, double max_delay_,
        double aging_horizon_)
      : base_delay(base_delay_),
        max_delay(max_delay_),
        aging_horizon(aging_horizon_),
        qps(qps_),
        burst(static_cast<double>(burst_)),
        tokens(static_cast<double>(burst_)),
        last_refill(Clock::now()) {}

  int resolve_class_locked(const std::string& item, int k) {
    auto it = klass.find(item);
    int have = it == klass.end() ? kKeepClass : it->second;
    if (k == kKeepClass) return have == kKeepClass ? kInteractive : have;
    int want = k ? kInteractive : kBackground;
    // upgrade-only while tracked: a background re-tag must not demote
    // pending interactive work (kube/workqueue.py twin)
    if (want == kBackground && have == kInteractive) return kInteractive;
    return want;
  }

  void drop_if_gone_locked(const std::string& item) {
    if (!dirty.count(item) && !processing.count(item) &&
        !waiting_index.count(item)) {
      klass.erase(item);
      enqueued_at.erase(item);
      runnable_at.erase(item);
    }
  }

  // Callers hold mu.  `front` (delay-heap promotions) enters at the
  // HEAD of the tier: a parked retry's request predates everything
  // enqueued while it was parked, so joining the tail would make its
  // wait grow with storm depth (kube/workqueue.py twin).
  void add_locked(const std::string& item, int k, bool front = false) {
    if (shutting_down) return;
    k = resolve_class_locked(item, k);
    auto prior = klass.find(item);
    int prior_k = prior == klass.end() ? kKeepClass : prior->second;
    klass[item] = k;
    if (dirty.count(item)) {
      // interactive re-add of an item waiting in the background tier:
      // promote it in place, keeping its enqueue time (latency is
      // measured from the oldest pending event)
      if (k == kInteractive && prior_k == kBackground &&
          !processing.count(item)) {
        auto& bq = tiers[kBackground];
        for (auto it = bq.begin(); it != bq.end(); ++it) {
          if (*it == item) {
            bq.erase(it);
            tiers[kInteractive].push_back(item);
            cv.notify_one();
            break;
          }
        }
      }
      return;
    }
    dirty.insert(item);
    Clock::time_point now = Clock::now();
    enqueued_at.emplace(item, now);
    if (processing.count(item)) return;
    runnable_at[item] = now;
    auto& tq = tiers[k];
    // only ahead of strictly-younger work: same-batch promotions stay
    // FIFO (kube/workqueue.py twin)
    bool ahead = false;
    if (front && !tq.empty()) {
      auto mine = enqueued_at.find(item);
      auto head = enqueued_at.find(tq.front());
      ahead = mine != enqueued_at.end() &&
              (head == enqueued_at.end() || mine->second < head->second);
    }
    if (ahead)
      tq.push_front(item);
    else
      tq.push_back(item);
    cv.notify_one();
  }

  // Move every due waiting entry onto the live queue.  Callers hold mu.
  void promote_ready_locked(Clock::time_point now) {
    // Match the Python queue: after shutdown() the waker exits and waiting
    // items are never delivered — promoting here would hand a worker an
    // item mid-teardown.
    if (shutting_down) return;
    while (!waiting.empty() && waiting.top().ready_at <= now) {
      WaitingEntry top = waiting.top();
      waiting.pop();
      auto idx = waiting_index.find(top.item);
      if (idx == waiting_index.end() || idx->second.first != top.ready_at ||
          idx->second.second != top.seq)
        continue;  // superseded by an earlier deadline
      waiting_index.erase(idx);
      add_locked(top.item, kKeepClass, /*front=*/true);
    }
  }

  // The aged-priority draw (kube/workqueue.py _pick_tier_locked):
  // returns the tier to pop from, or -1 when both are empty.
  int pick_tier_locked(Clock::time_point now) {
    bool have_i = !tiers[kInteractive].empty();
    bool have_b = !tiers[kBackground].empty();
    if (!have_i) return have_b ? kBackground : -1;
    if (!have_b) return kInteractive;
    if (aging_horizon <= 0) return kInteractive;
    auto wait_of = [&](const std::string& item) {
      auto it = runnable_at.find(item);
      if (it == runnable_at.end()) return 0.0;
      return std::chrono::duration<double>(now - it->second).count();
    };
    double i_wait = wait_of(tiers[kInteractive].front());
    double b_wait = wait_of(tiers[kBackground].front());
    if (b_wait > aging_horizon + i_wait) return kBackground;
    return kInteractive;
  }

  void schedule_after_locked(const std::string& item, double delay_s,
                             int k) {
    if (shutting_down) return;
    if (delay_s <= 0) {
      add_locked(item, k);
      return;
    }
    klass[item] = resolve_class_locked(item, k);
    // latency stamps start at the REQUEST: the backoff a delayed add
    // waits out is part of event->converged (kube/workqueue.py twin)
    enqueued_at.emplace(item, Clock::now());
    Clock::time_point deadline =
        Clock::now() + std::chrono::duration_cast<Clock::duration>(
                           std::chrono::duration<double>(delay_s));
    auto idx = waiting_index.find(item);
    if (idx != waiting_index.end() && idx->second.first <= deadline)
      return;  // an earlier wake is already scheduled
    uint64_t seq = ++waiting_seq;
    waiting_index[item] = {deadline, seq};
    waiting.push(WaitingEntry{deadline, seq, item});
    cv.notify_all();
  }

  double exp_delay_for(int f) const {
    double exp_delay = base_delay;
    for (int i = 0; i < f && exp_delay < max_delay; ++i) exp_delay *= 2.0;
    return exp_delay > max_delay ? max_delay : exp_delay;
  }

  // Combined limiter delay in seconds (max of exponential + bucket),
  // charging one failure + one token.  The bucket's deficit is bounded
  // at 2x burst (kube/workqueue.py BucketRateLimiter: an unbounded
  // reservation backlog would park the next lone event for minutes).
  // Callers hold mu.
  double rate_limit_when_locked(const std::string& item) {
    double exp_delay = exp_delay_for(failures[item]++);

    Clock::time_point now = Clock::now();
    double elapsed = std::chrono::duration<double>(now - last_refill).count();
    tokens = std::min(burst, tokens + elapsed * qps);
    last_refill = now;
    double bucket_delay = 0.0;
    if (tokens >= 1.0) {
      tokens -= 1.0;
    } else {
      double deficit = 1.0 - tokens;
      tokens -= 1.0;
      if (tokens < -2.0 * burst) tokens = -2.0 * burst;
      bucket_delay = deficit / qps;
    }
    return exp_delay > bucket_delay ? exp_delay : bucket_delay;
  }

  // The delay a DEDUPLICATED add consults: no failure charged, no
  // token consumed (kube/workqueue.py ItemExponential...peek).
  double rate_limit_peek_locked(const std::string& item) {
    auto it = failures.find(item);
    return exp_delay_for(it == failures.end() ? 0 : it->second);
  }
};

}  // namespace

extern "C" {

void* aga_wq_new2(double qps, int burst, double base_delay, double max_delay,
                  double aging_horizon) {
  return new Queue(qps, burst, base_delay, max_delay, aging_horizon);
}

void* aga_wq_new(double qps, int burst, double base_delay, double max_delay) {
  return aga_wq_new2(qps, burst, base_delay, max_delay, 2.0);
}

void aga_wq_free(void* h) { delete static_cast<Queue*>(h); }

void aga_wq_add2(void* h, const char* item, int klass) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->add_locked(item, klass);
}

void aga_wq_add(void* h, const char* item) { aga_wq_add2(h, item, kKeepClass); }

// Returns 0 = item copied into buf, 1 = shutdown-and-drained, 2 = timeout,
// 3 = buf too small (len written to *need).  timeout_s < 0 means block
// until an item arrives or shutdown.  out_klass (nullable) receives the
// claimed item's traffic class; out_wait_s (nullable) its queue wait in
// seconds (enqueue -> this get) — the latency stamp's raw material.
int aga_wq_get2(void* h, char* buf, int buflen, double timeout_s, int* need,
                int* out_klass, double* out_wait_s) {
  Queue* q = static_cast<Queue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  Clock::time_point deadline{};
  bool bounded = timeout_s >= 0;
  if (bounded)
    deadline = Clock::now() + std::chrono::duration_cast<Clock::duration>(
                                  std::chrono::duration<double>(timeout_s));
  for (;;) {
    Clock::time_point now = Clock::now();
    q->promote_ready_locked(now);
    if (!q->tiers[0].empty() || !q->tiers[1].empty()) break;
    if (q->shutting_down) return 1;
    if (bounded && now >= deadline) return 2;
    // Sleep until the caller deadline or the next delayed item, whichever
    // comes first; add_after/add/shutdown notify to re-evaluate sooner.
    Clock::time_point wake{};
    bool have_wake = false;
    if (bounded) {
      wake = deadline;
      have_wake = true;
    }
    if (!q->waiting.empty()) {
      Clock::time_point r = q->waiting.top().ready_at;
      if (!have_wake || r < wake) wake = r;
      have_wake = true;
    }
    if (have_wake)
      q->cv.wait_until(lk, wake);
    else
      q->cv.wait(lk);
  }
  Clock::time_point now = Clock::now();
  int tier = q->pick_tier_locked(now);
  std::string item = q->tiers[tier].front();
  q->tiers[tier].pop_front();
  q->processing.insert(item);
  q->dirty.erase(item);
  int n = static_cast<int>(item.size());
  if (need) *need = n;
  if (n + 1 > buflen) {
    // Undo so the caller can retry with a bigger buffer.
    q->processing.erase(item);
    q->dirty.insert(item);
    q->tiers[tier].push_front(item);
    return 3;
  }
  if (out_klass) {
    auto it = q->klass.find(item);
    *out_klass = it == q->klass.end() ? kInteractive : it->second;
  }
  if (out_wait_s) {
    auto it = q->enqueued_at.find(item);
    *out_wait_s =
        it == q->enqueued_at.end()
            ? 0.0
            : std::chrono::duration<double>(now - it->second).count();
  }
  q->enqueued_at.erase(item);
  q->runnable_at.erase(item);
  std::memcpy(buf, item.data(), n);
  buf[n] = '\0';
  return 0;
}

int aga_wq_get(void* h, char* buf, int buflen, double timeout_s, int* need) {
  return aga_wq_get2(h, buf, buflen, timeout_s, need, nullptr, nullptr);
}

void aga_wq_done(void* h, const char* item) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->processing.erase(item);
  if (q->dirty.count(item)) {
    q->runnable_at[item] = Clock::now();
    q->tiers[q->resolve_class_locked(item, kKeepClass)].push_back(item);
    q->cv.notify_one();
  } else {
    q->drop_if_gone_locked(item);
  }
}

void aga_wq_add_after2(void* h, const char* item, double delay_s, int klass) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->schedule_after_locked(item, delay_s, klass);
}

void aga_wq_add_after(void* h, const char* item, double delay_s) {
  aga_wq_add_after2(h, item, delay_s, kKeepClass);
}

// Returns the delay applied, so callers/metrics can observe backoff.
// The limiter is charged once per SCHEDULED delivery: an add deduped
// into an already-runnable item is a plain class-upgrade no-op, one
// for an item parked in the delay heap only peeks (it may pull the
// wake earlier within the current backoff) — kube/workqueue.py
// add_rate_limited, where the rationale lives.
double aga_wq_add_rate_limited2(void* h, const char* item, int klass) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  if (q->shutting_down) return 0.0;
  double delay;
  if (q->dirty.count(item)) {
    delay = 0.0;
  } else if (q->waiting_index.count(item)) {
    delay = q->rate_limit_peek_locked(item);
  } else {
    delay = q->rate_limit_when_locked(item);
  }
  q->schedule_after_locked(item, delay, klass);
  return delay;
}

double aga_wq_add_rate_limited(void* h, const char* item) {
  return aga_wq_add_rate_limited2(h, item, kKeepClass);
}

void aga_wq_forget(void* h, const char* item) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->failures.erase(item);
}

// Purge a PENDING item: tier slot, dirty mark, live delay-heap entry
// (the heap node goes stale and is skipped on pop) and limiter state.
// An item a worker holds is not interrupted — only its pending
// re-delivery is cancelled.  Returns 1 when anything was removed.
// The per-shard queue ownership hook (kube/workqueue.py remove()):
// a shard lost to a rebalance purges its backlog instead of burning
// workers on syncs the dispatch would drop anyway.
int aga_wq_remove(void* h, const char* item) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  std::string key(item);
  int removed = 0;
  if (q->dirty.erase(key)) {
    removed = 1;
    if (!q->processing.count(key)) {
      for (auto& tier : q->tiers) {
        for (auto it = tier.begin(); it != tier.end(); ++it) {
          if (*it == key) {
            tier.erase(it);
            break;
          }
        }
      }
    }
  }
  if (q->waiting_index.erase(key)) removed = 1;
  q->failures.erase(key);
  q->drop_if_gone_locked(key);
  return removed;
}

int aga_wq_num_requeues(void* h, const char* item) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  auto it = q->failures.find(item);
  return it == q->failures.end() ? 0 : it->second;
}

int aga_wq_len(void* h) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->promote_ready_locked(Clock::now());
  return static_cast<int>(q->tiers[0].size() + q->tiers[1].size());
}

int aga_wq_tier_len(void* h, int klass) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  return static_cast<int>(q->tiers[klass ? kInteractive : kBackground].size());
}

// Seconds the tier's head item has been RUNNABLE (0.0 when empty) —
// backs the workqueue_oldest_age_seconds{queue,tier} gauge and the
// age-watermark overload signal.  Deliberately not the request stamp:
// a promoted retry's backoff was a scheduling decision, not queue
// congestion (kube/workqueue.py twin).
double aga_wq_tier_oldest_age(void* h, int klass) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  auto& tier = q->tiers[klass ? kInteractive : kBackground];
  if (tier.empty()) return 0.0;
  auto it = q->runnable_at.find(tier.front());
  if (it == q->runnable_at.end()) return 0.0;
  double age = std::chrono::duration<double>(Clock::now() - it->second).count();
  return age > 0.0 ? age : 0.0;
}

int aga_wq_waiting_len(void* h) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  return static_cast<int>(q->waiting_index.size());
}

// Retune the aged-priority horizon live (the autotune engine's apply
// surface — kube/workqueue.py set_scheduling).  Takes effect on the
// next get(); <= 0 disables aging, like the constructor value.
void aga_wq_set_aging(void* h, double aging_horizon) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->aging_horizon = aging_horizon;
}

void aga_wq_shutdown(void* h) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->shutting_down = true;
  q->cv.notify_all();
}

int aga_wq_shutting_down(void* h) {
  Queue* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->shutting_down ? 1 : 0;
}

}  // extern "C"
