"""Native (C++) runtime components.

The compute path of this framework is JAX/XLA (``ops/``, ``parallel/``);
this package holds the native runtime around it.  Today that is the
rate-limited workqueue at the heart of the reconcile scheduler — the
analogue of client-go's Go-native ``util/workqueue`` used by the reference
(pkg/controller/globalaccelerator/controller.go:64-65).

Libraries are compiled lazily from the shipped sources with ``g++`` on
first use and cached next to the source; everything degrades gracefully to
the pure-Python implementations when no toolchain is available.
"""
from __future__ import annotations

import logging
import os
import subprocess
import sys
import tempfile
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_build_lock = threading.Lock()
_LIB_SUFFIX = ".dylib" if sys.platform == "darwin" else ".so"


def _lib_path(stem: str) -> str:
    return os.path.join(_NATIVE_DIR, f"_{stem}{_LIB_SUFFIX}")


def ensure_library(stem: str) -> Optional[str]:
    """Compile ``<stem>.cpp`` into ``_<stem>.so`` if needed.

    Returns the library path, or None when it cannot be built (no g++, or
    compilation failed).  Rebuilds when the source is newer than the cached
    library.  Safe under concurrent callers (in-process lock + atomic
    rename for other processes).
    """
    src = os.path.join(_NATIVE_DIR, f"{stem}.cpp")
    lib = _lib_path(stem)
    if not os.path.exists(src):
        return None
    with _build_lock:
        try:
            if (os.path.exists(lib)
                    and os.path.getmtime(lib) >= os.path.getmtime(src)):
                return lib
        except OSError:
            pass
        tmp = None
        try:
            # mkstemp inside the guard: an unwritable package dir (read-only
            # site-packages) must degrade to the Python queue, not raise.
            fd, tmp = tempfile.mkstemp(suffix=_LIB_SUFFIX, dir=_NATIVE_DIR)
            os.close(fd)
            cmd = ["g++", "-std=c++17", "-O2", "-shared", "-fPIC",
                   "-pthread", src, "-o", tmp]
            # the whole point of _build_lock is to serialize the
            # (rare, startup-only) g++ build; every other caller
            # SHOULD block here rather than race the compiler
            proc = subprocess.run(  # race: build-once
                cmd, capture_output=True, text=True, timeout=120)
            if proc.returncode != 0:
                logger.warning("native build of %s failed:\n%s", stem,
                               proc.stderr[-2000:])
                os.unlink(tmp)
                return None
            os.replace(tmp, lib)
            return lib
        except (OSError, subprocess.SubprocessError) as exc:
            logger.warning("native build of %s unavailable: %s", stem, exc)
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            return None
