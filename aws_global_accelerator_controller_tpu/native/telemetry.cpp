// Native telemetry data loader: background batch generation + ring buffer.
//
// The compute track trains on fleet-telemetry batches (models/traffic.py
// synthetic_batch: features [G, E, F] ~ N(0, 1), health/validity Bernoulli
// masks, target = capacity-proportional weights among healthy+valid
// endpoints).  Generating those on the Python side serialises with the
// training loop; this loader is the framework's native input pipeline: a
// pool of C++ threads fills a bounded ring of ready batches, and the
// consumer pops with the GIL released (ctypes releases it for the foreign
// call), so batch N+1 is generated while the device runs step N.  The
// reference repo has no data path at all (it is a Kubernetes controller,
// SURVEY.md preamble); this is the data-loader role a training framework
// needs, done native like the workqueue (native/workqueue.cpp).
//
// Exposed through a plain C ABI consumed via ctypes
// (models/loader.py: TelemetryLoader), mirroring native_workqueue.py.
//
// Randomness: one splitmix64-seeded xoshiro256++ stream per worker thread
// (seed, thread index) -> deterministic PER THREAD, but batch ordering in
// the ring depends on thread scheduling; callers needing bit-exact
// reproducibility use the JAX synthetic_batch path instead (the CLI
// default).  Normals via Box-Muller on uniform doubles.

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<float> features;  // snapshot [G, E, F] or window [T, G, E, F]
  std::vector<uint8_t> mask;    // [G, E]
  std::vector<float> target;    // [G, E]; per_step window: [T, G, E]
};

// -- PRNG: splitmix64 seeding + xoshiro256++ --------------------------------

static inline uint64_t splitmix64(uint64_t& x) {
  uint64_t z = (x += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

struct Rng {
  uint64_t s[4];
  explicit Rng(uint64_t seed) {
    for (int i = 0; i < 4; i++) s[i] = splitmix64(seed);
  }
  static inline uint64_t rotl(uint64_t x, int k) {
    return (x << k) | (x >> (64 - k));
  }
  uint64_t next() {
    const uint64_t result = rotl(s[0] + s[3], 23) + s[0];
    const uint64_t t = s[1] << 17;
    s[2] ^= s[0];
    s[3] ^= s[1];
    s[1] ^= s[2];
    s[0] ^= s[3];
    s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }
  // uniform in [0, 1) with 53-bit resolution
  double uniform() { return (next() >> 11) * 0x1.0p-53; }
  // standard normal via Box-Muller (one value per call; cache the pair)
  bool has_spare = false;
  double spare = 0.0;
  double normal() {
    if (has_spare) {
      has_spare = false;
      return spare;
    }
    double u, v, s2;
    do {
      u = 2.0 * uniform() - 1.0;
      v = 2.0 * uniform() - 1.0;
      s2 = u * u + v * v;
    } while (s2 >= 1.0 || s2 == 0.0);
    const double f = std::sqrt(-2.0 * std::log(s2) / s2);
    spare = v * f;
    has_spare = true;
    return u * f;
  }
};

struct Loader {
  int groups, endpoints, features, capacity;
  int steps = 0;  // 0 = snapshot mode; T >= 1 = window mode
  // window mode only: per-step targets [T, G, E] (the temporal
  // family's sequence-supervision law) instead of final-trend [G, E]
  bool per_step = false;
  std::mutex mu;
  std::condition_variable cv_pop;   // consumers wait for a ready batch
  std::condition_variable cv_push;  // producers wait for ring space
  std::condition_variable cv_drain; // stop() waits for consumers to leave
  std::deque<Batch> ring;
  bool stopping = false;
  int active_consumers = 0;         // threads inside aga_tl_next's wait
  std::atomic<uint64_t> produced{0};
  std::vector<std::thread> workers;

  Loader(int g, int e, int f, int cap) :
      groups(g), endpoints(e), features(f), capacity(cap) {}

  Batch generate(Rng& rng) const {
    return steps > 0 ? generate_window(rng) : generate_snapshot(rng);
  }

  Batch generate_snapshot(Rng& rng) const {
    Batch b;
    const int G = groups, E = endpoints, F = features;
    b.features.resize(size_t(G) * E * F);
    b.mask.resize(size_t(G) * E);
    b.target.resize(size_t(G) * E);
    for (auto& x : b.features) x = float(rng.normal());
    for (int g = 0; g < G; g++) {
      double denom = 0.0;
      std::vector<double> raw(E, 0.0);
      for (int e = 0; e < E; e++) {
        const bool healthy = rng.uniform() < 0.9;
        const bool valid = rng.uniform() < 0.8;
        b.mask[size_t(g) * E + e] = valid ? 1 : 0;
        if (healthy && valid) {
          // capacity proxy: exp of feature 0, as in synthetic_batch
          raw[e] = std::exp(double(
              b.features[(size_t(g) * E + e) * F]));
          denom += raw[e];
        }
      }
      for (int e = 0; e < E; e++)
        b.target[size_t(g) * E + e] =
            denom > 0.0 ? float(raw[e] / denom) : 0.0f;
    }
    return b;
  }

  Batch generate_window(Rng& rng) const {
    // temporal law, mirroring models/temporal.py synthetic_window:
    // i.i.d. N(0,1) features per step, mask ~ Bernoulli(0.85), target
    // ~ exp(capacity trend) among valid endpoints — trend over the
    // whole window ([G, E] target), or per step t relative to step 0
    // ([T, G, E] target, synthetic_window(per_step=True)'s law) when
    // per_step is set
    Batch b;
    const int T = steps, G = groups, E = endpoints, F = features;
    b.features.resize(size_t(T) * G * E * F);
    b.mask.resize(size_t(G) * E);
    b.target.resize(per_step ? size_t(T) * G * E : size_t(G) * E);
    for (auto& x : b.features) x = float(rng.normal());
    const size_t step_stride = size_t(G) * E * F;
    std::vector<double> raw(E);  // hoisted: T*G refills, one alloc
    for (int g = 0; g < G; g++) {
      for (int e = 0; e < E; e++)
        b.mask[size_t(g) * E + e] = rng.uniform() < 0.85 ? 1 : 0;
      const int t_begin = per_step ? 0 : T - 1;
      for (int t = t_begin; t < T; t++) {
        double denom = 0.0;
        std::fill(raw.begin(), raw.end(), 0.0);
        for (int e = 0; e < E; e++) {
          if (!b.mask[size_t(g) * E + e]) continue;
          const size_t f0 = (size_t(g) * E + e) * F;
          const double trend =
              double(b.features[size_t(t) * step_stride + f0])
              - double(b.features[f0]);
          raw[e] = std::exp(trend);
          denom += raw[e];
        }
        float* out = per_step
            ? &b.target[(size_t(t) * G + g) * E]
            : &b.target[size_t(g) * E];
        for (int e = 0; e < E; e++)
          out[e] = denom > 0.0 ? float(raw[e] / denom) : 0.0f;
      }
    }
    return b;
  }

  void worker(uint64_t seed) {
    Rng rng(seed);
    for (;;) {
      Batch b = generate(rng);  // outside the lock: the expensive part
      std::unique_lock<std::mutex> lk(mu);
      cv_push.wait(lk, [&] {
        return stopping || int(ring.size()) < capacity;
      });
      if (stopping) return;
      ring.push_back(std::move(b));
      produced.fetch_add(1, std::memory_order_relaxed);
      cv_pop.notify_one();
    }
  }

  void start(int n_threads, uint64_t seed) {
    for (int i = 0; i < n_threads; i++)
      workers.emplace_back(&Loader::worker, this,
                           seed * 0x9e3779b97f4a7c15ULL + i + 1);
  }

  void stop() {
    // Deletion safety: a consumer may be blocked inside aga_tl_next
    // with the GIL released.  Wake everyone, then WAIT for every
    // consumer to leave the critical section before the caller frees
    // this object (the workqueue keeps shutdown and free separate for
    // the same reason; here free implies a drain).
    {
      std::unique_lock<std::mutex> lk(mu);
      stopping = true;
      cv_pop.notify_all();
      cv_push.notify_all();
      cv_drain.wait(lk, [&] { return active_consumers == 0; });
    }
    for (auto& t : workers)
      if (t.joinable()) t.join();
  }
};

}  // namespace

extern "C" {

// steps == 0: snapshot mode ([G, E, F] batches); steps == T >= 1:
// window mode ([T, G, E, F] batches with a trend-law target).
// per_step != 0 (window mode only): the target is [T, G, E], one
// normalized trend-so-far distribution per step — the temporal
// family's sequence-supervision law.
void* aga_tl_new(int groups, int endpoints, int features, int capacity,
                 int n_threads, uint64_t seed, int steps, int per_step) {
  if (groups <= 0 || endpoints <= 0 || features <= 0 || capacity <= 0 ||
      n_threads <= 0 || steps < 0 || (per_step && steps == 0))
    return nullptr;
  auto* l = new Loader(groups, endpoints, features, capacity);
  l->steps = steps;
  l->per_step = per_step != 0;
  l->start(n_threads, seed);
  return l;
}

// Blocking pop into caller-provided buffers: features sized [G*E*F] in
// snapshot mode (steps == 0) or [steps*G*E*F] in window mode; mask
// always [G*E]; target [G*E], EXCEPT per_step window mode where it is
// [steps*G*E] — size accordingly or the memcpy overruns the buffer.
// Returns 1 on success, 0 when the loader was stopped.  Called with
// the GIL released (ctypes), so Python threads park here natively.
int aga_tl_next(void* h, float* features, uint8_t* mask, float* target) {
  auto* l = static_cast<Loader*>(h);
  Batch b;
  {
    std::unique_lock<std::mutex> lk(l->mu);
    l->active_consumers++;
    l->cv_pop.wait(lk, [&] { return l->stopping || !l->ring.empty(); });
    const bool ok = !l->stopping && !l->ring.empty();
    if (ok) {
      b = std::move(l->ring.front());
      l->ring.pop_front();
      l->cv_push.notify_one();
    }
    l->active_consumers--;
    if (l->active_consumers == 0) l->cv_drain.notify_all();
    if (!ok) return 0;  // stopping: caller must not touch the loader
  }
  std::memcpy(features, b.features.data(),
              b.features.size() * sizeof(float));
  std::memcpy(mask, b.mask.data(), b.mask.size());
  std::memcpy(target, b.target.data(), b.target.size() * sizeof(float));
  return 1;
}

// (produced batch count, current ring depth) for observability.
void aga_tl_stats(void* h, uint64_t* produced, int* depth) {
  auto* l = static_cast<Loader*>(h);
  *produced = l->produced.load(std::memory_order_relaxed);
  std::lock_guard<std::mutex> lk(l->mu);
  *depth = int(l->ring.size());
}

void aga_tl_free(void* h) {
  auto* l = static_cast<Loader*>(h);
  l->stop();
  delete l;
}

}  // extern "C"
