"""Region topology: the locality-domain model the multi-region control
plane reasons about (ISSUE 14; ROADMAP item 4).

Every earlier layer treated "the wire" as flat: a mutation call costs
the same whether its container lives next door or across an ocean.
The collectives literature (PAPERS.md: HiCCL's hierarchical compose,
Cloud Collectives' rank reordering) says flat fan-in is the slow shape
— the win comes from making the expensive domain boundary EXPLICIT and
aggregating inside it.  This module is that boundary made explicit:

- **Regions and the latency/bandwidth matrix.**  A deployment declares
  its regions and the per-(src, dst) cost of crossing between them
  (fast intra-region, slow and possibly asymmetric cross-region).  The
  fake cloud charges these costs through ``simclock`` per call
  (fake.FaultInjector), so the hierarchical-vs-flat win is MEASURED in
  (virtual or real) seconds, never asserted.
- **Partitions.**  ``partition_region``/``heal_region`` are the chaos
  pair: while a region is partitioned, calls crossing INTO it fail
  with a retryable ServiceUnavailable.  Partial partitions (``rate <
  1``) draw from their own per-(seed, src→dst pair) decision stream —
  crc32 of (seed, salt, pair, per-pair call index), the PR-3/PR-6
  seeded-decision model — so the same seeded scenario replays
  byte-identically (tests/chaos/test_chaos_determinism.py) and arming
  one pair's chaos never perturbs a sibling's draws.
- **Container/key bindings.**  The sim-side registry mapping AWS
  containers (hosted zone ids, endpoint-group ARNs) and kube object
  keys to their home regions.  The fake binds containers at creation
  (an EG knows its region; a zone is created with one); the provider
  binds kube keys as its ensure paths learn which regions an object's
  containers live in.  Unbound names resolve to the local region —
  zero extra cost, which is what keeps the no-topology path
  byte-identical to the pre-topology tree.
- **Mutation profiles.**  Per-shard, per-region mutation counts fed by
  the write path (topology/aggregator.py) — the observed traffic the
  locality placement (topology/placement.py) reorders shard→replica
  ranks by, and the source of the ``shard_locality_score`` gauge.

Knobs ``aggregate`` / ``digest_reads`` gate the two derived layers
(hierarchical write fan-in, digest-based sweep reads) independently so
benches can A/B each against the flat shape under the SAME latency
matrix.  A ``RegionTopology`` is inert until a factory is built with
it: no topology configured means no aggregator, no digest gate, no
latency model — the documented default (``--regions`` opts in).
"""
from __future__ import annotations

import threading
import zlib
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .. import metrics

# latency defaults: sub-millisecond inside a region, tens of
# milliseconds across — the asymmetry real inter-region links show
DEFAULT_INTRA_LATENCY = 0.0005
DEFAULT_CROSS_LATENCY = 0.04


class RegionTopology:
    """The region set + cost matrix + chaos/binding/profile state (one
    per deployment; the factory, the fake cloud and the placement all
    share this object).  Thread-safe; every decision that could vary
    between runs draws from a per-(seed, region-pair) stream."""

    def __init__(self, regions: Sequence[str],
                 local_region: Optional[str] = None,
                 intra_latency: float = DEFAULT_INTRA_LATENCY,
                 cross_latency: float = DEFAULT_CROSS_LATENCY,
                 matrix: Optional[Dict[Tuple[str, str], float]] = None,
                 bandwidth: float = 0.0,
                 mutation_latency_factor: float = 1.0,
                 jitter: float = 0.0,
                 seed: Optional[int] = None,
                 aggregate: bool = True,
                 aggregate_linger: Optional[float] = None,
                 digest_reads: bool = True,
                 digest_stability_waves: int = 10):
        if not regions:
            raise ValueError("a RegionTopology needs at least one region")
        self.regions: Tuple[str, ...] = tuple(regions)
        self.local_region = local_region or self.regions[0]
        if self.local_region not in self.regions:
            raise ValueError(
                f"local region {self.local_region!r} not in {self.regions}")
        self.intra_latency = intra_latency
        self.cross_latency = cross_latency
        # (src, dst) -> seconds overrides: the asymmetric matrix
        self._matrix = dict(matrix or {})
        # payload term: extra seconds PER UNIT (a record change, an
        # endpoint config) crossing regions — the beta of the alpha +
        # beta*n cost model collectives use; 0 disables
        self.bandwidth = bandwidth
        # cross-region MUTATIONS cost this multiple of the pair's read
        # latency: a control-plane write crosses the service's commit/
        # consensus path while reads are served from (edge) replicas —
        # the real Route53/GA shape, and the asymmetry hierarchical
        # fan-in amortizes (one commit round-trip per region batch)
        self.mutation_latency_factor = mutation_latency_factor
        # +/- fractional latency jitter, drawn per (seed, pair, index)
        self.jitter = jitter
        # cross-region MUTATIONS serialize per (src, dst) pair (the
        # alpha-cost model collectives reason with): a region's writes
        # funnel through its commit path one at a time — each occupies
        # the channel for its latency, so flat fan-in pays N
        # serialized crossings where one region batch pays one.
        # Modeled as a virtual queueing clock per pair (no lock is
        # held while sleeping).  READS are unserialized: they hit
        # replicated/anycast endpoints (the real DNS/GA shape), and
        # intra-region traffic rides the local fabric.
        self.link_serialization = True
        self._channel_free: Dict[Tuple[str, str], float] = {}
        self.seed = seed
        self.aggregate = aggregate
        # how long a region aggregator's leader lingers for cohort
        # mates: one cross-region latency by default — every extra
        # entry captured saves at least one full crossing, so a
        # one-crossing wait always amortizes on a storm and costs one
        # RTT-equivalent when alone (the urgent path stays the
        # coalescer's, one level up)
        self.aggregate_linger = (aggregate_linger
                                 if aggregate_linger is not None
                                 else cross_latency)
        self.digest_reads = digest_reads
        self.digest_stability_waves = digest_stability_waves
        self._lock = threading.Lock()
        # region -> failure rate while partitioned (absent = healthy)
        self._partitioned: Dict[str, float] = {}
        # per-(salt, src, dst) draw indexes: each fault source on each
        # pair owns its stream, the determinism contract
        self._draws: Dict[Tuple[str, str, str], int] = {}
        # container name (zone id / EG arn) -> region
        self._containers: Dict[str, str] = {}
        # kube object key -> regions its containers live in
        self._key_regions: Dict[str, Set[str]] = {}
        # keys with a container NO region digest covers (unbound zone,
        # out-of-topology region): their sweeps always run
        self._digest_veto: Set[str] = set()
        # (shard id, region) -> observed mutation count (placement feed)
        self._mutations: Dict[Tuple[int, str], int] = {}
        # bounded, ordered log of partition-injected failures — frozen
        # by the flight recorder next to the AWS/kube chaos logs, and
        # the determinism test's third decision stream
        self._decisions: deque = deque(maxlen=4096)

    # -- cost model -----------------------------------------------------

    def latency(self, src: Optional[str], dst: Optional[str],
                units: int = 1, mutation: bool = False) -> float:
        """Seconds one call from ``src`` to ``dst`` carrying ``units``
        payload items costs (``mutation`` applies the write-commit
        factor).  Unknown/unbound regions are local: no topology
        opinion means no added cost."""
        src = src or self.local_region
        dst = dst or self.local_region
        if src == dst or src not in self.regions \
                or dst not in self.regions:
            base = self.intra_latency
        else:
            base = self._matrix.get((src, dst), self.cross_latency)
            if mutation:
                base *= self.mutation_latency_factor
            if self.bandwidth > 0.0:
                base += max(0, units - 1) * self.bandwidth
        if self.jitter > 0.0 and self.seed is not None and src != dst:
            draw = self._draw("latency", src, dst)
            base *= 1.0 + self.jitter * (2.0 * draw - 1.0)
        return base

    def channel_latency(self, src: Optional[str], dst: Optional[str],
                        units: int = 1, mutation: bool = False,
                        now: float = 0.0) -> float:
        """Seconds the CALLER must wait for one call: the pair's
        latency plus — for MUTATIONS — any queueing behind earlier
        writes still occupying the pair's serial commit channel
        (``link_serialization``).  The channel is a FIFO server: this
        call is scheduled at ``max(now, channel_free)`` and holds the
        channel for its latency; the return value is completion-time
        minus ``now``.  Reads and intra-region calls pay the plain
        latency."""
        src = src or self.local_region
        dst = dst or self.local_region
        base = self.latency(src, dst, units=units, mutation=mutation)
        if (src == dst or not mutation
                or not self.link_serialization
                or src not in self.regions
                or dst not in self.regions):
            return base
        with self._lock:
            free = self._channel_free.get((src, dst), 0.0)
            start = max(now, free)
            self._channel_free[(src, dst)] = start + base
        return start + base - now

    def proximity(self, a: str, b: str) -> float:
        """Closeness of two regions in (0, 1]: 1 inside one region,
        falling with the pair's BASE latency — the placement's rank-
        reordering affinity term.  Deliberately un-jittered: a scoring
        pass must neither wobble the map nor consume the latency
        streams the wire's seeded draws replay from."""
        if a == b:
            return 1.0
        if a not in self.regions or b not in self.regions:
            return 1.0
        lat = self._matrix.get((a, b), self.cross_latency)
        if lat <= 0.0:
            return 1.0
        return min(1.0, max(self.intra_latency, 1e-6) / lat)

    def _draw(self, salt: str, src: str, dst: str) -> float:
        """One [0, 1) draw from the (salt, src→dst) stream — its OWN
        per-pair index, so concurrent fault sources and pairs never
        share (and never perturb) each other's sequences."""
        with self._lock:
            key = (salt, src, dst)
            index = self._draws.get(key, 0)
            self._draws[key] = index + 1
        return zlib.crc32(
            f"{self.seed}:{salt}:{src}>{dst}:{index}".encode()) / 2**32

    # -- partitions (the chaos pair) ------------------------------------

    def partition_region(self, region: str, rate: float = 1.0) -> None:
        """Cut ``region`` off: calls crossing INTO it fail (retryable)
        at ``rate`` — partial rates draw from the pair's own seeded
        stream.  Intra-region traffic (the regional gateway fanning
        out locally) is unaffected: a partition severs LINKS, not the
        region's own control plane."""
        if region not in self.regions:
            raise ValueError(f"unknown region {region!r}")
        with self._lock:
            self._partitioned[region] = rate

    def heal_region(self, region: str) -> None:
        with self._lock:
            self._partitioned.pop(region, None)

    def partitioned_regions(self) -> "Set[str]":
        with self._lock:
            return set(self._partitioned)

    def partition_decision(self, src: Optional[str],
                           dst: Optional[str], method: str,
                           now: float) -> bool:
        """Should this ``src``→``dst`` call fail under the current
        partition set?  Logged (bounded) when it does — the decision
        stream the determinism proof replays."""
        src = src or self.local_region
        dst = dst or self.local_region
        if src == dst:
            return False
        with self._lock:
            rate = self._partitioned.get(dst)
        if rate is None:
            return False
        if rate < 1.0:
            if self.seed is None:
                import random
                hit = random.random() < rate
            else:
                hit = self._draw("partition", src, dst) < rate
            if not hit:
                return False
        with self._lock:
            self._decisions.append({
                "t": round(now, 6), "src": src, "dst": dst,
                "method": method, "source": "partition"})
        return True

    def decision_log(self) -> List[dict]:
        with self._lock:
            return list(self._decisions)

    # -- container / key bindings ---------------------------------------

    def bind(self, container: str, region: str) -> None:
        """Record ``container`` (zone id / EG arn) as homed in
        ``region`` (idempotent; unknown regions are ignored so a fake
        seeded with out-of-topology regions stays cost-free)."""
        if region not in self.regions:
            return
        with self._lock:
            self._containers[container] = region

    def region_of(self, container: str) -> str:
        """Home region of a container; unbound -> local (cost-free)."""
        with self._lock:
            return self._containers.get(container, self.local_region)

    def bound_region(self, container: str) -> Optional[str]:
        """Like :meth:`region_of` but None for an unbound container —
        callers that must not confuse "lives locally" with "nothing
        known" (the digest gate's key bindings) use this spelling."""
        with self._lock:
            return self._containers.get(container)

    def containers_in(self, region: str) -> List[str]:
        with self._lock:
            return sorted(c for c, r in self._containers.items()
                          if r == region)

    def bind_key(self, key: str, region: "Optional[str]") -> None:
        """Accumulate ``region`` into the kube object ``key``'s
        region set (an object may span regions: its zone in one, its
        endpoint group in another) — the digest gate requires EVERY
        bound region clean before a sweep may be answered by digests.

        ``region`` None or outside the topology VETOES the key's
        digest answers instead (sticky): part of the object's state
        lives in a container no region digest covers, so its sweeps
        must always run — a binding from one controller's container
        must never mask another's uncovered one."""
        with self._lock:
            if region is None or region not in self.regions:
                self._digest_veto.add(key)
            else:
                self._key_regions.setdefault(key, set()).add(region)

    def key_regions(self, key: str) -> "Set[str]":
        with self._lock:
            return set(self._key_regions.get(key, ()))

    def key_digest_vetoed(self, key: str) -> bool:
        """True when some container of ``key`` is outside every
        region digest's coverage — the gate never skips its sweeps."""
        with self._lock:
            return key in self._digest_veto

    # -- mutation profiles (the placement feed) -------------------------

    def note_mutation(self, shard_id: Optional[int], region: str,
                      n: int = 1) -> None:
        """``n`` mutations for ``shard_id``'s containers landed in
        ``region`` — the observed-traffic profile locality placement
        reorders ranks by.  Also refreshes the shard's locality gauge
        (share of its traffic staying in the LOCAL region)."""
        if shard_id is None or region not in self.regions:
            return
        with self._lock:
            self._mutations[(shard_id, region)] = \
                self._mutations.get((shard_id, region), 0) + n
            total = 0
            local = 0
            for (sid, reg), count in self._mutations.items():
                if sid == shard_id:
                    total += count
                    if reg == self.local_region:
                        local += count
        if total:
            metrics.record_shard_locality(shard_id, local / total)

    def mutation_profile(self, shard_id: int) -> Dict[str, int]:
        with self._lock:
            return {region: count
                    for (sid, region), count in self._mutations.items()
                    if sid == shard_id}

    def seed_profile(self, profiles: Dict[int, Dict[str, int]]) -> None:
        """Install learned profiles wholesale (ledger replay at
        startup, tests) instead of accumulating via note_mutation."""
        with self._lock:
            self._mutations.clear()
            for sid, counts in profiles.items():
                for region, count in counts.items():
                    self._mutations[(sid, region)] = count


def parse_regions(spec: str,
                  local_region: Optional[str] = None,
                  seed: Optional[int] = None) -> Optional[RegionTopology]:
    """CLI helper: ``--regions us-west-2,eu-west-1`` -> a topology
    with default costs (empty spec -> None: the flat default)."""
    names = [r.strip() for r in (spec or "").split(",") if r.strip()]
    if not names:
        return None
    return RegionTopology(names, local_region=local_region, seed=seed)


def iter_region_pairs(regions: Iterable[str]):
    """Every ordered (src, dst) cross-region pair."""
    rs = list(regions)
    for src in rs:
        for dst in rs:
            if src != dst:
                yield src, dst
