"""Locality-driven shard placement: rank reordering over the region
topology (ISSUE 14; Cloud Collectives' "reorder ranks so traffic stays
inside cheap domains", PAPERS.md).

The rendezvous map (sharding/hashmap.py) places shards on replicas by
pure hash — blind to WHERE a shard's traffic actually goes.  With a
topology configured, each shard accumulates an observed mutation
profile (per-region counts fed by the aggregator,
topology/model.py ``note_mutation``), and this module turns that
profile into a per-(shard, member) weight for WEIGHTED rendezvous
hashing: a member whose home region is near the regions a shard's
keys mutate scores higher, so the shard's writes stay inside the
cheap domain.

Safety and stability:

- The weight only BIASES the hash — ownership is still decided by the
  shard leases (leaderelection/shards.py), so a replica acting on a
  stale or divergent profile can never create two writers.  Profiles
  are learned locally per replica (no gossip in this PR — documented
  in ARCHITECTURE.md); the churn bound below keeps any divergence
  from thrashing the map.
- Rebalance churn is BOUNDED: ``assignment`` takes the previous map
  and caps voluntary moves per pass (``max_moves``), keeping only the
  highest-affinity-gain moves — a profile shift migrates the fleet a
  few shards at a time, never in one wave.  Moves forced by
  membership change (a dead replica's shards) are never capped.
- No topology, no profile, or an unknown member region all degrade to
  weight 1.0 — and an all-1.0 weighted map is byte-identical to the
  unweighted rendezvous map (tests/test_topology.py pins this), which
  is what keeps the S=1/no-topology path identical to today.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

from ..sharding.hashmap import compute_assignment

# how strongly affinity biases the hash: weight = 1 + ALPHA * score,
# score in [0, 1] — at 3.0 a fully-local member wins ~4x the hash mass
# of a fully-remote one, enough to reorder ranks without drowning the
# hash's balancing term
DEFAULT_ALPHA = 3.0
# voluntary (affinity-driven) moves allowed per rebalance pass
DEFAULT_MAX_MOVES = 2


class LocalityPlacement:
    """Topology-weighted assignment for the shard-lease manager
    (``ShardLeaseManager(placement=...)``).

    ``member_region`` maps a replica identity to its home region
    (None/unknown -> no bias for that member)."""

    def __init__(self, topology,
                 member_region: Callable[[str], Optional[str]],
                 alpha: float = DEFAULT_ALPHA,
                 max_moves: int = DEFAULT_MAX_MOVES):
        self._topology = topology
        self._member_region = member_region
        self._alpha = alpha
        self._max_moves = max_moves
        self._prev: Optional[Dict[int, "str | None"]] = None

    # -- scoring --------------------------------------------------------

    def affinity(self, shard_id: int, member: str) -> float:
        """[0, 1]: how much of the shard's observed mutation traffic
        lands near ``member``'s home region (proximity-weighted
        share).  No profile or no known region -> 0 (no opinion)."""
        region = self._member_region(member)
        if region is None:
            return 0.0
        profile = self._topology.mutation_profile(shard_id)
        total = sum(profile.values())
        if not total:
            return 0.0
        near = sum(count * self._topology.proximity(region, dst)
                   for dst, count in profile.items())
        return near / total

    def weight(self, shard_id: int, member: str) -> float:
        return 1.0 + self._alpha * self.affinity(shard_id, member)

    # -- the assignment hook --------------------------------------------

    def assignment(self, num_shards: int, members
                   ) -> Dict[int, "str | None"]:
        """The churn-bounded topology-weighted map (the shard-lease
        manager's convergence target).  Remembers its own previous
        answer so the voluntary-move cap applies pass over pass."""
        want = compute_assignment(
            num_shards, members, weights=self.weight,
            prev=self._prev, max_moves=self._max_moves,
            gain=self.affinity)
        self._prev = dict(want)
        return want


def static_member_regions(mapping: Dict[str, str]
                          ) -> Callable[[str], Optional[str]]:
    """Convenience: identity -> region from a fixed dict (the CLI's
    ``--shard-region identity=region`` spelling and the tests')."""
    return mapping.get
