"""Digest-based cross-region reads: the sweep tier's gossip layer
(ISSUE 14).

The tiered drift sweep (reconcile/fingerprint.py) deep-verifies every
key once per sweep period — which, multi-region, means a steady-state
fleet pays N cross-region verifying reads per period for regions that
almost never drift.  This gate collapses that to ONE digest exchange
per region per resync wave: the regional gateway serves a fingerprint
rollup of its mutable container state (``get_region_digest`` — region-
level rollups over the same canonical state the PR-5 per-key
fingerprints digest), and a sweep-due key whose every bound region is
digest-CLEAN is downgraded to an ordinary resync delivery (which the
per-key fingerprint gate then answers in O(1)).

"Clean" is earned, never assumed — the state machine per region:

``WARMING``
    Sweeps run normally.  The gate tracks the region's digest across
    its own REFRESH sequence (one refresh per wave advance per
    region; several informers share the gate with independent wave
    counters, so stability is counted in the gate's refreshes, never
    by comparing callers' wave numbers); once the digest has been
    STABLE for a full sweep period (``stability_waves``, raised to at
    least the consumers' ``sweep_every`` via ``note_sweep_period``) —
    a window in which every key deep-verified at least once against
    exactly that digested state — the digest is promoted to the
    region's VERIFIED baseline.  (Stability alone is not enough: a
    region that drifted BEFORE the gate first looked would show a
    stable-but-wrong digest; requiring a full verified period under
    that digest is what makes the baseline trustworthy.)
``CLEAN``
    One digest exchange per wave.  Matching the baseline answers every
    sweep in the region; ANY mismatch — out-of-band drift, our own
    writes landing, a failed exchange, a partitioned region — drops
    the baseline and the region re-earns it through a fresh WARMING
    period (during which the ordinary sweeps detect and repair
    whatever changed).

Keys with no region binding (single-region deployments, objects whose
containers the provider has not yet resolved) always sweep — the safe
default, and what keeps the no-topology path byte-identical.
"""
from __future__ import annotations

import hashlib
import logging
from typing import Callable, Dict, Optional, Tuple

from ..analysis import locks
from ..autotune import knobs as knobcat
from ..autotune import targets as tune_targets
from ..metrics import record_region_digest_exchange

logger = logging.getLogger(__name__)


class _RegionState:
    __slots__ = ("baseline", "candidate", "stable_refreshes")

    def __init__(self):
        self.baseline: Optional[str] = None    # verified digest (CLEAN)
        self.candidate: Optional[str] = None   # stable digest warming up
        # consecutive wave-advancing refreshes that returned candidate
        self.stable_refreshes = 0


def rollup_digest(parts) -> str:
    """Canonical region rollup: sha1 over the sorted (container,
    canonical state) pairs — the shared spelling the fake gateway and
    any future real aggregation point must both use."""
    h = hashlib.sha1()
    for container, state in sorted(parts):
        h.update(container.encode())
        h.update(b"\x00")
        h.update(state.encode() if isinstance(state, str) else state)
        h.update(b"\x01")
    return h.hexdigest()


class RegionDigestGate:
    """The sweep gate (reconcile/fingerprint.py ``sweep_gate=``):
    ``allow_skip(key, wave)`` is True when every region bound to
    ``key`` is CLEAN this wave, meaning the sweep's deep verify is
    already answered by the digest exchange.  One gateway read per
    region per wave, whatever the fleet size.

    ``apis_for(region)`` resolves the REGION's wrapped bundle lazily
    (the factory's ``provider_for(region).apis``) so construction
    never races provider build — and so each region's exchange rides
    its own breaker: a partitioned region's failing digest reads open
    exactly that region's circuit, never a sibling's.  A bundle
    without a gateway disables the gate (every key sweeps)."""

    def __init__(self, apis_for: Callable[[str], object], topology,
                 stability_waves: Optional[int] = None,
                 exchange_every: int = knobcat.DIGEST_EXCHANGE_EVERY):
        self._apis = apis_for
        self._topology = topology
        self._stability = (stability_waves
                           if stability_waves is not None
                           else topology.digest_stability_waves)
        # exchange cadence (feedback-tunable, autotune/): refresh the
        # region digest only every this-many wave advances; between
        # refreshes CLEAN verdicts ride the cached digest, trading
        # drift-detection lag (bounded by cadence × resync period)
        # for fewer cross-region reads.  1 = every wave.
        self._exchange_every = max(1, int(exchange_every))
        self._lock = locks.make_lock("region-digest-gate")
        self._state: Dict[str, _RegionState] = {}
        # region -> (highest wave seen, digest or None): one exchange
        # per wave ADVANCE — the gate is shared by several informers
        # with independent (same-period, loosely skewed) wave
        # counters, so only a strictly higher wave refreshes; lagging
        # counters ride the cached answer instead of thrashing it
        self._wave_cache: Dict[str, Tuple[int, Optional[str]]] = {}
        tune_targets.note_digest_gate(self)

    def set_exchange_every(self, exchange_every: int) -> None:
        """Retune the exchange cadence live (the autotune registry's
        apply surface)."""
        with self._lock:
            self._exchange_every = max(1, int(exchange_every))

    def note_sweep_period(self, sweep_every: int) -> None:
        """A consumer declares its sweep period: CLEAN must be earned
        over at least that many waves, or keys in the residues that
        never deep-verified during the warming window could have
        pre-existing drift baked into the promoted baseline."""
        if sweep_every > 0:
            with self._lock:
                self._stability = max(self._stability, sweep_every)

    # -- the gate surface ----------------------------------------------

    def allow_skip(self, key: str, wave: int) -> bool:
        if self._topology.key_digest_vetoed(key):
            # part of the key's state lives in a container no region
            # digest covers: its sweeps always run
            return False
        regions = self._topology.key_regions(key)
        if not regions:
            return False
        return all(self._region_clean(region, wave)
                   for region in regions)

    # -- per-region machinery ------------------------------------------

    def _exchange(self, region: str, wave: int
                  ) -> "Tuple[Optional[str], bool]":
        """(digest, refreshed): the region's digest this wave, and
        whether THIS call advanced the refresh sequence (a strictly
        higher wave than any seen for the region).  The first due key
        of a wave pays the exchange; the rest — and any consumer
        whose counter lags — ride the cached answer.  digest None =
        exchange failed (partition, no gateway): never clean."""
        with self._lock:
            cached = self._wave_cache.get(region)
            # cadence: a refresh happens only when the wave advanced
            # past the last refresh by the exchange_every stride; the
            # waves in between (and lagging consumers) ride the cache
            if cached is not None \
                    and wave < cached[0] + self._exchange_every:
                return cached[1], False
        digest: Optional[str] = None
        try:
            apis = self._apis(region)
            gateway = getattr(apis, "gateway", None)
            if gateway is not None:
                record_region_digest_exchange()
                digest = gateway.get_region_digest(region)
        except Exception as e:
            logger.debug("region digest exchange failed for %s: %s",
                         region, e)
            digest = None
        with self._lock:
            cached = self._wave_cache.get(region)
            if cached is not None \
                    and wave < cached[0] + self._exchange_every:
                # a concurrent caller won the refresh race
                return cached[1], False
            self._wave_cache[region] = (wave, digest)
        return digest, True

    def _region_clean(self, region: str, wave: int) -> bool:
        digest, refreshed = self._exchange(region, wave)
        with self._lock:
            st = self._state.get(region)
            if st is None:
                st = self._state[region] = _RegionState()
            if digest is None:
                # a failed exchange proves nothing: drop everything
                # and re-earn (the partitioned-region shape)
                st.baseline = None
                st.candidate = None
                st.stable_refreshes = 0
                return False
            if st.baseline is not None:
                if digest == st.baseline:
                    return True
                # drift (or our own writes): re-earn through WARMING
                logger.info("region %s digest diverged from verified "
                            "baseline; sweeps re-enabled", region)
                st.baseline = None
                st.candidate = digest
                st.stable_refreshes = 0
                return False
            if digest != st.candidate:
                st.candidate = digest
                st.stable_refreshes = 0
                return False
            if refreshed:
                # stability is counted in the gate's OWN refreshes —
                # one per wave advance — never by comparing different
                # consumers' wave counters
                st.stable_refreshes += 1
            # stable candidate: promoted once a full sweep period has
            # deep-verified every key against exactly this digest
            if st.stable_refreshes >= self._stability:
                st.baseline = digest
                logger.info("region %s digest verified stable over %d "
                            "refreshes; sweeps now digest-answered",
                            region, self._stability)
                return True
            return False

    # -- observability ---------------------------------------------------

    def clean_regions(self) -> "list[str]":
        with self._lock:
            return sorted(r for r, st in self._state.items()
                          if st.baseline is not None)
