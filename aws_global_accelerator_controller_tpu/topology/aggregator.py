"""Per-region intent aggregators: hierarchical write fan-in
(ISSUE 14's tentpole; the HiCCL compose shape applied to the write
path).

The write pipeline so far ends at the :class:`~..cloudprovider.aws
.batcher.MutationCoalescer`: intents fold per CONTAINER (one hosted
zone, one endpoint group) and each drained cohort issues one wire call
for its container.  That is the right shape inside a region — but a
fleet-wide storm touches many containers across many regions, and
per-container calls each pay the full cross-region latency: S shard
cohorts x C containers of flat fan-in across the expensive domain.

This module adds the second aggregation level: between the coalescer
and the wire sits one aggregator group PER REGION.  A cohort flush
hands its container batch here (the ShardedCoalescer→aggregator
handoff, lint rule L116) instead of calling the service directly; the
aggregator lingers briefly, collects every contribution bound for the
same region — across containers AND across shard cohorts — and issues
ONE ``apply_region_batch`` per region (the regional gateway fans out
locally at intra-region cost).  A fleet-wide change becomes one
cross-region message per region instead of one per container.

Contracts preserved end to end:

- **PR-4 fold/bisect/error demux.**  Folding already happened above
  (per container, in the cohort).  The region batch is NOT atomic
  across containers: the gateway applies each container entry
  atomically and reports per-entry verdicts, so one poisoned zone
  batch fails alone — its cohort's flush receives exactly that entry's
  error and runs its own bisect by resubmitting halves through this
  same handoff.  A region-level failure (partition, retry budget, open
  circuit — the wrapped call's verdict) fails every contribution with
  the same hint and every cohort parks, the PR-4 cohort-level demux
  one level up.
- **PR-8 fence/ownership.**  Every contribution carries its cohort's
  :class:`~..resilience.fence.CompositeFence` (process + owning
  shard).  The flush pushes those fences into the wrapper's
  per-attempt write-fence TLS and re-checks each contribution per
  attempt under the drain permit: a TRIPPED fence (ordered shutdown /
  handoff drain) still flushes, a SEALED shard's contribution is
  rejected with :class:`FencedError` — per attempt, never silently
  dropped — while its region-mates fly.  A seal landing mid-retry
  surfaces as FencedError out of the wrapped call; the flush
  re-partitions the cohort and re-issues with the survivors.
- **PR-12 tracing.**  The region flush span joins the first
  contribution's trace and LINKS the rest (the coalescer flush-span
  shape one level down), and stamps a ``region`` mark into every
  member context.

The aggregator is also where the placement's mutation profile is fed:
every contribution notes (shard, region) into the topology
(topology/model.py ``note_mutation``), the observed-traffic counts
locality placement reorders ranks by.
"""
from __future__ import annotations

import logging
from contextlib import ExitStack
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis import locks
from ..autotune import knobs as knobcat
from ..metrics import record_region_batch
from ..resilience import ErrorClass, FencedError, classify
from ..resilience.fence import flush_permit, push_write_fence
from ..simulation import clock as simclock
from ..tracing import default_tracer

logger = logging.getLogger(__name__)

# in-flush retries of a PER-ENTRY retryable verdict (a transient fault
# inside the gateway's local fan-out): flat fan-in absorbed these in
# the wrapper's per-call retry policy, so the aggregated path must
# absorb them too — a transient entry blip must never surface to the
# coalescer's demux as a terminal rejection (which would bisect a
# healthy batch or park a whole cohort)
ENTRY_RETRY_LIMIT = 4

ENTRY_RECORD_SETS = "record_sets"
ENTRY_ENDPOINT_GROUP = "endpoint_group"

# bound on one region batch (far above any real cohort wave; the
# gateway applies entries serially, so an unbounded batch could hold
# the region flush for an unbounded intra-region span)
MAX_REGION_BATCH = 4096


class _Contribution:
    """One cohort flush's handoff: a container batch bound for one
    region, completed (or failed) exactly once by the region flush
    that carried — or rejected — it."""

    __slots__ = ("kind", "key", "payload", "fence", "ctxs", "shard_id",
                 "event", "exc")

    def __init__(self, kind, key, payload, fence, ctxs, shard_id):
        self.kind = kind
        self.key = key
        self.payload = payload
        self.fence = fence
        self.ctxs = tuple(ctxs or ())
        self.shard_id = shard_id
        self.event = simclock.make_event()
        self.exc: Optional[BaseException] = None

    def complete(self) -> None:
        self.event.set()

    def fail(self, exc: BaseException) -> None:
        self.exc = exc
        self.event.set()


class _RegionGroup:
    """One region's aggregation queue (persistent: the group count is
    the region count, never container churn)."""

    __slots__ = ("region", "cond", "pending", "leader", "flushing")

    def __init__(self, region: str):
        self.region = region
        self.cond = simclock.make_condition(
            locks.make_lock(f"region-aggregator[{region}]"))
        self.pending: List[_Contribution] = []
        self.leader = False
        self.flushing = False


# bound on the wait-for-previous-flush poll, the coalescer's constant
FLUSH_SERIALIZE_POLL = 0.05


class RegionAggregator:
    """The per-region fan-in layer (module docstring).  ``apis_for``
    resolves a region to its RESILIENT bundle (the factory's
    ``provider_for(region).apis``), so every region's wire call rides
    its OWN retry/breaker/token-bucket stack — a partitioned region
    opens its own circuit without tripping its siblings'."""

    def __init__(self, apis_for: Callable[[str], object], topology,
                 linger: float = knobcat.FAKE_COALESCER_LINGER,
                 clock: Callable[[], float] = simclock.monotonic):
        self._apis_for = apis_for
        self._topology = topology
        self._linger = linger
        self._clock = clock
        self._lock = locks.make_lock("region-aggregator-groups")
        self._groups: Dict[str, _RegionGroup] = {}

    # -- the handoff surface (what batcher._wire_* calls) ---------------

    def submit_record_sets(self, hosted_zone_id: str, changes,
                           fence=None, ctxs=(), shard_id=None) -> None:
        """One cohort's drained zone batch; blocks until the region
        flush carrying it lands (or rejects it) and raises that
        verdict — the coalescer's flush demuxes it exactly as it would
        a direct wire call's."""
        region = self._topology.region_of(hosted_zone_id)
        self._submit(region, _Contribution(
            ENTRY_RECORD_SETS, hosted_zone_id, list(changes), fence,
            ctxs, shard_id))

    def submit_endpoint_group(self, endpoint_group_arn: str, configs,
                              fence=None, ctxs=(),
                              shard_id=None) -> None:
        """One cohort's merged endpoint-group replacement set."""
        region = self._topology.region_of(endpoint_group_arn)
        self._submit(region, _Contribution(
            ENTRY_ENDPOINT_GROUP, endpoint_group_arn, list(configs),
            fence, ctxs, shard_id))

    # -- internals ------------------------------------------------------

    def _group(self, region: str) -> _RegionGroup:
        with self._lock:
            group = self._groups.get(region)
            if group is None:
                group = self._groups[region] = _RegionGroup(region)
            return group

    def _submit(self, region: str, c: _Contribution) -> None:
        self._topology.note_mutation(c.shard_id, region,
                                     max(1, len(c.payload)))
        group = self._group(region)
        with group.cond:
            group.pending.append(c)
            lead = not group.leader
            if lead:
                group.leader = True
            elif len(group.pending) >= MAX_REGION_BATCH:
                group.cond.notify_all()
        if lead:
            self._lead(group)
        c.event.wait()
        if c.exc is not None:
            raise c.exc

    def _lead(self, group: _RegionGroup) -> None:
        """Linger-drain-flush, the coalescer's leader pipeline one
        level up: the first contributor into an idle region group
        lingers for cohort-mates (other containers, other shards),
        hands leadership to the next epoch, and flushes outside every
        lock.  A tripped fence among the pending contributions cuts
        the linger short — the ordered-stop/handoff drain must not
        wait out a batching deadline no new work can fill."""
        with group.cond:
            deadline = self._clock() + self._linger
            while len(group.pending) < MAX_REGION_BATCH:
                if any(c.fence is not None and c.fence.is_tripped()
                       for c in group.pending):
                    break
                remaining = deadline - self._clock()
                if remaining <= 0:
                    break
                group.cond.wait(remaining)
            while group.flushing:
                group.cond.wait(FLUSH_SERIALIZE_POLL)
            contributions = list(group.pending)
            del group.pending[:]
            group.leader = False
            group.flushing = True
        try:
            self._flush(group.region, contributions)
        except BaseException as e:  # belt: _flush answers its own
            for c in contributions:
                if not c.event.is_set():
                    c.fail(e)
            raise
        finally:
            with group.cond:
                group.flushing = False
                group.cond.notify_all()

    def _check_fences(self, contributions: List[_Contribution]
                      ) -> Tuple[List[_Contribution], int]:
        """Partition the cohort by fence liveness under the drain
        permit: a TRIPPED fence's already-accepted contribution still
        flushes, a SEALED one is rejected NOW (its waiter gets the
        FencedError; never silently dropped).  Returns the live set
        and how many were rejected."""
        live: List[_Contribution] = []
        rejected = 0
        for c in contributions:
            if c.fence is not None:
                try:
                    with flush_permit():
                        c.fence.check("aggregator")
                except FencedError as fe:
                    c.fail(fe)
                    rejected += 1
                    continue
            live.append(c)
        return live, rejected

    def _flush(self, region: str, contributions: List[_Contribution]
               ) -> None:
        if not contributions:
            return
        ctxs = []
        seen = set()
        for c in contributions:
            for ctx in c.ctxs:
                if id(ctx) not in seen:
                    seen.add(id(ctx))
                    ctxs.append(ctx)
        with default_tracer.attach(ctxs[0] if ctxs else None), \
                default_tracer.span("region_flush", region=region,
                                    cohort=len(contributions)) as fs:
            fs.links = tuple(sorted({c.trace_id for c in ctxs}))
            pending = contributions
            fence_err: Optional[FencedError] = None
            attempts: Dict[int, int] = {}
            while pending:
                live, rejected = self._check_fences(pending)
                if not live:
                    return
                if fence_err is not None and rejected == 0:
                    # the wrapper rejected the attempt but no
                    # CONTRIBUTION's fence did (the process fence
                    # sealed under fence-less contributions):
                    # re-issuing would loop — the wrapper's verdict is
                    # every remaining waiter's answer
                    for c in live:
                        c.fail(fence_err)
                    return
                apis = self._apis_for(region)
                gateway = getattr(apis, "gateway", None)
                if gateway is None:
                    # a backend with no regional gateway (the real
                    # boto bundle): fall back to flat per-container
                    # calls through the region's wrapper — correct,
                    # just without the fan-in win
                    self._flush_flat(apis, live)
                    return
                entries = [(c.kind, c.key, c.payload) for c in live]
                try:
                    with ExitStack() as stack:
                        stack.enter_context(flush_permit())
                        for c in live:
                            stack.enter_context(
                                push_write_fence(c.fence))
                        results = gateway.apply_region_batch(region,
                                                             entries)
                except FencedError as fe:
                    # a fence sealed mid-retry: the wrapper rejected
                    # the ATTEMPT.  Re-partition — the sealed
                    # contributions fail individually above, the
                    # survivors re-issue (rejected per attempt, never
                    # silently dropped)
                    pending = live
                    fence_err = fe
                    continue
                except Exception as e:
                    # region-level verdict (partition, retry budget,
                    # open circuit): every contribution's cohort
                    # parks on the same hint — the PR-4 demux shape
                    fs.error = f"{type(e).__name__}: {e}"
                    for c in live:
                        c.fail(e)
                    return
                record_region_batch(region)
                # the wire call landed: any earlier FencedError was a
                # fence that has since been rejected out — it must not
                # terminally answer a LATER retry round's survivors
                fence_err = None
                for ctx in ctxs:
                    ctx.mark(fs.span_id, "region")
                retry: List[_Contribution] = []
                for c, verdict in zip(live, results):
                    if verdict is None:
                        c.complete()
                        continue
                    # a retryable per-entry verdict (transient chaos
                    # inside the local fan-out) is absorbed HERE, the
                    # way the wrapper's retry policy absorbed it on
                    # the flat path — bounded, then it becomes the
                    # waiter's real answer
                    attempts[id(c)] = attempts.get(id(c), 0) + 1
                    if (classify(verdict) in (ErrorClass.THROTTLE,
                                              ErrorClass.TRANSIENT)
                            and attempts[id(c)] < ENTRY_RETRY_LIMIT):
                        retry.append(c)
                    else:
                        c.fail(verdict)
                if retry:
                    simclock.sleep(self._linger)
                    pending = retry
                    continue
                return

    def _flush_flat(self, apis, live: List[_Contribution]) -> None:
        """Per-container fallback when the region has no gateway."""
        for c in live:
            try:
                with flush_permit(), push_write_fence(c.fence):
                    if c.kind == ENTRY_RECORD_SETS:
                        apis.route53.change_resource_record_sets_batch(
                            c.key, c.payload)
                    else:
                        apis.ga.update_endpoint_group(c.key, c.payload)
            except Exception as e:
                c.fail(e)
            else:
                c.complete()
