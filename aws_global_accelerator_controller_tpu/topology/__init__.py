"""Topology-aware multi-region control plane (ISSUE 14; ROADMAP
item 4): region as a first-class locality domain.

- :mod:`.model` — the region set, latency/bandwidth matrix,
  partition/heal chaos hooks, container/key bindings and mutation
  profiles (one :class:`RegionTopology` per deployment).
- :mod:`.aggregator` — hierarchical write fan-in: per-region intent
  aggregators between the sharded coalescer and the wire (one batch
  per region instead of one per container).
- :mod:`.digest` — digest-based cross-region reads: the sweep tier's
  per-region fingerprint-rollup exchange.
- :mod:`.placement` — locality-driven shard placement: topology-
  weighted rendezvous rank reordering with bounded churn.

Flat fan-in remains the default: nothing here activates until a
factory is built with a topology (``--regions``).
"""
from .aggregator import RegionAggregator
from .digest import RegionDigestGate, rollup_digest
from .model import RegionTopology, parse_regions
from .placement import LocalityPlacement, static_member_regions

__all__ = [
    "LocalityPlacement",
    "RegionAggregator",
    "RegionDigestGate",
    "RegionTopology",
    "parse_regions",
    "rollup_digest",
    "static_member_regions",
]
