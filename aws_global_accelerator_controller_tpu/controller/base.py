"""Shared controller plumbing: filters and the worker-thread harness.

The reference duplicates the Service/Ingress filter predicates and the
worker spawn loop across its controllers
(pkg/controller/globalaccelerator/controller.go:195-225 vs
pkg/controller/route53/controller.go:188-218); here they are shared.
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, List

from ..apis import (
    AWS_LOAD_BALANCER_TYPE_ANNOTATION,
    INGRESS_CLASS_ANNOTATION,
    ROUTE53_HOSTNAME_ANNOTATION,
)
from ..kube.objects import Ingress, KubeObject, Service
from ..kube.workqueue import (
    CLASS_BACKGROUND,
    RateLimitingQueue,
)
from ..reconcile import process_next_work_item

logger = logging.getLogger(__name__)

WORKER_POLL = 0.2  # get() timeout so workers observe the stop event

# Shared informer indexes (kube/informers.py Indexer).  Registered by
# the controllers that consume them; names are shared so two
# controllers indexing the same informer the same way reuse one index.
LB_DNS_INDEX = "lb-dns"
ROUTE53_HOSTNAME_INDEX = "route53-hostname"


def index_by_lb_dns(obj) -> List[str]:
    """Service/Ingress -> the LB DNS names in its status: the key both
    the GA and Route53 paths reason about (one accelerator per LB
    hostname), so 'who else claims this LB' is an O(1) bucket read."""
    return [i.hostname for i in obj.status.load_balancer.ingress
            if i.hostname]


def index_by_route53_hostname(obj) -> List[str]:
    """Service/Ingress -> the hostnames its route53-hostname annotation
    claims (comma-separated, route53/service.go:71)."""
    value = obj.annotations.get(ROUTE53_HOSTNAME_ANNOTATION)
    if not value:
        return []
    return [h for h in value.split(",") if h]


def was_load_balancer_service(svc: Service) -> bool:
    """type: LoadBalancer AND (aws-load-balancer-type annotation OR
    loadBalancerClass set) (reference globalaccelerator/service.go:18-26)."""
    if svc.spec.type != "LoadBalancer":
        return False
    return (AWS_LOAD_BALANCER_TYPE_ANNOTATION in svc.annotations
            or svc.spec.load_balancer_class is not None)


def was_alb_ingress(ingress: Ingress) -> bool:
    """ingressClassName == 'alb' OR legacy ingress.class annotation present
    (reference globalaccelerator/ingress.go:19-27)."""
    if ingress.spec.ingress_class_name == "alb":
        return True
    return INGRESS_CLASS_ANNOTATION in ingress.annotations


def annotation_presence_changed(old: KubeObject, new: KubeObject,
                                annotation: str) -> bool:
    """(reference globalaccelerator/controller.go:250-259)"""
    return (annotation in old.annotations) != (annotation in new.annotations)


def resync_enqueue(fingerprints, queue, obj, wave: int) -> None:
    """The enqueue-time half of the steady-state fast path, shared by
    every controller's tagged resync handler.

    An unchanged object (recorded fingerprint matches, not due for a
    sweep) is answered HERE — one counter bump, zero queue churn: the
    truly-idle fleet costs nothing at rest, not even workqueue ops.
    Everything else (changed objects, keys whose record was dropped by
    an error, sweep-due keys) takes ``add_rate_limited``, so a key
    failing its backstop syncs keeps the per-key exponential backoff
    and a parked key is never converted into an immediate retry by the
    next resync wave (the plain-``add`` shortcut would bypass exactly
    the hot-retry protection the resilience layer's park provides).

    Overload shedding: with the queue past a watermark (depth, or the
    oldest interactive item's age — kube/workqueue.py ``overloaded``),
    background re-deliveries are DROPPED here instead of enqueued —
    the correctness-free shed: nothing about the key's fingerprint
    state changed, so the next resync wave re-delivers it exactly as
    this one would have.  Interactive work never sheds, and a key a
    real watch event claimed (pending EVENT origin) rides through
    untouched."""
    from .. import metrics
    from ..reconcile.fingerprint import ORIGIN_RESYNC, ORIGIN_SWEEP

    key = obj.key()
    origin = fingerprints.note_resync(key, wave)
    if origin == ORIGIN_RESYNC and fingerprints.matches(key, obj):
        fingerprints.claim_origin(key)
        metrics.record_fastpath_skip(fingerprints.controller)
        return
    if origin in (ORIGIN_RESYNC, ORIGIN_SWEEP):
        reason = queue.overloaded() if hasattr(queue, "overloaded") \
            else None
        if reason is not None:
            # shed background work first — never interactive, never
            # correctness (the un-popped origin claim is harmless: the
            # next delivery upgrades or re-claims it)
            fingerprints.claim_origin(key)
            metrics.record_shed(fingerprints.controller, reason)
            return
    queue.add_rate_limited(key, klass=CLASS_BACKGROUND)


def spawn_workers(name: str, count: int, stop: threading.Event,
                  queue: RateLimitingQueue, key_to_obj, process_delete,
                  process_create_or_update,
                  fingerprints=None) -> List[threading.Thread]:
    """Start ``count`` reconcile worker threads over one queue
    (the wait.Until(runWorker, 1s) analogue,
    reference globalaccelerator/controller.go:208-213).
    ``fingerprints`` (reconcile/fingerprint.py FingerprintCache) arms
    the steady-state fast path for this queue's dispatch."""

    def loop():
        while not stop.is_set():
            if not process_next_work_item(
                    queue, key_to_obj, process_delete,
                    process_create_or_update, get_timeout=WORKER_POLL,
                    fingerprints=fingerprints):
                return

    threads = []
    for i in range(count):
        t = threading.Thread(target=loop, daemon=True,
                             name=f"{name}-worker-{i}")
        t.start()
        threads.append(t)
    return threads


def run_controller(name: str, stop: threading.Event,
                   queues: List[RateLimitingQueue],
                   worker_sets: Callable[[], List[threading.Thread]]) -> None:
    """Common Run() tail: spawn workers, block on stop, shut queues down."""
    from .. import metrics
    for q in queues:
        metrics.watch_queue_depth(q)
    threads = worker_sets()
    logger.info("started %s workers", name)
    stop.wait()
    logger.info("shutting down %s workers", name)
    for q in queues:
        q.shutdown()
    for t in threads:
        t.join(timeout=2.0)
