"""Shared controller plumbing: filters and the worker-thread harness.

The reference duplicates the Service/Ingress filter predicates and the
worker spawn loop across its controllers
(pkg/controller/globalaccelerator/controller.go:195-225 vs
pkg/controller/route53/controller.go:188-218); here they are shared.
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, List

from ..analysis import locks
from ..simulation import clock as simclock
from ..apis import (
    AWS_LOAD_BALANCER_TYPE_ANNOTATION,
    INGRESS_CLASS_ANNOTATION,
    ROUTE53_HOSTNAME_ANNOTATION,
)
from ..kube.objects import Ingress, KubeObject, Service
from ..kube.workqueue import (
    CLASS_BACKGROUND,
    CLASS_INTERACTIVE,
    RateLimitingQueue,
)
from ..reconcile import process_next_work_item

logger = logging.getLogger(__name__)

WORKER_POLL = 0.2  # get() timeout so workers observe the stop event

# Shared informer indexes (kube/informers.py Indexer).  Registered by
# the controllers that consume them; names are shared so two
# controllers indexing the same informer the same way reuse one index.
LB_DNS_INDEX = "lb-dns"
ROUTE53_HOSTNAME_INDEX = "route53-hostname"


def index_by_lb_dns(obj) -> List[str]:
    """Service/Ingress -> the LB DNS names in its status: the key both
    the GA and Route53 paths reason about (one accelerator per LB
    hostname), so 'who else claims this LB' is an O(1) bucket read."""
    return [i.hostname for i in obj.status.load_balancer.ingress
            if i.hostname]


def index_by_route53_hostname(obj) -> List[str]:
    """Service/Ingress -> the hostnames its route53-hostname annotation
    claims (comma-separated, route53/service.go:71)."""
    value = obj.annotations.get(ROUTE53_HOSTNAME_ANNOTATION)
    if not value:
        return []
    return [h for h in value.split(",") if h]


def was_load_balancer_service(svc: Service) -> bool:
    """type: LoadBalancer AND (aws-load-balancer-type annotation OR
    loadBalancerClass set) (reference globalaccelerator/service.go:18-26)."""
    if svc.spec.type != "LoadBalancer":
        return False
    return (AWS_LOAD_BALANCER_TYPE_ANNOTATION in svc.annotations
            or svc.spec.load_balancer_class is not None)


def was_alb_ingress(ingress: Ingress) -> bool:
    """ingressClassName == 'alb' OR legacy ingress.class annotation present
    (reference globalaccelerator/ingress.go:19-27)."""
    if ingress.spec.ingress_class_name == "alb":
        return True
    return INGRESS_CLASS_ANNOTATION in ingress.annotations


def annotation_presence_changed(old: KubeObject, new: KubeObject,
                                annotation: str) -> bool:
    """(reference globalaccelerator/controller.go:250-259)"""
    return (annotation in old.annotations) != (annotation in new.annotations)


def resync_enqueue(fingerprints, queue, obj, wave: int) -> "str | None":
    """The enqueue-time half of the steady-state fast path, shared by
    every controller's tagged resync handler.

    An unchanged object (recorded fingerprint matches, not due for a
    sweep) is answered HERE — one counter bump, zero queue churn: the
    truly-idle fleet costs nothing at rest, not even workqueue ops.
    Everything else (changed objects, keys whose record was dropped by
    an error, sweep-due keys) takes ``add_rate_limited``, so a key
    failing its backstop syncs keeps the per-key exponential backoff
    and a parked key is never converted into an immediate retry by the
    next resync wave (the plain-``add`` shortcut would bypass exactly
    the hot-retry protection the resilience layer's park provides).

    Overload shedding: with the queue past a watermark (depth, or the
    oldest interactive item's age — kube/workqueue.py ``overloaded``),
    background re-deliveries are DROPPED here instead of enqueued —
    the correctness-free shed: nothing about the key's fingerprint
    state changed, so the next resync wave re-delivers it exactly as
    this one would have.  Interactive work never sheds, and a key a
    real watch event claimed (pending EVENT origin) rides through
    untouched."""
    from .. import metrics, tracing
    from ..reconcile.fingerprint import ORIGIN_RESYNC, ORIGIN_SWEEP

    key = obj.key()
    origin = fingerprints.note_resync(key, wave)
    if origin == ORIGIN_RESYNC and fingerprints.matches(key, obj):
        fingerprints.claim_origin(key)
        metrics.record_fastpath_skip(fingerprints.controller)
        return None
    if origin in (ORIGIN_RESYNC, ORIGIN_SWEEP):
        reason = queue.overloaded() if hasattr(queue, "overloaded") \
            else None
        if reason is not None:
            # shed background work first — never interactive, never
            # correctness (the un-popped origin claim is harmless: the
            # next delivery upgrades or re-claims it)
            fingerprints.claim_origin(key)
            metrics.record_shed(fingerprints.controller, reason)
            return None
    # a re-delivery that reaches the queue starts (or merges into) a
    # trace at its origin stage — sweep waves are exactly the traffic
    # whose stage attribution the convergence ledger explains.  No
    # ring span for bulk origins: a fleet-wide wave must not evict
    # the diagnostic span history (tracing.new_context docstring)
    ctx = tracing.new_context(origin or "resync", key=key,
                              controller=fingerprints.controller,
                              record_span=False)
    queue.add_rate_limited(key, klass=CLASS_BACKGROUND, ctx=ctx)
    # the origin that was actually ENQUEUED (None = answered/shed
    # above): callers batching sweep-tier work — the fleet-sweep
    # planner stages ORIGIN_SWEEP keys — key off this return
    return origin


def event_enqueue(gate, fingerprints, queue, obj,
                  origin: str = "event") -> None:
    """One watch event's enqueue, shared by every controller handler:
    mint the trace context at the event boundary (tracing.py — the
    root of the event→converged trace), route it through the shard
    gate (a deferred event keeps its trace for replay-on-acquire),
    note the event for the fingerprint layer and enqueue interactive.
    """
    from .. import tracing

    key = obj.key()
    ctx = tracing.new_context(origin, key=key,
                              queue=queue.name or "queue")
    if gate is not None and not gate.admit(obj, ctx=ctx):
        return
    if fingerprints is not None:
        fingerprints.note_event(key)
    queue.add_rate_limited(key, klass=CLASS_INTERACTIVE, ctx=ctx)


class ShardGate:
    """One queue's shard-ownership event gate WITH deferred replay.

    Gating an informer EVENT on ownership has a hole the cache-scan
    re-delivery cannot close: deletes and demotions (the managed /
    hostname annotation removed) are exactly the events the informer
    cache cannot reconstruct at acquire time — the object is gone
    from the cache, or no longer matches the controller's predicate,
    yet its AWS-side teardown has not run.  Dropping such an event
    while the shard is unowned (a crash gap, a handoff window) would
    orphan the accelerator chain / records forever.

    So a gated event is never dropped: :meth:`admit` records the key
    under the route's shard, and when THIS replica later acquires
    that shard the listener replays every deferred key as an
    interactive event — the dispatch already handles not-found as
    delete and no-longer-managed as cleanup.  Every live replica
    defers independently, so whichever of them wins the shard replays
    what it saw; the residual hole (no replica alive to observe the
    event) is the pre-existing full-restart gap, unchanged by
    sharding.  Memory is bounded by distinct gated keys per shard
    (cleared on replay), the informer cache's own magnitude."""

    def __init__(self, shards, queue, fingerprints, route_key):
        self.shards = shards
        self.queue = queue
        self.fingerprints = fingerprints
        self.route_key = route_key
        self._lock = locks.make_lock("shard-gate")
        # shard id -> {object key: TraceContext or None}: a deferred
        # event keeps its trace so the replay-on-acquire CONTINUES the
        # original event's trace across the ownership gap (tracing.py)
        self._deferred: dict = {}

    def admit(self, obj, ctx=None) -> bool:
        """True when this replica owns the object's route; otherwise
        the key (with its event's trace context) is deferred for
        replay-on-acquire and the handler must return without
        enqueueing."""
        try:
            rkey = self.route_key(obj)
        except Exception:
            rkey = obj.key()
        sid = self.shards.shard_of(rkey)
        if self.shards.owns(sid):
            return True
        with self._lock:
            pending = self._deferred.setdefault(sid, {})
            have = pending.get(obj.key())
            if have is not None and ctx is not None and have is not ctx:
                # a later event superseding a deferred one: the
                # survivor links the earlier trace (queue-dedup merge
                # semantics, controller/base + kube/workqueue)
                ctx.link(have.trace_id)
                have.link(ctx.trace_id)
            if ctx is not None or have is None:
                pending[obj.key()] = ctx
        return False

    def claim(self, sid: int) -> dict:
        """Take (and clear) the events deferred for ``sid`` as
        ``{object key: TraceContext-or-None}``.  The acquire listener
        claims them BEFORE its cache scan so a live deferred key's
        re-delivery CONTINUES the original event's trace instead of
        minting a fresh one, then hands the remainder (deletes,
        demotions — the events the cache cannot reconstruct) to
        :meth:`replay`."""
        with self._lock:
            return self._deferred.pop(sid, {})

    def replay(self, sid: int, skip=(), entries=None) -> int:
        """Re-deliver deferred events (the acquire listener calls this
        alongside its cache scan), interactive class — these are real
        user-visible changes the gap swallowed.  ``skip`` is the set
        of keys the cache scan is already re-delivering (live,
        predicate-passing objects): only the events the cache CANNOT
        reconstruct — deletes (object gone) and demotions (predicate
        now false) — replay here, so a rebalance after days of churn
        does not flood the interactive tier with already-converged
        keys.  ``entries`` replays an already-:meth:`claim`-ed dict
        instead of claiming now."""
        keys = self.claim(sid) if entries is None else entries
        replayed = 0
        for key, ctx in keys.items():
            if key in skip:
                continue
            if self.fingerprints is not None:
                self.fingerprints.note_event(key)
            if ctx is not None:
                # the original event's trace survives the handoff: the
                # hop names the boundary it just crossed
                ctx.hop("shard-replay")
            self.queue.add_rate_limited(key, klass=CLASS_INTERACTIVE,
                                        ctx=ctx)
            replayed += 1
        return replayed


def wire_shard_listener(shards, informer, queue, fingerprints,
                        route_key, predicate, gate=None,
                        interactive_pred=None) -> None:
    """Register one (informer, queue) pair's shard ownership hooks
    (sharding/shardset.py ``ShardSet.add_listener``):

    - **acquired**: re-deliver the shard's keys as BACKGROUND work —
      the successor's re-adoption.  Fingerprints for these keys are
      cold (never recorded here, or dropped on a previous loss), so
      each rides a full provider-verifying sync exactly like the PR-6
      restart-recovery path: reads + fingerprint rebuild, zero
      mutations against a converged world.  Keys matching
      ``interactive_pred`` (an object with a rollout ramp in flight —
      the previous owner's persisted step is waiting to be resumed)
      ride CLASS_INTERACTIVE instead: a mid-ramp binding must not
      queue its resume behind the whole shard's background re-verify.
    - **lost**: drop the shard's fingerprint records (the next owner's
      writes make them unprovable — FingerprintCache.invalidate_shard)
      and purge its pending backlog from the queue (the syncs would
      all be dropped by the dispatch's ownership check anyway; purging
      saves the churn).

    ``route_key(obj)`` is the controller's routing-key extractor (the
    AWS-side container: an EndpointGroupBinding's ARN; the owning
    object key where the container is created 1:1 by the object);
    ``predicate(obj)`` is the controller's watch filter.  Standalone
    (unmanaged) shard sets never fire listeners, so the single-process
    deployment pays nothing."""

    def on_change(event: str, sid: int) -> None:
        keys = []
        for obj in informer.cache_list():
            try:
                rkey = route_key(obj)
            except Exception:
                rkey = obj.key()
            if shards.shard_of(rkey) == sid:
                keys.append((obj.key(), obj))
        if event == "acquired":
            from .. import tracing

            deferred = gate.claim(sid) if gate is not None else {}
            scanned = set()
            for key, obj in keys:
                if predicate(obj):
                    scanned.add(key)
                    if key in deferred:
                        # a real event arrived during the ownership
                        # gap: its re-delivery rides interactive (it
                        # is user-visible work, not re-adoption) and
                        # CONTINUES the deferred trace when one rode
                        # the event — membership in the deferred map,
                        # NOT the context, decides the semantics, so
                        # disabling tracing changes nothing about
                        # scheduling (the set_enabled contract)
                        ctx = deferred[key]
                        if ctx is not None:
                            ctx.hop("shard-replay")
                        if fingerprints is not None:
                            fingerprints.note_event(key)
                        klass = CLASS_INTERACTIVE
                    else:
                        ctx = tracing.new_context("shard-acquire",
                                                  key=key, shard=sid,
                                                  record_span=False)
                        klass = (CLASS_INTERACTIVE
                                 if interactive_pred is not None
                                 and interactive_pred(obj)
                                 else CLASS_BACKGROUND)
                    queue.add_rate_limited(key, klass=klass, ctx=ctx)
            if gate is not None:
                # replay the events the cache scan above cannot
                # reconstruct — deletes and demotions the ownership
                # gap swallowed (ShardGate docstring)
                gate.replay(sid, skip=scanned, entries=deferred)
            return
        # lost: this replica's records for the shard prove nothing
        # once a successor writes — and its backlog is dead weight
        if fingerprints is not None:
            # route-mapped keys exactly; records whose object already
            # left the informer cache fall back to the key hash
            # (over-invalidation is always safe — one extra full sync)
            lost = {key for key, _ in keys}
            fingerprints.invalidate_shard(
                sid, lambda key: sid if key in lost
                else shards.shard_of(key))
        remove = getattr(queue, "remove", None)
        if remove is not None:
            for key, _ in keys:
                remove(key)

    shards.add_listener(on_change)


def spawn_workers(name: str, count: int, stop: threading.Event,
                  queue: RateLimitingQueue, key_to_obj, process_delete,
                  process_create_or_update,
                  fingerprints=None, shards=None) -> List[threading.Thread]:
    """Start ``count`` reconcile worker threads over one queue
    (the wait.Until(runWorker, 1s) analogue,
    reference globalaccelerator/controller.go:208-213).
    ``fingerprints`` (reconcile/fingerprint.py FingerprintCache) arms
    the steady-state fast path for this queue's dispatch; ``shards``
    (sharding/) arms shard-routed dispatch — unowned keys drop, owned
    syncs run under their shard's route guard."""

    def loop():
        while not stop.is_set():
            # the 0.2s get-poll exists to observe ``stop`` on the
            # system clock; under a virtual clock an idle worker
            # waking every 0.2 VIRTUAL seconds is pure scheduler
            # churn (a 100k-fleet steady window is hours of virtual
            # time) — work and shutdown both notify the queue
            # condition, so the long poll changes nothing else
            poll = 60.0 if simclock.virtual_active() else WORKER_POLL
            if not process_next_work_item(
                    queue, key_to_obj, process_delete,
                    process_create_or_update, get_timeout=poll,
                    fingerprints=fingerprints, shards=shards):
                return

    threads = []
    for i in range(count):
        threads.append(simclock.start_thread(
            loop, daemon=True, name=f"{name}-worker-{i}"))
    return threads


def run_controller(name: str, stop: threading.Event,
                   queues: List[RateLimitingQueue],
                   worker_sets: Callable[[], List[threading.Thread]]) -> None:
    """Common Run() tail: spawn workers, block on stop, shut queues down."""
    from .. import metrics
    for q in queues:
        metrics.watch_queue_depth(q)
    threads = worker_sets()
    logger.info("started %s workers", name)
    stop.wait()
    logger.info("shutting down %s workers", name)
    for q in queues:
        q.shutdown()
    for t in threads:
        simclock.join_thread(t, timeout=2.0)
