"""Route53 controller.

Same watch/filter skeleton as the GlobalAccelerator controller but keyed on
the route53-hostname annotation (reference pkg/controller/route53/).  The
annotation value splits on ',' for multiple hostnames (service.go:71).
Cross-controller coupling is implicit through AWS state: this controller
discovers the accelerator the GA controller created via its
target-hostname tag and retries on a 1m timer until it appears
(SURVEY.md §3.3).
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

from .. import cloudprovider
from ..apis import (
    AWS_LOAD_BALANCER_TYPE_ANNOTATION,
    ROUTE53_HOSTNAME_ANNOTATION,
)
from ..cloudprovider.aws import get_lb_name_from_hostname
from ..cloudprovider.aws.factory import CloudFactory
from ..errors import new_no_retry_errorf
from ..kube.client import KubeClient
from ..kube.informers import SharedInformerFactory, wait_for_cache_sync
from ..kube.objects import Ingress, Service, split_meta_namespace_key
from ..kube.workqueue import (
    CLASS_INTERACTIVE,
    DEFAULT_AGE_WATERMARK,
    DEFAULT_AGING_HORIZON,
    DEFAULT_DEPTH_WATERMARK,
    new_rate_limiting_queue,
)
from ..reconcile import Result
from ..reconcile.fingerprint import FingerprintCache, FingerprintConfig
from .base import (
    ROUTE53_HOSTNAME_INDEX,
    annotation_presence_changed,
    index_by_route53_hostname,
    ShardGate,
    resync_enqueue,
    wire_shard_listener,
    run_controller,
    spawn_workers,
    was_load_balancer_service,
)

logger = logging.getLogger(__name__)

CONTROLLER_AGENT_NAME = "route53-controller"


def route53_service_fingerprint(svc) -> tuple:
    """Exactly the Service fields the Route53 sync reads (filter
    predicate, hostname annotation, LB hostnames) — pure over informer
    state, never ``apis.*`` (lint rule L107)."""
    return (
        "route53", "Service", svc.spec.type,
        svc.spec.load_balancer_class,
        AWS_LOAD_BALANCER_TYPE_ANNOTATION in svc.annotations,
        svc.annotations.get(ROUTE53_HOSTNAME_ANNOTATION),
        tuple(i.hostname for i in svc.status.load_balancer.ingress),
    )


def route53_ingress_fingerprint(ingress) -> tuple:
    """The Ingress twin (no LB-service predicate: the route53
    controller watches ALL annotated ingresses) — pure, no ``apis.*``
    (L107)."""
    return (
        "route53", "Ingress",
        ingress.annotations.get(ROUTE53_HOSTNAME_ANNOTATION),
        tuple(i.hostname for i in ingress.status.load_balancer.ingress),
    )


@dataclass
class Route53Config:
    workers: int = 1
    cluster_name: str = "default"
    queue_qps: float = 10.0    # client-go default bucket
    queue_burst: int = 100
    # overload scheduler knobs (kube/workqueue.py priority tiers)
    aging_horizon: float = DEFAULT_AGING_HORIZON
    depth_watermark: int = DEFAULT_DEPTH_WATERMARK
    age_watermark: float = DEFAULT_AGE_WATERMARK
    # steady-state fast path (reconcile/fingerprint.py)
    fingerprints: FingerprintConfig = field(
        default_factory=FingerprintConfig)


class Route53Controller:
    def __init__(self, kube_client: KubeClient,
                 informer_factory: SharedInformerFactory,
                 cloud_factory: CloudFactory,
                 config: Route53Config):
        self.cluster_name = config.cluster_name
        self.workers = config.workers
        self.kube_client = kube_client
        self.cloud_factory = cloud_factory
        self.recorder = kube_client.event_recorder(CONTROLLER_AGENT_NAME)

        self.service_queue = new_rate_limiting_queue(
            name=f"{CONTROLLER_AGENT_NAME}-service",
            qps=config.queue_qps, burst=config.queue_burst,
            aging_horizon=config.aging_horizon,
            depth_watermark=config.depth_watermark,
            age_watermark=config.age_watermark)
        self.ingress_queue = new_rate_limiting_queue(
            name=f"{CONTROLLER_AGENT_NAME}-ingress",
            qps=config.queue_qps, burst=config.queue_burst,
            aging_horizon=config.aging_horizon,
            depth_watermark=config.depth_watermark,
            age_watermark=config.age_watermark)

        # steady-state fast path: one fingerprint gate per queue
        self.service_fingerprints = FingerprintCache(
            f"{CONTROLLER_AGENT_NAME}-service",
            route53_service_fingerprint, config.fingerprints)
        self.ingress_fingerprints = FingerprintCache(
            f"{CONTROLLER_AGENT_NAME}-ingress",
            route53_ingress_fingerprint, config.fingerprints)

        self.service_informer = informer_factory.services()
        self.service_informer.add_event_handler(
            add=self._add_service, update=self._update_service,
            delete=self._delete_service, resync=self._resync_service)
        self.service_informer.add_index(ROUTE53_HOSTNAME_INDEX,
                                        index_by_route53_hostname)
        self.ingress_informer = informer_factory.ingresses()
        self.ingress_informer.add_event_handler(
            add=self._add_ingress, update=self._update_ingress,
            delete=self._delete_ingress, resync=self._resync_ingress)
        self.ingress_informer.add_index(ROUTE53_HOSTNAME_INDEX,
                                        index_by_route53_hostname)

        # shard ownership (sharding/): records are 1:1 with (object,
        # hostname), so the routing key is the object key — all of one
        # object's record intents ride its shard's coalescer cohort
        self.shards = cloud_factory.shards
        # event gates with deferred replay (base.ShardGate): a
        # hostname-annotation removal or delete swallowed by an
        # ownership gap is replayed on acquire
        self.service_gate = ShardGate(
            self.shards, self.service_queue, self.service_fingerprints,
            lambda o: o.key())
        self.ingress_gate = ShardGate(
            self.shards, self.ingress_queue, self.ingress_fingerprints,
            lambda o: o.key())
        wire_shard_listener(
            self.shards, self.service_informer, self.service_queue,
            self.service_fingerprints, lambda o: o.key(),
            lambda o: (was_load_balancer_service(o)
                       and self._has_hostname(o)),
            gate=self.service_gate)
        wire_shard_listener(
            self.shards, self.ingress_informer, self.ingress_queue,
            self.ingress_fingerprints, lambda o: o.key(),
            self._has_hostname, gate=self.ingress_gate)

    # -- event handlers (route53/controller.go:90-172) ------------------

    @staticmethod
    def _has_hostname(obj) -> bool:
        return ROUTE53_HOSTNAME_ANNOTATION in obj.annotations

    def _add_service(self, svc: Service) -> None:
        if was_load_balancer_service(svc) and self._has_hostname(svc):
            if not self.service_gate.admit(svc):
                return
            self.service_fingerprints.note_event(svc.key())
            self.service_queue.add_rate_limited(
                svc.key(), klass=CLASS_INTERACTIVE)

    def _update_service(self, old: Service, new: Service) -> None:
        if old == new:
            return
        if was_load_balancer_service(new):
            if self._has_hostname(new) or annotation_presence_changed(
                    old, new, ROUTE53_HOSTNAME_ANNOTATION):
                if not self.service_gate.admit(new):
                    return
                self.service_fingerprints.note_event(new.key())
                self.service_queue.add_rate_limited(
                    new.key(), klass=CLASS_INTERACTIVE)

    def _delete_service(self, svc: Service) -> None:
        if was_load_balancer_service(svc):
            if not self.service_gate.admit(svc):
                return
            self.service_fingerprints.note_event(svc.key())
            self.service_queue.add_rate_limited(
                svc.key(), klass=CLASS_INTERACTIVE)

    def _resync_service(self, svc: Service, wave: int) -> None:
        """Tagged resync backstop for annotated Services — gated at
        enqueue time (base.resync_enqueue)."""
        if was_load_balancer_service(svc) and self._has_hostname(svc):
            if not self.shards.owns_key(svc.key()):
                return
            resync_enqueue(self.service_fingerprints,
                           self.service_queue, svc, wave)

    def _add_ingress(self, ingress: Ingress) -> None:
        # the route53 controller watches ALL ingresses with the annotation
        # (route53/controller.go:133-137; no ALB filter on add)
        if self._has_hostname(ingress):
            if not self.ingress_gate.admit(ingress):
                return
            self.ingress_fingerprints.note_event(ingress.key())
            self.ingress_queue.add_rate_limited(
                ingress.key(), klass=CLASS_INTERACTIVE)

    def _update_ingress(self, old: Ingress, new: Ingress) -> None:
        if old == new:
            return
        if self._has_hostname(new) or annotation_presence_changed(
                old, new, ROUTE53_HOSTNAME_ANNOTATION):
            if not self.ingress_gate.admit(new):
                return
            self.ingress_fingerprints.note_event(new.key())
            self.ingress_queue.add_rate_limited(
                new.key(), klass=CLASS_INTERACTIVE)

    def _delete_ingress(self, ingress: Ingress) -> None:
        if not self.ingress_gate.admit(ingress):
            return
        self.ingress_fingerprints.note_event(ingress.key())
        self.ingress_queue.add_rate_limited(
            ingress.key(), klass=CLASS_INTERACTIVE)

    def _resync_ingress(self, ingress: Ingress, wave: int) -> None:
        if self._has_hostname(ingress):
            if not self.shards.owns_key(ingress.key()):
                return
            resync_enqueue(self.ingress_fingerprints,
                           self.ingress_queue, ingress, wave)

    # -- run ------------------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        logger.info("starting Route53 controller")
        if not wait_for_cache_sync(stop, self.service_informer,
                                   self.ingress_informer):
            # only reachable when stop fired first — clean abort, not
            # a thread crash (r4 VERDICT next #7)
            logger.info("stopping Route53 controller before caches "
                        "synced (shutdown during apiserver wait)")
            return

        def workers():
            return (spawn_workers(
                        f"{CONTROLLER_AGENT_NAME}-service", self.workers,
                        stop, self.service_queue, self._key_to_service,
                        self.process_service_delete,
                        self.process_service_create_or_update,
                        fingerprints=self.service_fingerprints,
                        shards=self.shards)
                    + spawn_workers(
                        f"{CONTROLLER_AGENT_NAME}-ingress", self.workers,
                        stop, self.ingress_queue, self._key_to_ingress,
                        self.process_ingress_delete,
                        self.process_ingress_create_or_update,
                        fingerprints=self.ingress_fingerprints,
                        shards=self.shards))

        run_controller(CONTROLLER_AGENT_NAME, stop,
                       [self.service_queue, self.ingress_queue], workers)

    def _key_to_service(self, key: str):
        ns, name = split_meta_namespace_key(key)
        return self.service_informer.lister.get(ns, name)

    def _key_to_ingress(self, key: str):
        ns, name = split_meta_namespace_key(key)
        return self.ingress_informer.lister.get(ns, name)

    # -- process funcs (route53/service.go, route53/ingress.go) ---------

    def process_service_delete(self, key: str) -> Result:
        logger.info("%s has been deleted", key)
        try:
            ns, name = split_meta_namespace_key(key)
        except ValueError as e:
            raise new_no_retry_errorf("invalid resource key: %s", key) from e
        self.cloud_factory.global_provider().cleanup_record_set(
            self.cluster_name, "service", ns, name)
        return Result()

    def process_service_create_or_update(self, obj) -> Result:
        if not isinstance(obj, Service):
            raise new_no_retry_errorf("object is not Service, it is %s",
                                      type(obj).__name__)
        svc = obj
        hostname = svc.annotations.get(ROUTE53_HOSTNAME_ANNOTATION)
        if hostname is None:
            self.cloud_factory.global_provider().cleanup_record_set(
                self.cluster_name, "service", svc.metadata.namespace,
                svc.metadata.name)
            logger.info("deleted route53 records for Service %s", svc.key())
            self.recorder.event(svc, "Normal", "Route53RecordDeleted",
                                "Route53 record sets are deleted")
            return Result()

        hostnames = hostname.split(",")
        self._warn_contested_hostnames(svc, hostnames)
        for lb_ingress in svc.status.load_balancer.ingress:
            result = self._ensure_for_lb_ingress(
                svc, lb_ingress, hostnames,
                lambda provider: provider.ensure_route53_for_service(
                    svc, lb_ingress, hostnames, self.cluster_name))
            if result is not None:
                return result
        return Result()

    def process_ingress_delete(self, key: str) -> Result:
        logger.info("%s has been deleted", key)
        try:
            ns, name = split_meta_namespace_key(key)
        except ValueError as e:
            raise new_no_retry_errorf("invalid resource key: %s", key) from e
        self.cloud_factory.global_provider().cleanup_record_set(
            self.cluster_name, "ingress", ns, name)
        return Result()

    def process_ingress_create_or_update(self, obj) -> Result:
        if not isinstance(obj, Ingress):
            raise new_no_retry_errorf("object is not Ingress, it is %s",
                                      type(obj).__name__)
        ingress = obj
        hostname = ingress.annotations.get(ROUTE53_HOSTNAME_ANNOTATION)
        if hostname is None:
            self.cloud_factory.global_provider().cleanup_record_set(
                self.cluster_name, "ingress", ingress.metadata.namespace,
                ingress.metadata.name)
            logger.info("deleted route53 records for Ingress %s",
                        ingress.key())
            self.recorder.event(ingress, "Normal", "Route53RecordDeleted",
                                "Route53 record sets are deleted")
            return Result()

        hostnames = hostname.split(",")
        self._warn_contested_hostnames(ingress, hostnames)
        for lb_ingress in ingress.status.load_balancer.ingress:
            result = self._ensure_for_lb_ingress(
                ingress, lb_ingress, hostnames,
                lambda provider: provider.ensure_route53_for_ingress(
                    ingress, lb_ingress, hostnames, self.cluster_name))
            if result is not None:
                return result
        return Result()

    def _warn_contested_hostnames(self, obj, hostnames) -> None:
        """Indexed duplicate-claim check: two objects annotating the
        SAME route53 hostname would fight over one record set (last
        writer wins, ownership TXT flapping).  The hostname index
        answers 'who else claims this name' in O(1) across both
        watched kinds instead of a lister scan per sync."""
        for hostname in hostnames:
            others = [
                o.key()
                for informer in (self.service_informer,
                                 self.ingress_informer)
                for o in informer.by_index(ROUTE53_HOSTNAME_INDEX,
                                           hostname)
                if o.key() != obj.key() or o.kind != obj.kind]
            if others:
                logger.error(
                    "%s %s contests route53 hostname %s with %s — the "
                    "record set will flap between owners",
                    type(obj).__name__, obj.key(), hostname, others)

    def _ensure_for_lb_ingress(self, obj, lb_ingress, hostnames, ensure):
        try:
            provider_name = cloudprovider.detect_cloud_provider(
                lb_ingress.hostname)
        except ValueError as e:
            logger.error("%s", e)
            return None
        if provider_name != cloudprovider.PROVIDER_AWS:
            logger.warning("not implemented for %s", provider_name)
            return None
        _, region = get_lb_name_from_hostname(lb_ingress.hostname)
        provider = self.cloud_factory.provider_for(region)
        created, retry_after = ensure(provider)
        if retry_after > 0:
            return Result(requeue=True, requeue_after=retry_after)
        if created:
            self.recorder.eventf(
                obj, "Normal", "Route53RecordCreated",
                "Route53 record set is created: %s", hostnames)
        return None
