"""Route53 controller.

Same watch/filter skeleton as the GlobalAccelerator controller but keyed on
the route53-hostname annotation (reference pkg/controller/route53/).  The
annotation value splits on ',' for multiple hostnames (service.go:71).
Cross-controller coupling is implicit through AWS state: this controller
discovers the accelerator the GA controller created via its
target-hostname tag and retries on a 1m timer until it appears
(SURVEY.md §3.3).
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

from .. import cloudprovider
from ..apis import (
    AWS_LOAD_BALANCER_TYPE_ANNOTATION,
    ROLLOUT_STATE_ANNOTATION,
    ROUTE53_HOSTNAME_ANNOTATION,
    ROUTE53_SET_IDENTIFIER_ANNOTATION,
    ROUTE53_WEIGHT_ANNOTATION,
)
from ..cloudprovider.aws import get_lb_name_from_hostname
from ..cloudprovider.aws.factory import CloudFactory
from ..cloudprovider.aws.helpers import RecordPolicy
from ..errors import ConflictError, new_no_retry_errorf
from ..rollout import (
    RolloutEngine,
    RolloutState,
    breaker_region_health,
    parse_spec,
    rollout_annotation_items,
)
from ..kube.client import KubeClient
from ..kube.informers import SharedInformerFactory, wait_for_cache_sync
from ..kube.objects import Ingress, Service, split_meta_namespace_key
from ..kube.workqueue import (
    DEFAULT_AGE_WATERMARK,
    DEFAULT_AGING_HORIZON,
    DEFAULT_DEPTH_WATERMARK,
    new_rate_limiting_queue,
)
from ..reconcile import Result
from ..reconcile.fingerprint import FingerprintCache, FingerprintConfig
from .base import (
    ROUTE53_HOSTNAME_INDEX,
    annotation_presence_changed,
    event_enqueue,
    index_by_route53_hostname,
    ShardGate,
    resync_enqueue,
    wire_shard_listener,
    run_controller,
    spawn_workers,
    was_load_balancer_service,
)

logger = logging.getLogger(__name__)

CONTROLLER_AGENT_NAME = "route53-controller"


def route53_service_fingerprint(svc) -> tuple:
    """Exactly the Service fields the Route53 sync reads (filter
    predicate, hostname + weighted-routing + rollout annotations, LB
    hostnames) — pure over informer state, never ``apis.*`` (lint
    rule L107)."""
    return (
        "route53", "Service", svc.spec.type,
        svc.spec.load_balancer_class,
        AWS_LOAD_BALANCER_TYPE_ANNOTATION in svc.annotations,
        svc.annotations.get(ROUTE53_HOSTNAME_ANNOTATION),
        svc.annotations.get(ROUTE53_SET_IDENTIFIER_ANNOTATION),
        svc.annotations.get(ROUTE53_WEIGHT_ANNOTATION),
        rollout_annotation_items(svc.annotations),
        tuple(i.hostname for i in svc.status.load_balancer.ingress),
    )


def route53_ingress_fingerprint(ingress) -> tuple:
    """The Ingress twin (no LB-service predicate: the route53
    controller watches ALL annotated ingresses) — pure, no ``apis.*``
    (L107)."""
    return (
        "route53", "Ingress",
        ingress.annotations.get(ROUTE53_HOSTNAME_ANNOTATION),
        ingress.annotations.get(ROUTE53_SET_IDENTIFIER_ANNOTATION),
        ingress.annotations.get(ROUTE53_WEIGHT_ANNOTATION),
        rollout_annotation_items(ingress.annotations),
        tuple(i.hostname for i in ingress.status.load_balancer.ingress),
    )


def record_ramp_active(obj) -> bool:
    """Is a record-weight ramp in flight for this object?  Core kinds
    have no free-form status, so the rollout state rides the
    controller-owned ``rollout.agac/state`` annotation — pure (L107)."""
    return RolloutState.from_json(
        obj.annotations.get(ROLLOUT_STATE_ANNOTATION)).active()


@dataclass
class Route53Config:
    workers: int = 1
    cluster_name: str = "default"
    queue_qps: float = 10.0    # client-go default bucket
    queue_burst: int = 100
    # overload scheduler knobs (kube/workqueue.py priority tiers)
    aging_horizon: float = DEFAULT_AGING_HORIZON
    depth_watermark: int = DEFAULT_DEPTH_WATERMARK
    age_watermark: float = DEFAULT_AGE_WATERMARK
    # steady-state fast path (reconcile/fingerprint.py)
    fingerprints: FingerprintConfig = field(
        default_factory=FingerprintConfig)


class Route53Controller:
    def __init__(self, kube_client: KubeClient,
                 informer_factory: SharedInformerFactory,
                 cloud_factory: CloudFactory,
                 config: Route53Config):
        self.cluster_name = config.cluster_name
        self.workers = config.workers
        self.kube_client = kube_client
        self.cloud_factory = cloud_factory
        self.recorder = kube_client.event_recorder(CONTROLLER_AGENT_NAME)

        self.service_queue = new_rate_limiting_queue(
            name=f"{CONTROLLER_AGENT_NAME}-service",
            qps=config.queue_qps, burst=config.queue_burst,
            aging_horizon=config.aging_horizon,
            depth_watermark=config.depth_watermark,
            age_watermark=config.age_watermark)
        self.ingress_queue = new_rate_limiting_queue(
            name=f"{CONTROLLER_AGENT_NAME}-ingress",
            qps=config.queue_qps, burst=config.queue_burst,
            aging_horizon=config.aging_horizon,
            depth_watermark=config.depth_watermark,
            age_watermark=config.age_watermark)

        # the safe-rollout gate for WEIGHTED record pairs (rollout/):
        # a weighted object declaring rollout.agac/* annotations ramps
        # its record weight through the declared steps; state persists
        # in the controller-owned rollout.agac/state annotation
        self.rollout = RolloutEngine(
            CONTROLLER_AGENT_NAME, shards=cloud_factory.shards,
            region_health=breaker_region_health(cloud_factory))

        # steady-state fast path: one fingerprint gate per queue; a
        # mid-ramp object vetoes the skip (its convergence is driven
        # by timed re-deliveries the gate must not answer)
        # multi-region digest gate (topology/digest.py): see the GA
        # controller's twin comment
        sweep_gate = getattr(cloud_factory, "digest_gate", None)
        if sweep_gate is not None:
            sweep_gate.note_sweep_period(config.fingerprints.sweep_every)
        self.service_fingerprints = FingerprintCache(
            f"{CONTROLLER_AGENT_NAME}-service",
            route53_service_fingerprint, config.fingerprints,
            skip_veto=record_ramp_active,
            sweep_gate=sweep_gate.allow_skip if sweep_gate else None)
        self.ingress_fingerprints = FingerprintCache(
            f"{CONTROLLER_AGENT_NAME}-ingress",
            route53_ingress_fingerprint, config.fingerprints,
            skip_veto=record_ramp_active,
            sweep_gate=sweep_gate.allow_skip if sweep_gate else None)

        self.service_informer = informer_factory.services()
        self.service_informer.add_event_handler(
            add=self._add_service, update=self._update_service,
            delete=self._delete_service, resync=self._resync_service)
        self.service_informer.add_index(ROUTE53_HOSTNAME_INDEX,
                                        index_by_route53_hostname)
        self.ingress_informer = informer_factory.ingresses()
        self.ingress_informer.add_event_handler(
            add=self._add_ingress, update=self._update_ingress,
            delete=self._delete_ingress, resync=self._resync_ingress)
        self.ingress_informer.add_index(ROUTE53_HOSTNAME_INDEX,
                                        index_by_route53_hostname)

        # shard ownership (sharding/): records are 1:1 with (object,
        # hostname), so the routing key is the object key — all of one
        # object's record intents ride its shard's coalescer cohort
        self.shards = cloud_factory.shards
        # event gates with deferred replay (base.ShardGate): a
        # hostname-annotation removal or delete swallowed by an
        # ownership gap is replayed on acquire
        self.service_gate = ShardGate(
            self.shards, self.service_queue, self.service_fingerprints,
            lambda o: o.key())
        self.ingress_gate = ShardGate(
            self.shards, self.ingress_queue, self.ingress_fingerprints,
            lambda o: o.key())
        wire_shard_listener(
            self.shards, self.service_informer, self.service_queue,
            self.service_fingerprints, lambda o: o.key(),
            lambda o: (was_load_balancer_service(o)
                       and self._has_hostname(o)),
            gate=self.service_gate,
            # resume-on-acquire: a mid-ramp weighted record replays
            # interactive so the successor resumes the persisted step
            # ahead of the background re-verify
            interactive_pred=record_ramp_active)
        wire_shard_listener(
            self.shards, self.ingress_informer, self.ingress_queue,
            self.ingress_fingerprints, lambda o: o.key(),
            self._has_hostname, gate=self.ingress_gate,
            interactive_pred=record_ramp_active)

    # -- event handlers (route53/controller.go:90-172) ------------------

    @staticmethod
    def _has_hostname(obj) -> bool:
        return ROUTE53_HOSTNAME_ANNOTATION in obj.annotations

    def _add_service(self, svc: Service) -> None:
        if was_load_balancer_service(svc) and self._has_hostname(svc):
            event_enqueue(self.service_gate, self.service_fingerprints,
                          self.service_queue, svc)

    def _update_service(self, old: Service, new: Service) -> None:
        if old == new:
            return
        if was_load_balancer_service(new):
            if self._has_hostname(new) or annotation_presence_changed(
                    old, new, ROUTE53_HOSTNAME_ANNOTATION):
                event_enqueue(self.service_gate,
                              self.service_fingerprints,
                              self.service_queue, new)

    def _delete_service(self, svc: Service) -> None:
        if was_load_balancer_service(svc):
            event_enqueue(self.service_gate, self.service_fingerprints,
                          self.service_queue, svc)

    def _resync_service(self, svc: Service, wave: int) -> None:
        """Tagged resync backstop for annotated Services — gated at
        enqueue time (base.resync_enqueue)."""
        if was_load_balancer_service(svc) and self._has_hostname(svc):
            if not self.shards.owns_key(svc.key()):
                return
            resync_enqueue(self.service_fingerprints,
                           self.service_queue, svc, wave)

    def _add_ingress(self, ingress: Ingress) -> None:
        # the route53 controller watches ALL ingresses with the annotation
        # (route53/controller.go:133-137; no ALB filter on add)
        if self._has_hostname(ingress):
            event_enqueue(self.ingress_gate, self.ingress_fingerprints,
                          self.ingress_queue, ingress)

    def _update_ingress(self, old: Ingress, new: Ingress) -> None:
        if old == new:
            return
        if self._has_hostname(new) or annotation_presence_changed(
                old, new, ROUTE53_HOSTNAME_ANNOTATION):
            event_enqueue(self.ingress_gate, self.ingress_fingerprints,
                          self.ingress_queue, new)

    def _delete_ingress(self, ingress: Ingress) -> None:
        event_enqueue(self.ingress_gate, self.ingress_fingerprints,
                      self.ingress_queue, ingress)

    def _resync_ingress(self, ingress: Ingress, wave: int) -> None:
        if self._has_hostname(ingress):
            if not self.shards.owns_key(ingress.key()):
                return
            resync_enqueue(self.ingress_fingerprints,
                           self.ingress_queue, ingress, wave)

    # -- run ------------------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        logger.info("starting Route53 controller")
        if not wait_for_cache_sync(stop, self.service_informer,
                                   self.ingress_informer):
            # only reachable when stop fired first — clean abort, not
            # a thread crash (r4 VERDICT next #7)
            logger.info("stopping Route53 controller before caches "
                        "synced (shutdown during apiserver wait)")
            return

        def workers():
            return (spawn_workers(
                        f"{CONTROLLER_AGENT_NAME}-service", self.workers,
                        stop, self.service_queue, self._key_to_service,
                        self._rollout_health_tracked(
                            self.process_service_delete),
                        self._rollout_health_tracked(
                            self.process_service_create_or_update),
                        fingerprints=self.service_fingerprints,
                        shards=self.shards)
                    + spawn_workers(
                        f"{CONTROLLER_AGENT_NAME}-ingress", self.workers,
                        stop, self.ingress_queue, self._key_to_ingress,
                        self._rollout_health_tracked(
                            self.process_ingress_delete),
                        self._rollout_health_tracked(
                            self.process_ingress_create_or_update),
                        fingerprints=self.ingress_fingerprints,
                        shards=self.shards))

        run_controller(CONTROLLER_AGENT_NAME, stop,
                       [self.service_queue, self.ingress_queue], workers)

    def _rollout_health_tracked(self, fn):
        """EndpointGroupBinding-worker-loop parity for the rollout
        health gate: any sync exception marks the key's ramp degraded
        for one bake interval (``note_error`` — a record ramp must not
        advance through a failing sync loop), and a sync that runs to
        completion (mid-ramp requeues included) clears the window
        (``note_ok``).  ``fn`` is a process func taking either the key
        string (delete) or the object (create/update)."""
        def wrapped(arg):
            key = arg if isinstance(arg, str) else arg.key()
            try:
                res = fn(arg)
            except Exception:
                self.rollout.note_error(key)
                raise
            self.rollout.note_ok(key)
            return res
        return wrapped

    def _key_to_service(self, key: str):
        ns, name = split_meta_namespace_key(key)
        return self.service_informer.lister.get(ns, name)

    def _key_to_ingress(self, key: str):
        ns, name = split_meta_namespace_key(key)
        return self.ingress_informer.lister.get(ns, name)

    # -- process funcs (route53/service.go, route53/ingress.go) ---------

    def process_service_delete(self, key: str) -> Result:
        logger.info("%s has been deleted", key)
        try:
            ns, name = split_meta_namespace_key(key)
        except ValueError as e:
            raise new_no_retry_errorf("invalid resource key: %s", key) from e
        self.cloud_factory.global_provider().cleanup_record_set(
            self.cluster_name, "service", ns, name)
        return Result()

    def process_service_create_or_update(self, obj) -> Result:
        if not isinstance(obj, Service):
            raise new_no_retry_errorf("object is not Service, it is %s",
                                      type(obj).__name__)
        svc = obj
        hostname = svc.annotations.get(ROUTE53_HOSTNAME_ANNOTATION)
        if hostname is None:
            self.cloud_factory.global_provider().cleanup_record_set(
                self.cluster_name, "service", svc.metadata.namespace,
                svc.metadata.name)
            logger.info("deleted route53 records for Service %s", svc.key())
            self.recorder.event(svc, "Normal", "Route53RecordDeleted",
                                "Route53 record sets are deleted")
            return Result()

        hostnames = hostname.split(",")
        self._warn_contested_hostnames(svc, hostnames)
        policy, ramp_weights, ramp_requeue = self._record_rollout(
            svc, "service", hostnames, self.kube_client.services)
        for lb_ingress in svc.status.load_balancer.ingress:
            result = self._ensure_for_lb_ingress(
                svc, lb_ingress, hostnames,
                lambda provider: provider.ensure_route53_for_service(
                    svc, lb_ingress, hostnames, self.cluster_name,
                    policy=policy, weights=ramp_weights))
            if result is not None:
                return result
        if ramp_requeue > 0:
            return Result(requeue_after=ramp_requeue)
        return Result()

    def process_ingress_delete(self, key: str) -> Result:
        logger.info("%s has been deleted", key)
        try:
            ns, name = split_meta_namespace_key(key)
        except ValueError as e:
            raise new_no_retry_errorf("invalid resource key: %s", key) from e
        self.cloud_factory.global_provider().cleanup_record_set(
            self.cluster_name, "ingress", ns, name)
        return Result()

    def process_ingress_create_or_update(self, obj) -> Result:
        if not isinstance(obj, Ingress):
            raise new_no_retry_errorf("object is not Ingress, it is %s",
                                      type(obj).__name__)
        ingress = obj
        hostname = ingress.annotations.get(ROUTE53_HOSTNAME_ANNOTATION)
        if hostname is None:
            self.cloud_factory.global_provider().cleanup_record_set(
                self.cluster_name, "ingress", ingress.metadata.namespace,
                ingress.metadata.name)
            logger.info("deleted route53 records for Ingress %s",
                        ingress.key())
            self.recorder.event(ingress, "Normal", "Route53RecordDeleted",
                                "Route53 record sets are deleted")
            return Result()

        hostnames = hostname.split(",")
        self._warn_contested_hostnames(ingress, hostnames)
        policy, ramp_weights, ramp_requeue = self._record_rollout(
            ingress, "ingress", hostnames, self.kube_client.ingresses)
        for lb_ingress in ingress.status.load_balancer.ingress:
            result = self._ensure_for_lb_ingress(
                ingress, lb_ingress, hostnames,
                lambda provider: provider.ensure_route53_for_ingress(
                    ingress, lb_ingress, hostnames, self.cluster_name,
                    policy=policy, weights=ramp_weights))
            if result is not None:
                return result
        if ramp_requeue > 0:
            return Result(requeue_after=ramp_requeue)
        return Result()

    def _record_rollout(self, obj, resource: str, hostnames,
                        client) -> "tuple":
        """The weighted-record ramp turn for one object: returns
        (RecordPolicy, per-hostname weights override or None, requeue
        seconds).  Simple (non-weighted) objects skip the engine
        entirely — reference parity.  A weighted object with rollout
        annotations ramps its record weight through the declared steps
        with state persisted in the ``rollout.agac/state`` annotation
        (written BEFORE the record weights it implies — the same
        crash-resume ordering as the EndpointGroupBinding status
        plane)."""
        policy = RecordPolicy.from_annotations(obj.annotations)
        if not policy.weighted:
            return policy, None, 0.0
        if (parse_spec(obj.annotations) is None
                and not record_ramp_active(obj)):
            # weighted but NOT ramping (no declared ramp, no active
            # persisted state): pure reference snap — skip the
            # per-hostname record read-back and the engine turn
            # entirely; the ensure path's own need_records_update
            # read-back covers drift for this shape
            return policy, None, 0.0
        provider = self.cloud_factory.global_provider()
        desired = {h: policy.weight for h in hostnames}
        observed = provider.get_record_weights(
            hostnames, self.cluster_name, resource,
            obj.metadata.namespace, obj.metadata.name,
            policy.set_identifier)
        outcome = self.rollout.decide(
            key=obj.key(), route=obj.key(),
            annotations=obj.annotations,
            state_dict=RolloutState.from_json(
                obj.annotations.get(ROLLOUT_STATE_ANNOTATION)).to_dict()
            if obj.annotations.get(ROLLOUT_STATE_ANNOTATION) else None,
            desired=desired, observed=observed,
            generation=obj.metadata.generation)
        if outcome.state is not None:
            self._persist_ramp_state(obj, client, outcome.state)
        # hold is the weight vector in force NOW: the ensure path
        # upserts records at these values (a drifted record is
        # repaired back to the STEP weight mid-ramp, the target only
        # once the ramp completes)
        return policy, outcome.hold, outcome.requeue_after

    def _persist_ramp_state(self, obj, client, state) -> None:
        """Write the ramp state annotation, retrying resourceVersion
        conflicts against the fresh object (the metadata-plane twin of
        the EndpointGroupBinding controller's ``_update_status``).
        Mirrors onto the caller's ``obj`` so later reads in this sync
        see the persisted step."""
        raw = state.to_json()
        obj.metadata.annotations[ROLLOUT_STATE_ANNOTATION] = raw
        copied = obj.deep_copy()
        last = None
        for _ in range(5):
            copied.metadata.annotations[ROLLOUT_STATE_ANNOTATION] = raw
            try:
                client.update(copied)
                return
            except ConflictError as e:
                last = e
                copied = client.get(obj.metadata.namespace,
                                    obj.metadata.name).deep_copy()
        raise last

    def _warn_contested_hostnames(self, obj, hostnames) -> None:
        """Indexed duplicate-claim check: two objects annotating the
        SAME route53 hostname would fight over one record set (last
        writer wins, ownership TXT flapping).  The hostname index
        answers 'who else claims this name' in O(1) across both
        watched kinds instead of a lister scan per sync.

        Weighted pairs are the EXCEPTION: two objects claiming one
        hostname with DISTINCT set identifiers are a legitimate
        blue-green pair — each owns its own (name, SetIdentifier)
        record — so only claimants whose identifier COLLIDES (both
        simple, or both the same identifier) are contested."""
        own_policy = RecordPolicy.from_annotations(obj.annotations)
        for hostname in hostnames:
            others = []
            for informer in (self.service_informer,
                             self.ingress_informer):
                for o in informer.by_index(ROUTE53_HOSTNAME_INDEX,
                                           hostname):
                    if o.key() == obj.key() and o.kind == obj.kind:
                        continue
                    other_policy = RecordPolicy.from_annotations(
                        o.annotations)
                    if (other_policy.set_identifier
                            != own_policy.set_identifier):
                        continue   # distinct sides of a weighted pair
                    others.append(o.key())
            if others:
                logger.error(
                    "%s %s contests route53 hostname %s with %s — the "
                    "record set will flap between owners",
                    type(obj).__name__, obj.key(), hostname, others)

    def _ensure_for_lb_ingress(self, obj, lb_ingress, hostnames, ensure):
        try:
            provider_name = cloudprovider.detect_cloud_provider(
                lb_ingress.hostname)
        except ValueError as e:
            logger.error("%s", e)
            return None
        if provider_name != cloudprovider.PROVIDER_AWS:
            logger.warning("not implemented for %s", provider_name)
            return None
        _, region = get_lb_name_from_hostname(lb_ingress.hostname)
        provider = self.cloud_factory.provider_for(region)
        created, retry_after = ensure(provider)
        if retry_after > 0:
            return Result(requeue=True, requeue_after=retry_after)
        if created:
            self.recorder.eventf(
                obj, "Normal", "Route53RecordCreated",
                "Route53 record set is created: %s", hostnames)
        return None
